"""API-coverage diff: reference `python/paddle` public surface vs
paddle_tpu's importable surface.

The reference package can't be imported (compiled C extensions), so its
surface is scraped with `ast`: every module's `__all__` plus public
top-level def/class names.  paddle_tpu IS importable, so presence is
checked with getattr walks.  Output: per-namespace missing-name lists,
worst first.  Heuristic by design — used to aim work, not as a gate.

Usage: python tools/api_coverage.py [--limit N] [--namespace paddle.nn]
           [--json FILE|-] [--baseline FILE] [--write-baseline FILE]

`--json` emits the machine-readable report alongside the text one so CI
can diff coverage; `--baseline` compares against a previously-written
JSON report and exits nonzero when any namespace regressed (more
missing names than before).
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import sys

REF = "/root/reference/python/paddle"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# reference namespaces that are GPU/legacy plumbing with no TPU analogue
SKIP = {
    "fluid", "libs", "proto", "cost_model", "distributed.fleet.proto",
    "utils.cpp_extension", "utils.gast", "incubate.xpu", "device.cuda",
    "base", "_typing", "tests",
}


def ref_public_names(py_path):
    try:
        tree = ast.parse(open(py_path, encoding="utf-8").read())
    except SyntaxError:
        return set()
    names = set()
    explicit_all = None
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    try:
                        explicit_all = {e for e in ast.literal_eval(node.value)
                                        if isinstance(e, str)}
                    except Exception:
                        pass
        elif isinstance(node, (ast.FunctionDef, ast.ClassDef)):
            if not node.name.startswith("_"):
                names.add(node.name)
    return explicit_all if explicit_all is not None else names


def walk_reference():
    """namespace ('' for top level) -> public names."""
    out = {}
    for root, dirs, files in os.walk(REF):
        rel = os.path.relpath(root, REF)
        ns = "" if rel == "." else rel.replace(os.sep, ".")
        if any(ns == s or ns.startswith(s + ".") for s in SKIP):
            dirs[:] = []
            continue
        for f in files:
            if not f.endswith(".py"):
                continue
            mod_ns = ns if f == "__init__.py" else \
                (f[:-3] if not ns else ns)  # non-init defs roll up to pkg
            out.setdefault(mod_ns, set()).update(
                ref_public_names(os.path.join(root, f)))
    return out


def has_attr_path(obj, name):
    return getattr(obj, name, None) is not None


# paddle_tpu-NATIVE namespaces with no reference-paddle analogue: their
# declared public surface (__all__) is the contract; a name that stops
# resolving is a regression exactly like a reference-parity gap.
NATIVE_NAMESPACES = ("serving", "serving.router", "serving.fleet",
                     "serving.traffic",
                     "analysis", "observability",
                     "observability.fleettrace", "quantization",
                     "resilience")


def collect_native():
    """[(namespace, missing_count, missing_names, note)] for the
    paddle_tpu-native subsystems (checked against their own __all__)."""
    import importlib
    rows = []
    for ns in NATIVE_NAMESPACES:
        try:
            mod = importlib.import_module(f"paddle_tpu.{ns}")
        except Exception as e:  # noqa: BLE001 — report, don't crash the tool
            # count high enough that a whole-namespace import break
            # always regresses vs any baseline with partial gaps
            rows.append((f"<native>.{ns}", 999, [],
                         f"IMPORT FAILED: {type(e).__name__}"))
            continue
        declared = sorted(getattr(mod, "__all__", []))
        missing = sorted(n for n in declared
                         if getattr(mod, n, None) is None)
        # always emit the row (missing_count 0 when healthy): the
        # baseline then RECORDS the namespace, so a later import break
        # or dropped name regresses against an explicit 0
        rows.append((f"<native>.{ns}", len(missing), missing,
                     "" if missing else f"OK ({len(declared)} names)"))
    return rows


def collect():
    """[(namespace, missing_count, missing_names, note)] sorted worst-first."""
    import paddle_tpu

    ref = walk_reference()
    rows = []
    for ns, names in sorted(ref.items()):
        target = paddle_tpu
        ok = True
        for part in (ns.split(".") if ns else []):
            target = getattr(target, part, None)
            if target is None:
                ok = False
                break
        if not ok:
            rows.append((ns or "<top>", len(names), sorted(names),
                         "NAMESPACE MISSING"))
            continue
        missing = sorted(n for n in names if not has_attr_path(target, n))
        if missing:
            rows.append((ns or "<top>", len(missing), missing, ""))
    rows.extend(collect_native())
    rows.sort(key=lambda r: (-r[1], r[0]))
    return rows


def to_json_doc(rows):
    return {
        "version": 1,
        "total_missing": sum(r[1] for r in rows),
        "namespaces": {
            ns: {"missing_count": n, "missing": names, "note": note}
            for ns, n, names, note in rows
        },
    }


def diff_regressions(doc, baseline):
    """Namespaces whose missing_count grew vs `baseline` (same schema)."""
    base_ns = baseline.get("namespaces", {})
    regs = []
    for ns, info in doc["namespaces"].items():
        before = base_ns.get(ns, {}).get("missing_count", 0)
        if info["missing_count"] > before:
            regs.append((ns, before, info["missing_count"]))
    return sorted(regs, key=lambda r: -(r[2] - r[1]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--limit", type=int, default=25)
    ap.add_argument("--namespace", default=None)
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="write the machine-readable report ('-' = stdout)")
    ap.add_argument("--baseline", metavar="FILE", default=None,
                    help="previous --json report; exit 1 on any namespace "
                         "regression")
    ap.add_argument("--write-baseline", metavar="FILE", default=None,
                    help="write the current report as the new baseline")
    args = ap.parse_args()

    all_rows = collect()
    rows = [r for r in all_rows if r[1] > 0]   # text shows gaps only
    if args.namespace:
        rows = [r for r in all_rows
                if ("paddle." + ("" if r[0] == "<top>" else r[0]))
                .startswith(args.namespace)]
    total_missing = sum(r[1] for r in rows)
    print(f"namespaces with gaps: {len(rows)}; total missing names: "
          f"{total_missing}\n")
    for ns, n, sample, note in rows[:args.limit]:
        print(f"paddle.{ns:40s} {n:4d} missing {note}  e.g. "
              f"{', '.join(sample[:8])}")

    # JSON / baseline / regression always cover the FULL surface —
    # --namespace only narrows the text display, so a baseline written
    # alongside a namespace filter cannot be silently truncated
    doc = to_json_doc(all_rows)
    for path in (args.json, args.write_baseline):
        if not path:
            continue
        if path == "-":
            json.dump(doc, sys.stdout, indent=1, sort_keys=True)
            print()
        else:
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1, sort_keys=True)
                fh.write("\n")

    if args.baseline:
        try:
            with open(args.baseline, encoding="utf-8") as fh:
                baseline = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"api_coverage: cannot read baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        regs = diff_regressions(doc, baseline)
        if regs:
            print("\nCOVERAGE REGRESSIONS (missing-name count grew):")
            for ns, before, now in regs:
                print(f"  paddle.{ns}: {before} -> {now}")
            return 1
        print("\nno coverage regressions vs baseline")
    return 0


if __name__ == "__main__":
    sys.exit(main())
