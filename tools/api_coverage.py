"""API-coverage diff: reference `python/paddle` public surface vs
paddle_tpu's importable surface.

The reference package can't be imported (compiled C extensions), so its
surface is scraped with `ast`: every module's `__all__` plus public
top-level def/class names.  paddle_tpu IS importable, so presence is
checked with getattr walks.  Output: per-namespace missing-name lists,
worst first.  Heuristic by design — used to aim work, not as a gate.

Usage: python tools/api_coverage.py [--limit N] [--namespace paddle.nn]
"""
from __future__ import annotations

import argparse
import ast
import os
import sys

REF = "/root/reference/python/paddle"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# reference namespaces that are GPU/legacy plumbing with no TPU analogue
SKIP = {
    "fluid", "libs", "proto", "cost_model", "distributed.fleet.proto",
    "utils.cpp_extension", "utils.gast", "incubate.xpu", "device.cuda",
    "base", "_typing", "tests",
}


def ref_public_names(py_path):
    try:
        tree = ast.parse(open(py_path, encoding="utf-8").read())
    except SyntaxError:
        return set()
    names = set()
    explicit_all = None
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    try:
                        explicit_all = {e for e in ast.literal_eval(node.value)
                                        if isinstance(e, str)}
                    except Exception:
                        pass
        elif isinstance(node, (ast.FunctionDef, ast.ClassDef)):
            if not node.name.startswith("_"):
                names.add(node.name)
    return explicit_all if explicit_all is not None else names


def walk_reference():
    """namespace ('' for top level) -> public names."""
    out = {}
    for root, dirs, files in os.walk(REF):
        rel = os.path.relpath(root, REF)
        ns = "" if rel == "." else rel.replace(os.sep, ".")
        if any(ns == s or ns.startswith(s + ".") for s in SKIP):
            dirs[:] = []
            continue
        for f in files:
            if not f.endswith(".py"):
                continue
            mod_ns = ns if f == "__init__.py" else \
                (f[:-3] if not ns else ns)  # non-init defs roll up to pkg
            out.setdefault(mod_ns, set()).update(
                ref_public_names(os.path.join(root, f)))
    return out


def has_attr_path(obj, name):
    return getattr(obj, name, None) is not None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--limit", type=int, default=25)
    ap.add_argument("--namespace", default=None)
    args = ap.parse_args()

    import paddle_tpu

    ref = walk_reference()
    rows = []
    for ns, names in sorted(ref.items()):
        if args.namespace and not ("paddle." + ns).startswith(
                args.namespace) and not (ns == "" and
                                         args.namespace == "paddle"):
            continue
        target = paddle_tpu
        ok = True
        for part in (ns.split(".") if ns else []):
            target = getattr(target, part, None)
            if target is None:
                ok = False
                break
        if not ok:
            rows.append((ns or "<top>", len(names), sorted(names)[:12],
                         "NAMESPACE MISSING"))
            continue
        missing = sorted(n for n in names if not has_attr_path(target, n))
        if missing:
            rows.append((ns or "<top>", len(missing), missing[:12], ""))
    rows.sort(key=lambda r: -r[1])
    total_missing = sum(r[1] for r in rows)
    print(f"namespaces with gaps: {len(rows)}; total missing names: "
          f"{total_missing}\n")
    for ns, n, sample, note in rows[:args.limit]:
        print(f"paddle.{ns:40s} {n:4d} missing {note}  e.g. "
              f"{', '.join(sample[:8])}")


if __name__ == "__main__":
    main()
