#!/bin/bash
# One-shot silicon capture: run the moment a TPU probe succeeds.
# NEVER kill any of these processes (a killed TPU-claim holder wedges
# the tunnel for hours) — every step has its own generous timeout-free
# budget and exits on its own. Total healthy runtime ~15-20 min.
#
#   bash tools/run_on_silicon.sh
#
# Captures, in order of value:
#   1. bench.py           -> headline JSON + BENCH_NOTES.md append
#   2. tests_tpu/         -> 28 compiled-mode kernel tests
#   3. tools/sweep_flash  -> block sweep + measured-VPU roofline
#
# Exit code: 0 only if every step succeeded (steps still all run).
set -u
cd "$(dirname "$0")/.."
STAMP=$(date -u +%Y%m%d_%H%M%S)
LOG=silicon_capture_${STAMP}.log
exec > >(tee "$LOG") 2>&1
rc=0
echo "=== silicon capture ${STAMP} ==="
echo "--- 1. bench.py ---"
python bench.py || rc=1
echo "--- 2. tests_tpu ---"
python -m pytest tests_tpu/ -q --no-header -p no:cacheprovider || rc=1
echo "--- 3. flash sweep ---"
python tools/sweep_flash.py || rc=1
echo "=== capture complete (rc=$rc) ==="
echo "log: $LOG (bench JSON + sweep also appended to BENCH_NOTES.md)"
exit $rc
