#!/bin/bash
# One-shot silicon capture: run the moment a TPU probe succeeds.
# NEVER kill any of these processes (a killed TPU-claim holder wedges
# the tunnel for hours) — every step has its own generous timeout-free
# budget and exits on its own. Total healthy runtime ~15-20 min.
#
#   bash tools/run_on_silicon.sh
#
# Captures, in order of value:
#   1. bench.py           -> headline JSON + BENCH_NOTES.md append
#   2. tests_tpu/         -> 28 compiled-mode kernel tests
#   3. tools/sweep_flash  -> block sweep + measured-VPU roofline
#
# Exit code: 0 only if every step succeeded (steps still all run).
set -u
cd "$(dirname "$0")/.."
STAMP=$(date -u +%Y%m%d_%H%M%S)
LOG=silicon_capture_${STAMP}.log
exec > >(tee "$LOG") 2>&1
rc=0
echo "=== silicon capture ${STAMP} ==="
echo "--- 1. bench.py ---"
python bench.py || rc=1
echo "--- 2. tests_tpu ---"
python -m pytest tests_tpu/ -q --no-header -p no:cacheprovider || rc=1
echo "--- 3. gpt 355M fused-head batch sweep (r4's lost datapoint) ---"
python tools/profile_gpt.py --batch 16 --fused-head --iters 6 || rc=1
echo "--- 4. gpt-3 1.3B single-chip fit (VERDICT r4 #2) ---"
# CPU-smoked shape (tiny) before any silicon compile — wedge rule.
# batch 4 first (smaller program), then 8; separate processes so an
# OOM in one cannot take the other's datapoint.
python tools/profile_gpt.py --preset 1p3b --batch 4 --iters 5 || rc=1
python tools/profile_gpt.py --preset 1p3b --batch 8 --iters 5 || rc=1
echo "--- 5. bert occupancy profile (unfused vs incubate-fused A/B) ---"
python tools/profile_bert.py --batch 48 || rc=1
python tools/profile_bert.py --batch 48 --fused || rc=1
echo "--- 5b. vit-b16 lane (BASELINE configs[1] second half) ---"
python tools/profile_vit.py --batch 128 --iters 8 || rc=1
echo "--- 6. flash sweep ---"
python tools/sweep_flash.py || rc=1
echo "=== capture complete (rc=$rc) ==="
echo "log: $LOG (bench JSON + sweep also appended to BENCH_NOTES.md)"
exit $rc
