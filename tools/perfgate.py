#!/usr/bin/env python
"""perfgate — machine-checked perf budgets from DETERMINISTIC cost models.

BENCH wall-times depend on the host, the chip, and the claim being up —
a CI gate can't block on them.  What IS stable run-to-run is the cost
model: the roofline profiler's analytic bytes/flops per traced step
(observability.profile), the shardlint liveness/padding estimates
(analysis.cost_audit), and the serving engine's declared lifetime
compile bound.  perfgate traces the flagship programs on CPU (no
compile, no TPU claim), extracts those numbers, and compares them
against the checked-in baseline (tools/perf_baseline.json) — so every
future bytes/step optimization (ROADMAP item 5: bf16 activations,
fused optimizer, Pallas LN) lands against a machine-checked budget
instead of a hand-read bench log, and an accidental +20% bytes/step
regression fails CI the day it lands.

Every metric is lower-is-better.  `--check` fails on any metric above
baseline * (1 + tolerance); improvements beyond tolerance are reported
with a hint to re-baseline (ratcheting the budget down is a reviewed
diff, like every other baseline in tools/).

Usage:
  python tools/perfgate.py                 # report current numbers
  python tools/perfgate.py --check         # vs baseline, CI gate
  python tools/perfgate.py --write-baseline
  python tools/perfgate.py --json -        # machine-readable report
  python tools/perfgate.py --targets gpt_hybrid_train

Exit codes: 0 clean, 1 regressions (--check), 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the gate is trace-only (shape-level): the CPU backend is always the
# right one — a wedged TPU claim must never hang CI
os.environ.setdefault("JAX_PLATFORMS", "cpu")

DEFAULT_BASELINE = os.path.join(REPO, "tools", "perf_baseline.json")
DEFAULT_TOLERANCE = 0.05


# ------------------------------------------------------------- targets
def build_gpt_train_step(optimized=True, remat=None, guard=False):
    """The flagship hybrid-parallel train step — the SHARED builder
    other tools profile the same program from (tools/obs_report.py
    --roofline --demo, tests/test_profile.py), with the loss under an
    explicit profile scope so its softmax/gather traffic is attributed
    rather than bucketed <unattributed>.

    ``optimized=True`` (the shipped flagship since the PR 10 bytes/step
    work) enables the three byte-cutting fronts: bf16 activation
    residency (``to_static(amp_policy="bf16")``), the fused single-pass
    AdamW update (``fused=True``), and the Pallas fused LN/residual
    blocks (``fused_ln=True``).  ``optimized=False`` is the plain-f32
    per-op build (the remat lane's baseline and the XLA-reconciliation
    test use it).  ``remat`` threads to ``to_static(remat=...)``;
    ``guard=True`` arms the training sentinel's in-trace anomaly
    probes on both halves (``to_static(guard=True)`` +
    ``AdamW(guard=True)``) — the ``sentinel`` perfgate target measures
    their cost-model overhead against the unguarded flagship."""
    import numpy as np

    import paddle_tpu as P
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
    from paddle_tpu.observability import profile

    P.seed(0)
    cfg = gpt3_tiny(fused_ln=bool(optimized))
    model = GPTForCausalLM(cfg)
    opt = P.optimizer.AdamW(learning_rate=1e-4,
                            parameters=model.parameters(),
                            fused=bool(optimized), guard=bool(guard))

    @P.jit.to_static(amp_policy="bf16" if optimized else None,
                     remat=remat, guard=bool(guard))
    def train_step(ids, labels):
        opt.clear_grad()
        logits = model(ids)
        with profile.scope("loss"):
            loss = F.cross_entropy(logits.reshape([-1, cfg.vocab_size]),
                                   labels.reshape([-1]))
        loss.backward()
        opt.step()
        return loss

    rng = np.random.default_rng(0)
    ids = P.to_tensor(rng.integers(0, cfg.vocab_size, (2, 32)),
                      dtype="int64")
    labels = P.to_tensor(rng.integers(0, cfg.vocab_size, (2, 32)),
                         dtype="int64")
    return train_step, ids, labels


def gpt_roofline_report(optimized=True, remat=None, guard=False):
    """(RooflineReport, CostReport) for the gpt hybrid train step —
    shared by the gate metrics and the bench.py --worker-profile lane."""
    from paddle_tpu.analysis.cost_audit import audit_memory
    from paddle_tpu.observability import profile

    train_step, ids, labels = build_gpt_train_step(optimized=optimized,
                                                   remat=remat,
                                                   guard=guard)
    jaxpr, infos = train_step.traced_program(ids, labels)
    report = profile.profile_traced(jaxpr, where="<gpt_hybrid_train>")
    _findings, cost = audit_memory(jaxpr, where="<gpt_hybrid_train>",
                                   inputs=infos)
    return report, cost


def remat_report():
    """The bench.py --worker-remat lane: remat-on vs remat-off COST
    MODEL numbers for the gpt train step, reported honestly — remat
    re-runs each block's forward inside backward, so bytes/step go UP
    (that's the flops-for-HBM trade, not a win to hide), and on the
    param-dominated TINY CI config the liveness peak estimate can rise
    too (the anti-CSE barriers around each region count as copies).
    The old bench "remat" key was a bare bool that implied a free win;
    these numbers are what the trade actually costs on the audited
    program.  ``remat="bf16"`` halves the saved boundary activations."""
    t0 = time.time()
    rep_off, cost_off = gpt_roofline_report(optimized=False)
    rep_on, cost_on = gpt_roofline_report(optimized=False, remat="bf16")
    bytes_saved = 100.0 * (1.0 - rep_on.total_bytes
                           / max(1, rep_off.total_bytes))
    peak_saved = 100.0 * (1.0 - cost_on.peak_hbm_bytes
                          / max(1, cost_off.peak_hbm_bytes))
    return {
        "remat_bytes_per_step_off": rep_off.total_bytes,
        "remat_bytes_per_step_on": rep_on.total_bytes,
        "remat_bytes_saved_pct": round(bytes_saved, 2),
        "remat_peak_hbm_off_mb": round(
            cost_off.peak_hbm_bytes / (1 << 20), 3),
        "remat_peak_hbm_on_mb": round(
            cost_on.peak_hbm_bytes / (1 << 20), 3),
        "remat_peak_hbm_saved_pct": round(peak_saved, 2),
        "remat_elapsed_s": round(time.time() - t0, 2),
    }


def target_gpt_hybrid_train():
    report, cost = gpt_roofline_report()
    return {
        "bytes_per_step": report.total_bytes,
        "flops_per_step": report.total_flops,
        "unattributed_bytes_pct": round(
            100.0 * (1.0 - report.frac_attributed_bytes), 2),
        "unattributed_flops_pct": round(
            100.0 * (1.0 - report.frac_attributed_flops), 2),
        "padding_waste_pct": round(100.0 * cost.padding_waste, 2),
        "peak_hbm_mb": round(cost.peak_hbm_bytes / (1 << 20), 3),
    }


def target_serving():
    """The serving engine's whole program set: total/decode traffic from
    the roofline cost model plus the engine's declared lifetime compile
    bound — the number the bounded-compile contract lives or dies by."""
    import paddle_tpu as P
    from paddle_tpu import serving
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.observability import profile

    P.seed(0)
    mcfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=128, dropout=0.0,
                     attention_dropout=0.0)
    engine = serving.LLMEngine(
        GPTForCausalLM(mcfg),
        serving.EngineConfig(max_num_seqs=4, page_size=8, max_model_len=64,
                             prefill_buckets=(16, 32)))
    try:
        reports = profile.profile_engine(engine)
        decode = reports.get("decode")
        return {
            "compile_bound": engine.config.compile_bound,
            "decode_bytes_per_step": decode.total_bytes if decode else 0,
            "programs_total_bytes": sum(r.total_bytes
                                        for r in reports.values()),
        }
    finally:
        engine.shutdown()


def _quant_engines():
    """(engine factory, shared model) for the quantization target and
    the bench --worker-quant lane — the SAME tiny geometry as
    target_serving, so the kv numbers compare apples to apples."""
    import paddle_tpu as P
    from paddle_tpu import serving
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(0)
    mcfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=128, dropout=0.0,
                     attention_dropout=0.0)
    model = GPTForCausalLM(mcfg)

    def build(**kw):
        return serving.LLMEngine(
            model, serving.EngineConfig(
                max_num_seqs=4, page_size=8, max_model_len=64,
                prefill_buckets=(16, 32), **kw))

    return build


def target_quantization():
    """Both quantized memory planes, deterministically accounted.

    Plane 1 — int8 KV pages: pool-storage bytes per token of capacity
    and the ratios vs the bf16/f32 pools at identical geometry (the
    acceptance bar is <= 0.55x vs bf16), plus the cost-model peak HBM
    of the int8 decode program — proof the narrow storage reaches the
    SL301 liveness estimate, not just the allocator.  Plane 2 — the
    EQuARX all-reduce wire model for a reference 1M-element gradient at
    axis size 8 (analytic, device-count-independent; the traced
    cross-check lives in tests/test_quantized_kv.py).  Every metric is
    lower-is-better."""
    import jax.numpy as jnp

    from paddle_tpu.analysis.cost_audit import audit_memory
    from paddle_tpu.quantization.collectives import \
        quantized_all_reduce_wire_bytes

    build = _quant_engines()
    out = {}
    engines = {}
    try:
        engines["f32"] = build()
        engines["bf16"] = build(dtype=jnp.bfloat16)
        engines["int8"] = build(kv_cache_dtype="int8")
        bpt = {k: e.kv_bytes_per_token for k, e in engines.items()}
        out["kv_bytes_per_token"] = round(bpt["int8"], 3)
        out["kv_quant_vs_bf16_ratio"] = round(bpt["int8"] / bpt["bf16"], 4)
        out["kv_quant_vs_f32_ratio"] = round(bpt["int8"] / bpt["f32"], 4)
        progs = engines["int8"].audit_programs()
        _f, cost = audit_memory(progs["decode"],
                                where="<quant decode>")
        out["quant_decode_peak_hbm_mb"] = round(
            cost.peak_hbm_bytes / (1 << 20), 3)
        _f, cost_f32 = audit_memory(
            engines["f32"].audit_programs()["decode"],
            where="<f32 decode>")
        out["quant_vs_f32_decode_peak_ratio"] = round(
            cost.peak_hbm_bytes / max(1, cost_f32.peak_hbm_bytes), 4)
    finally:
        for e in engines.values():
            e.shutdown()
    wire = quantized_all_reduce_wire_bytes(1 << 20, axis_size=8)
    out["allreduce_bytes"] = wire["allreduce_bytes"]
    out["allreduce_quant_vs_wide_ratio"] = \
        wire["allreduce_quant_vs_wide_ratio"]
    return out


def target_sentinel():
    """The training sentinel's detection-cost contract, measured on the
    SAME optimized flagship the gpt_hybrid_train target gates: trace
    the guarded build (``to_static(guard=True)`` +
    ``AdamW(guard=True)``) and compare its cost-model bytes/step
    against the unguarded one.  The headline metric is
    ``guard_bytes_overhead_pct`` — the <2% acceptance bar of the
    in-trace-probes design (the fused Adam kernel reduces grad
    sum-of-squares while g is already in registers, so the probe's
    bytes are the tiny partials/summary plumbing plus the rank-1
    unfused reductions).  The zero-extra-compiles half of the contract
    is a recompile-log proof, pinned in tests/test_sentinel.py."""
    import gc

    rep_off, _cost_off = gpt_roofline_report()
    # the unguarded build's model holds reference cycles; un-collected,
    # its state tensors are still registry-live and ride into the
    # guarded trace as extra lifted inputs, inflating the liveness
    # peak estimate by a whole phantom model
    gc.collect()
    rep_on, cost_on = gpt_roofline_report(guard=True)
    overhead = 100.0 * (rep_on.total_bytes
                        / max(1, rep_off.total_bytes) - 1.0)
    return {
        "guard_bytes_per_step": rep_on.total_bytes,
        "guard_bytes_overhead_pct": round(max(0.0, overhead), 3),
        "guard_flops_overhead_pct": round(max(0.0, 100.0 * (
            rep_on.total_flops / max(1, rep_off.total_flops) - 1.0)), 3),
        "guard_peak_hbm_mb": round(cost_on.peak_hbm_bytes / (1 << 20),
                                   3),
    }


def target_traffic():
    """The traffic harness's SLO contract on the VIRTUAL clock: a burst
    trace against a router with one active replica and one parked
    spare, the SLO autoscaler in the loop.  Every number is a property
    of the deterministic schedule (seeded trace + virtual time), not of
    the host, so the gate pins behavior, not wall time.  All metrics
    are lower-is-better: ``goodput_shortfall_pct`` is 100x(1 -
    goodput-under-SLO fraction), ``scaleup_reaction_ticks`` is the
    burst-onset -> spare-admitting reaction time in driver ticks (the
    warm-AOT-respawn payoff the autoscaler rides), and
    ``slo_violations`` / ``ttft_p99_ms`` pin the tail."""
    import shutil
    import tempfile

    import paddle_tpu as P
    from paddle_tpu import serving
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import traffic
    from paddle_tpu.serving.router import Router, RouterConfig

    P.seed(0)
    mcfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=128, dropout=0.0,
                     attention_dropout=0.0)
    ecfg = serving.EngineConfig(max_num_seqs=4, page_size=8,
                                max_model_len=64, prefill_buckets=(16, 32),
                                crash_safe_decode=False)
    model = GPTForCausalLM(mcfg)
    spec = traffic.TrafficSpec(
        name="perfgate", seed=11,
        arrival={"kind": "onoff", "base_qps": 2.0, "burst_qps": 40.0,
                 "period_s": 2.0, "duty": 0.35},
        duration_s=2.0, prompt_len=((1.0, 4, 16),),
        output_tokens=((1.0, 4, 8),),
        classes=(traffic.DeadlineClass("interactive", ttft_slo_s=0.5),))
    quantum = 0.01
    cache = tempfile.mkdtemp(prefix="ptpu_perfgate_traffic_")
    clock = traffic.VirtualClock()
    try:
        router = Router(model, ecfg, num_replicas=2,
                        config=RouterConfig(sleep=lambda s: None),
                        program_cache=cache, clock=clock)
        router.park(1)
        router.step()
        scaler = traffic.SLOAutoscaler(
            router,
            slo=traffic.SLO(ttft_p99_s=0.5, queue_high=3.0,
                            queue_low=0.5),
            config=traffic.AutoscalerConfig(min_replicas=1, up_after=2,
                                            down_after=30, cooldown=5),
            clock=clock, name="perfgate")
        driver = traffic.TrafficDriver(
            router, spec, clock, quantum_s=quantum, name="perfgate",
            on_tick=lambda d: scaler.observe())
        rep = driver.run()
        snap = scaler.snapshot()
        reaction_ticks = (max(int(round(t / quantum))
                              for t in snap["reaction_times_s"])
                          if snap["reaction_times_s"] else 10 ** 6)
        out = {
            "goodput_shortfall_pct": round(
                100.0 * (1.0 - rep["goodput_frac"]), 3),
            "slo_violations": rep["violations"] + rep["expired"],
            "ttft_p99_ms": rep["ttft_p99_ms"],
            "scaleup_reaction_ticks": reaction_ticks,
            "token_loss": rep["token_loss"],
        }
        driver.release()
        scaler.release()
        router.shutdown()
    finally:
        shutil.rmtree(cache, ignore_errors=True)
    return out


TARGETS = {
    "gpt_hybrid_train": target_gpt_hybrid_train,
    "serving": target_serving,
    "quantization": target_quantization,
    "sentinel": target_sentinel,
    "traffic": target_traffic,
}


def run_targets(names=None):
    out = {}
    for name in (names or sorted(TARGETS)):
        if name not in TARGETS:
            raise SystemExit(f"perfgate: unknown target {name!r} "
                             f"(have: {', '.join(sorted(TARGETS))})")
        out[name] = TARGETS[name]()
    return out


def bench_report():
    """The bench.py --worker-profile lane: roofline headline numbers
    merged into every BENCH report next to the measured wall-time
    lanes."""
    t0 = time.time()
    report, cost = gpt_roofline_report()
    return {
        "profile_bytes_per_step": report.total_bytes,
        "profile_flops_per_step": report.total_flops,
        "profile_top_layer": report.top_layer,
        "profile_bound_fraction": round(report.bound_fraction, 4),
        "profile_attributed_bytes_pct": round(
            100.0 * report.frac_attributed_bytes, 2),
        "profile_padding_waste_pct": round(100.0 * cost.padding_waste, 2),
        "profile_elapsed_s": round(time.time() - t0, 2),
    }


# --------------------------------------------------------------- gate
def compare(current, baseline, tolerance):
    """(regressions, improvements, notes) — every metric lower-is-
    better; a metric present in the baseline but missing from the
    current run is gate erosion and counts as a regression."""
    regressions, improvements, notes = [], [], []
    base_targets = baseline.get("targets", {})
    for tname, base_metrics in sorted(base_targets.items()):
        cur_metrics = current.get(tname)
        if cur_metrics is None:
            regressions.append((tname, "<target>", None, None,
                                "target missing from current run"))
            continue
        for m, base in sorted(base_metrics.items()):
            cur = cur_metrics.get(m)
            where = f"{tname}.{m}"
            if cur is None:
                regressions.append((tname, m, base, None,
                                    "metric missing (gate erosion)"))
            elif base == 0:
                if cur > 0:
                    regressions.append((tname, m, base, cur,
                                        "grew from a zero baseline"))
            elif cur > base * (1.0 + tolerance):
                regressions.append(
                    (tname, m, base, cur,
                     f"+{100.0 * (cur / base - 1.0):.1f}% over baseline "
                     f"(tolerance {100.0 * tolerance:.0f}%)"))
            elif cur < base * (1.0 - tolerance):
                improvements.append(
                    (tname, m, base, cur,
                     f"-{100.0 * (1.0 - cur / base):.1f}% under baseline"))
        for m in sorted(set(cur_metrics) - set(base_metrics)):
            notes.append(f"{tname}.{m}: new metric (not gated yet — "
                         f"--write-baseline to start gating it)")
    for tname in sorted(set(current) - set(base_targets)):
        notes.append(f"{tname}: new target (not gated yet)")
    return regressions, improvements, notes


def render_diff(current, baseline):
    """Print the old-vs-new per-metric table (--diff) and return the
    rows as dicts (for --json).  Purely informational: the % delta
    column is signed (negative = improvement, every metric is
    lower-is-better); metrics present on only one side are labeled.
    The table renderer itself was promoted to analysis/common.py so
    tracelint/shardlint/racelint/numlint share the format for their
    own ``--diff`` modes."""
    from paddle_tpu.analysis.common import render_diff_table
    rows = []
    base_targets = baseline.get("targets", {})
    for tname in sorted(set(base_targets) | set(current)):
        sub = render_diff_table(base_targets.get(tname, {}),
                                current.get(tname, {}), title=tname,
                                label="metric")
        for r in sub:
            rows.append({"target": tname, "metric": r["metric"],
                         "baseline": r["baseline"],
                         "current": r["current"], "delta": r["delta"]})
    return rows


# ----------------------------------------------------------------- CLI
def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="perfgate", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--targets", nargs="*", default=None,
                    help=f"targets to run (default: all — "
                         f"{', '.join(sorted(TARGETS))})")
    ap.add_argument("--check", action="store_true",
                    help="compare against the baseline; exit 1 on any "
                         "regression beyond tolerance")
    ap.add_argument("--diff", action="store_true",
                    help="render an old-vs-new per-metric table with % "
                         "deltas against the baseline (informational: "
                         "metric values never affect the exit code; an "
                         "unreadable baseline is still usage-error 2)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current numbers as the new baseline")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline path (default tools/perf_baseline.json)")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="relative slack before a metric regresses "
                         f"(default: baseline's, else {DEFAULT_TOLERANCE})")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="also write the report as JSON ('-' = stdout)")
    args = ap.parse_args(argv)

    t0 = time.time()
    current = run_targets(args.targets)
    elapsed = time.time() - t0

    if not args.diff:
        for tname, metrics in sorted(current.items()):
            print(f"== {tname}")
            for m, v in sorted(metrics.items()):
                print(f"   {m:28s} {v}")

    doc = {"tool": "perfgate", "version": 1, "elapsed_s": round(elapsed, 2),
           "targets": current}

    rc = 0
    if args.diff:
        try:
            with open(args.baseline, encoding="utf-8") as fh:
                baseline = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"perfgate: cannot read baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        doc["diff"] = render_diff(current, baseline)
    if args.write_baseline:
        base_doc = {"tool": "perfgate", "version": 1,
                    "tolerance": (args.tolerance
                                  if args.tolerance is not None
                                  else DEFAULT_TOLERANCE),
                    "targets": current}
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump(base_doc, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"perfgate: baseline written to {args.baseline}")
    elif args.check:
        try:
            with open(args.baseline, encoding="utf-8") as fh:
                baseline = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"perfgate: cannot read baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        tol = (args.tolerance if args.tolerance is not None
               else baseline.get("tolerance", DEFAULT_TOLERANCE))
        regressions, improvements, notes = compare(current, baseline, tol)
        doc["regressions"] = [
            {"target": t, "metric": m, "baseline": b, "current": c,
             "why": why} for t, m, b, c, why in regressions]
        for t, m, b, c, why in regressions:
            print(f"REGRESSION {t}.{m}: {b} -> {c} ({why})")
        for t, m, b, c, why in improvements:
            print(f"improved   {t}.{m}: {b} -> {c} ({why}) — consider "
                  f"--write-baseline to ratchet the budget")
        for n in notes:
            print(f"note       {n}")
        if regressions:
            print(f"perfgate: FAILED ({len(regressions)} regression(s) "
                  f"vs {os.path.relpath(args.baseline, REPO)})")
            rc = 1
        else:
            print(f"perfgate: clean vs "
                  f"{os.path.relpath(args.baseline, REPO)} "
                  f"(tolerance {100.0 * tol:.0f}%)")

    if args.json:
        payload = json.dumps(doc, indent=1, sort_keys=True)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
