#!/usr/bin/env python
"""kernlint CLI — KLxxx static audit of Pallas kernel INTERIORS.

Every sibling analyzer stops at the ``pallas_call`` boundary (numlint's
dtype_flow documents the body as deliberately opaque; the roofline
profiler costs call-boundary bytes only).  kernlint walks through it:
the kernel jaxpr, the grid, and every in/out BlockSpec are all in
``eqn.params``, so tile alignment, the VMEM bill, in-kernel
accumulation dtypes, alias hazards, grid coverage and ragged tails are
all decidable at trace time — before XLA or Mosaic ever see the kernel
(see paddle_tpu/analysis/kernel_rules.py and docs/kernlint.md):

- KL101 block shape not a multiple of the dtype's native TPU tile
  ((8,128) f32 / (16,128) bf16 / (32,128) int8);
- KL102 static per-call VMEM footprint (block buffers, double-buffering
  and scratch — analysis/vmem_model.py) over the ChipSpec budget;
- KL103 narrow (bf16/f16) accumulation inside the kernel body — a dot
  without preferred_element_type=f32, a narrow reduction, a narrow
  `+=` ref carry;
- KL104 input_output_aliases hazards — shape/dtype mismatch across the
  alias, aliased input read after the aliased output stored;
- KL105 grid x block under-covers an operand, or overlapping index
  maps double-write an output block on non-consecutive steps;
- KL106 a partial final block read with no @pl.when / iota guard —
  the exact hazard class ROADMAP item 1's ragged paged-attention
  kernel lives in.

Audit targets: the optimized gpt_hybrid_train step (perfgate's shared
builder — the Pallas kernels as the flagship actually invokes them),
every serving-engine program via ``LLMEngine.audit_programs()``
(pure-JAX today — pre-gating item 1's serving kernel), each
``ops/pallas`` kernel traced STANDALONE in interpret mode (flash,
block-sparse, ring, norm, optim — every code path, not just the ones
the flagship picks), and ``pallas_source`` — the trace-free AST pass
over ``ops/pallas/*.py``.

Usage:
  python tools/kernlint.py                     # report everything
  python tools/kernlint.py --check             # vs baseline, CI gate
  python tools/kernlint.py --write-baseline
  python tools/kernlint.py --diff              # per-rule counts vs baseline
  python tools/kernlint.py --json -            # machine-readable report
  python tools/kernlint.py --rules             # KL rule catalogue
  python tools/kernlint.py --targets norm optim

Exit codes: 0 clean, 1 findings (plain) / NEW findings vs baseline
(--check), 2 usage error.

Suppression: the same `# tracelint: disable=KL101` per-line comments
the other analyzers honor (`# kernlint: disable=...` is an accepted
alias, scoped to KL codes — no foreign spelling can waive a KL
finding, and a kernlint-spelled comment waives nothing else).  The
checked-in baseline (tools/kernlint_baseline.json) holds the reviewed
findings; `--check` reports only regressions beyond it.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(1, os.path.join(REPO, "tools"))

# static analysis must never claim (or wedge on) the TPU: every target
# traces in interpret mode, so the CPU backend is always right here
os.environ.setdefault("JAX_PLATFORMS", "cpu")

DEFAULT_BASELINE = os.path.join(REPO, "tools", "kernlint_baseline.json")


# ------------------------------------------------------------- targets
def target_gpt_hybrid_train():
    """The optimized flagship train step (perfgate's shared builder:
    bf16 activation residency + fused AdamW + Pallas fused LN) — the
    kernels exactly as the program that ships invokes them."""
    from perfgate import build_gpt_train_step

    from paddle_tpu import analysis

    train_step, ids, labels = build_gpt_train_step(optimized=True)
    jaxpr, _infos = train_step.traced_program(ids, labels)
    return [("gpt_hybrid_train",
             analysis.check_kernels(jaxpr, where="<gpt_hybrid_train>"))]


def target_serving():
    """Every serving-engine program.  Pure-JAX today (zero pallas_call
    eqns, zero findings) — the target exists so ROADMAP item 1's ragged
    paged-attention kernel is gated the moment it lands."""
    import jax.numpy as jnp

    import paddle_tpu as P
    from paddle_tpu import analysis, serving
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(0)
    mcfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=128, dropout=0.0,
                     attention_dropout=0.0)
    engine = serving.LLMEngine(
        GPTForCausalLM(mcfg),
        serving.EngineConfig(max_num_seqs=4, page_size=8,
                             max_model_len=64, prefill_buckets=(16, 32),
                             dtype=jnp.float32))
    out = []
    try:
        for name, jaxpr in engine.audit_programs().items():
            out.append((f"serving/{name}", analysis.check_kernels(
                jaxpr, where=f"<serving {name}>")))
    finally:
        engine.shutdown()
    return out


def _standalone(label, fn, *args):
    """Trace one kernel entry point standalone and audit the jaxpr."""
    import jax

    from paddle_tpu import analysis

    jaxpr = jax.make_jaxpr(fn)(*args)
    return [(label, analysis.check_kernels(jaxpr, where=f"<{label}>"))]


def target_flash_attention():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import flash_attention as fa

    q = jnp.zeros((1, 256, 2, 64), jnp.float32)
    return _standalone(
        "flash_attention",
        lambda q, k, v: fa.flash_attention_bshd(
            q, k, v, causal=True, block_q=128, block_k=128,
            interpret=True),
        q, q, q)


def target_block_sparse_attention():
    import numpy as np
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import block_sparse_attention as bsa

    q = jnp.zeros((1, 2, 256, 64), jnp.float32)
    mask = np.tril(np.ones((2, 2), bool))        # 2x2 blocks of 128
    tables = bsa.prepare_block_mask(mask, 128, 128)
    return _standalone(
        "block_sparse_attention",
        lambda q, k, v: bsa.block_sparse_flash_attention(
            q, k, v, tables, 0.125, 128, 128, True),
        q, q, q)


def target_ring_attention():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import ring_attention as ra

    q = jnp.zeros((1, 2, 256, 64), jnp.float32)
    return _standalone(
        "ring_attention",
        lambda q, k, v: ra.ring_flash_attention(
            q, k, v, causal=True, axis_size=1, block_q=128,
            block_k=128, interpret=True),
        q, q, q)


def target_norm():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import norm

    x = jnp.zeros((64, 256), jnp.float32)
    w = jnp.ones((256,), jnp.float32)
    b = jnp.zeros((256,), jnp.float32)
    out = _standalone(
        "norm/layer_norm",
        lambda x, w, b: norm.fused_layer_norm(x, w, b, interpret=True),
        x, w, b)
    out += _standalone(
        "norm/rms_norm",
        lambda x, w: norm.fused_rms_norm(x, w, interpret=True), x, w)
    return out


def target_optim():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import optim

    p = jnp.zeros((256, 512), jnp.float32)
    g = jnp.ones_like(p)
    m = jnp.zeros_like(p)
    v = jnp.zeros_like(p)

    def run(p, g, m, v, guard):
        return optim.fused_adam_update(
            p, g, m, v, 1e-3, 0.9, 0.999, beta1=0.9, beta2=0.999,
            eps=1e-8, weight_decay=0.01, guard=guard, interpret=True)

    out = _standalone("optim/adamw",
                      lambda *a: run(*a, guard=False), p, g, m, v)
    out += _standalone("optim/adamw_guard",
                       lambda *a: run(*a, guard=True), p, g, m, v)
    return out


def target_pallas_source():
    """The trace-free AST pass over ops/pallas/*.py (static KL101 on
    literal block tuples, static KL103 on unwidened dot-like calls)."""
    from paddle_tpu import analysis

    return [("pallas_source", analysis.check_kernel_files())]


TARGETS = {
    "gpt_hybrid_train": target_gpt_hybrid_train,
    "serving": target_serving,
    "flash_attention": target_flash_attention,
    "block_sparse_attention": target_block_sparse_attention,
    "ring_attention": target_ring_attention,
    "norm": target_norm,
    "optim": target_optim,
    "pallas_source": target_pallas_source,
}


def run_targets(names=None):
    """[(program_name, [Finding])] over the chosen targets."""
    results = []
    for name in (names or sorted(TARGETS)):
        if name not in TARGETS:
            raise SystemExit(f"kernlint: unknown target {name!r} "
                             f"(have: {', '.join(sorted(TARGETS))})")
        results.extend(TARGETS[name]())
    return results


def bench_report(targets=None):
    """The bench.py --worker-kernlint lane: finding count + per-rule
    breakdown over every kernel target, so every BENCH run records the
    kernel-interior hazard picture next to the cost audit."""
    t0 = time.time()
    results = run_targets(targets)
    breakdown = {}
    for _name, findings in results:
        for f in findings:
            breakdown[f.code] = breakdown.get(f.code, 0) + 1
    return {
        "kernlint_finding_count": sum(len(fs) for _, fs in results),
        "kernlint_rule_breakdown": dict(sorted(breakdown.items())),
        "kernlint_elapsed_s": round(time.time() - t0, 2),
    }


# ----------------------------------------------------------------- CLI
def main(argv=None):
    from paddle_tpu.analysis import common
    from paddle_tpu.analysis.rules import KERNLINT_CODES, RULES

    ap = argparse.ArgumentParser(
        prog="kernlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--targets", nargs="*", default=None,
                    help=f"audit targets (default: all — "
                         f"{', '.join(sorted(TARGETS))})")
    common.add_baseline_args(ap, DEFAULT_BASELINE)
    ap.add_argument("--rules", action="store_true",
                    help="print the KL rule catalogue and exit")
    args = ap.parse_args(argv)

    if args.rules:
        return common.print_rules(RULES, codes=set(KERNLINT_CODES))

    t0 = time.time()
    results = run_targets(args.targets)
    elapsed = time.time() - t0
    findings = [f for _, fs in results for f in fs]

    if not args.write_baseline and not args.diff:
        for name, fs in results:
            print(f"== {name}: {len(fs)} finding(s)")
    return common.run_baseline_flow(
        findings, args, tool="kernlint", repo=REPO, elapsed=elapsed)


if __name__ == "__main__":
    sys.exit(main())
