#!/usr/bin/env python
"""lint_all — the one-exit-code gate CI runs.

Chains every baseline-gated analyzer in the repo, plus the chaos suite:

  1. tracelint  --check paddle_tpu examples   (AST trace-safety, TLxxx)
  2. shardlint  --check                       (sharding/memory audit, SLxxx)
  3. racelint   --check paddle_tpu            (host concurrency audit, RLxxx)
  4. perfgate   --check                       (deterministic cost-model
                                               perf budgets: bytes/flops
                                               per step, padding waste,
                                               compile bounds vs
                                               tools/perf_baseline.json)
  5. api_coverage --baseline                  (public-surface regressions)
  6. pytest -m chaos                          (deterministic fault-injection
                                               acceptance proofs, run under
                                               the racelint lock-order
                                               tracer — tests/conftest.py
                                               arms it for chaos-marked
                                               tests and fails on any
                                               dynamic order violation)

The static gates compare against their checked-in baselines and fail
only on REGRESSIONS; the chaos gate re-proves the resilience contracts
(torn-checkpoint + preemption training resume matches the fault-free
trajectory; serving pool-exhaustion + mid-decode-fault recovery stays
token-identical under the compile bound — docs/resilience.md).  So
`python tools/lint_all.py` exits 0 on a healthy tree and nonzero the
moment any gate slips.  The `lint`-marked pytest test
(tests/test_lint_all.py) shells out to this script, which is how tier-1
enforces every gate at once.  The chaos gate deselects itself there via
`-m "chaos"` targeting only tests/test_resilience.py — chaos tests
carry no `lint` marker, so the recursion terminates.

Usage: python tools/lint_all.py
       [--skip tracelint shardlint racelint perfgate coverage chaos]
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")

GATES = {
    "tracelint": [sys.executable, os.path.join(TOOLS, "tracelint.py"),
                  "--check", "paddle_tpu", "examples"],
    "shardlint": [sys.executable, os.path.join(TOOLS, "shardlint.py"),
                  "--check"],
    "racelint": [sys.executable, os.path.join(TOOLS, "racelint.py"),
                 "--check", "paddle_tpu"],
    "perfgate": [sys.executable, os.path.join(TOOLS, "perfgate.py"),
                 "--check"],
    "coverage": [sys.executable, os.path.join(TOOLS, "api_coverage.py"),
                 "--baseline",
                 os.path.join(TOOLS, "api_coverage_baseline.json")],
    # scoped to the one chaos file: `-m chaos` over the whole tree would
    # pay full collection, and -p no:cacheprovider keeps gate runs from
    # racing tier-1's .pytest_cache
    "chaos": [sys.executable, "-m", "pytest", "-q", "-m", "chaos",
              "-p", "no:cacheprovider",
              os.path.join(REPO, "tests", "test_resilience.py")],
}


def main(argv=None):
    ap = argparse.ArgumentParser(prog="lint_all", description=__doc__)
    ap.add_argument("--skip", nargs="*", default=(),
                    choices=sorted(GATES), help="gates to skip")
    args = ap.parse_args(argv)

    failures = []
    for name, cmd in GATES.items():
        if name in args.skip:
            print(f"-- {name}: SKIPPED")
            continue
        t0 = time.time()
        try:
            # a wedged backend init must FAIL the gate, not hang CI
            proc = subprocess.run(cmd, cwd=REPO, capture_output=True,
                                  text=True, timeout=300)
        except subprocess.TimeoutExpired:
            print(f"-- {name}: FAIL (timed out after 300s)")
            failures.append(name)
            continue
        status = "ok" if proc.returncode == 0 else f"FAIL rc={proc.returncode}"
        print(f"-- {name}: {status} in {time.time() - t0:.1f}s")
        if proc.returncode != 0:
            failures.append(name)
            sys.stdout.write(proc.stdout)
            sys.stderr.write(proc.stderr)
    if failures:
        print(f"lint_all: FAILED ({', '.join(failures)})")
        return 1
    print("lint_all: all gates clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
