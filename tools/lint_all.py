#!/usr/bin/env python
"""lint_all — the one-exit-code gate CI runs.

Chains every baseline-gated analyzer in the repo, plus the chaos suite:

  1. tracelint  --check paddle_tpu examples   (AST trace-safety, TLxxx)
  2. shardlint  --check                       (sharding/memory audit, SLxxx)
  3. racelint   --check paddle_tpu            (host concurrency audit, RLxxx)
  4. numlint    --check                       (numerics & precision-flow
                                               audit over the traced
                                               flagship + serving
                                               programs, NLxxx)
  5. kernlint   --check                       (Pallas kernel-interior
                                               audit: tile alignment,
                                               VMEM budgets, in-kernel
                                               numerics, alias hazards,
                                               grid coverage, ragged
                                               tails — KLxxx over the
                                               flagship + serving + each
                                               ops/pallas kernel traced
                                               standalone)
  6. protolint  --check paddle_tpu            (coordination-KV protocol
                                               audit: key leaks, consume-
                                               without-delete, unbounded
                                               blocking gets, cross-role
                                               wait cycles, liveness
                                               budgets, error envelopes,
                                               seq reuse — PLxxx)
  7. perfgate   --check                       (deterministic cost-model
                                               perf budgets: bytes/flops
                                               per step, padding waste,
                                               compile bounds vs
                                               tools/perf_baseline.json)
  8. api_coverage --baseline                  (public-surface regressions)
  9. pytest -m chaos                          (deterministic fault-injection
                                               acceptance proofs, run under
                                               the racelint lock-order
                                               tracer — tests/conftest.py
                                               arms it for chaos-marked
                                               tests and fails on any
                                               dynamic order violation;
                                               since PR 14 this includes
                                               the fleet suite: the
                                               threaded reconfigure ladder
                                               in tests/test_fleet.py and
                                               the REAL 3-process
                                               SIGKILL→reconfigure→resume
                                               proof in tests/
                                               test_distributed_multiprocess
                                               .py — measured ~25-35s,
                                               budgeted inside the gate's
                                               480s wall-time cap)

The static gates compare against their checked-in baselines and fail
only on REGRESSIONS; the chaos gate re-proves the resilience contracts
(torn-checkpoint + preemption training resume matches the fault-free
trajectory; serving pool-exhaustion + mid-decode-fault recovery stays
token-identical under the compile bound — docs/resilience.md; the
multi-host serving fleet keeps streams exactly-once and output
token-identical through SIGKILL and SIGSTOP-wedge failovers —
docs/serving.md "Multi-host fleet").  So
`python tools/lint_all.py` exits 0 on a healthy tree and nonzero the
moment any gate slips.  The `lint`-marked pytest test
(tests/test_lint_all.py) shells out to this script, which is how tier-1
enforces every gate at once.  The chaos gate deselects itself there via
`-m "chaos"` targeting only tests/test_resilience.py — chaos tests
carry no `lint` marker, so the recursion terminates.

Usage: python tools/lint_all.py
       [--skip tracelint shardlint racelint numlint kernlint protolint
        perfgate coverage chaos]
       [--only <gate> [<gate> ...]]
       [--json FILE|-]   one unified {"tool": "lint_all", "gates":
                         {gate: {ok, findings, elapsed_s}}} document —
                         `findings` parsed from a gate's own summary
                         line where it prints one, else null
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")

GATES = {
    "tracelint": [sys.executable, os.path.join(TOOLS, "tracelint.py"),
                  "--check", "paddle_tpu", "examples"],
    "shardlint": [sys.executable, os.path.join(TOOLS, "shardlint.py"),
                  "--check"],
    "racelint": [sys.executable, os.path.join(TOOLS, "racelint.py"),
                 "--check", "paddle_tpu"],
    "numlint": [sys.executable, os.path.join(TOOLS, "numlint.py"),
                "--check"],
    "kernlint": [sys.executable, os.path.join(TOOLS, "kernlint.py"),
                 "--check"],
    "protolint": [sys.executable, os.path.join(TOOLS, "protolint.py"),
                  "--check", "paddle_tpu"],
    "perfgate": [sys.executable, os.path.join(TOOLS, "perfgate.py"),
                 "--check"],
    "coverage": [sys.executable, os.path.join(TOOLS, "api_coverage.py"),
                 "--baseline",
                 os.path.join(TOOLS, "api_coverage_baseline.json")],
    # scoped to the chaos-bearing files: `-m chaos` over the whole tree
    # would pay full collection, and -p no:cacheprovider keeps gate
    # runs from racing tier-1's .pytest_cache
    "chaos": [sys.executable, "-m", "pytest", "-q", "-m", "chaos",
              "-p", "no:cacheprovider",
              os.path.join(REPO, "tests", "test_resilience.py"),
              os.path.join(REPO, "tests", "test_fleet.py"),
              os.path.join(REPO, "tests", "test_sentinel.py"),
              os.path.join(REPO, "tests", "test_serving_fleet.py"),
              os.path.join(REPO, "tests", "test_traffic.py"),
              os.path.join(REPO, "tests",
                           "test_distributed_multiprocess.py")],
}

# per-gate wall budgets: the static gates are seconds, but the chaos
# gate now spawns a real 3-process fleet (2 rendezvous + a SIGKILL
# detection window) — measured ~25-35s for the fleet half, capped with
# generous headroom for cold CI boxes
_GATE_TIMEOUT_S = {"chaos": 480}
_DEFAULT_TIMEOUT_S = 300

# the analyzers' shared summary line: "{tool}: N finding(s) ..."
_FINDINGS_RE = re.compile(r"^\w+: (\d+) finding\(s\)", re.MULTILINE)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="lint_all", description=__doc__)
    ap.add_argument("--skip", nargs="*", default=(),
                    choices=sorted(GATES), help="gates to skip")
    ap.add_argument("--only", nargs="*", default=None,
                    choices=sorted(GATES),
                    help="run ONLY these gates (everything else is "
                         "reported as SKIPPED)")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="also write the unified per-gate report as "
                         "JSON ('-' for stdout)")
    args = ap.parse_args(argv)

    if args.only is not None and not args.only:
        # `--only` with no gates (e.g. an empty shell variable) would
        # skip EVERYTHING and still print "all gates clean" — a false
        # green; fail fast instead
        ap.error("--only requires at least one gate")

    doc = {"tool": "lint_all", "version": 1, "gates": {}}
    failures = []
    for name, cmd in GATES.items():
        if name in args.skip or \
                (args.only is not None and name not in args.only):
            print(f"-- {name}: SKIPPED")
            doc["gates"][name] = {"ok": None, "findings": None,
                                  "elapsed_s": 0.0, "skipped": True}
            continue
        t0 = time.time()
        budget = _GATE_TIMEOUT_S.get(name, _DEFAULT_TIMEOUT_S)
        try:
            # a wedged backend init must FAIL the gate, not hang CI
            proc = subprocess.run(cmd, cwd=REPO, capture_output=True,
                                  text=True, timeout=budget)
        except subprocess.TimeoutExpired:
            print(f"-- {name}: FAIL (timed out after {budget}s)")
            failures.append(name)
            doc["gates"][name] = {"ok": False, "findings": None,
                                  "elapsed_s": round(time.time() - t0, 2),
                                  "error": "timeout"}
            continue
        elapsed = time.time() - t0
        status = "ok" if proc.returncode == 0 else f"FAIL rc={proc.returncode}"
        print(f"-- {name}: {status} in {elapsed:.1f}s")
        m = _FINDINGS_RE.search(proc.stdout)
        doc["gates"][name] = {
            "ok": proc.returncode == 0,
            "findings": int(m.group(1)) if m else None,
            "elapsed_s": round(elapsed, 2),
        }
        if proc.returncode != 0:
            failures.append(name)
            sys.stdout.write(proc.stdout)
            sys.stderr.write(proc.stderr)

    if args.json:
        if args.json == "-":
            json.dump(doc, sys.stdout, indent=1)
            print()
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1)
                fh.write("\n")

    if failures:
        print(f"lint_all: FAILED ({', '.join(failures)})")
        return 1
    print("lint_all: all gates clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
