#!/usr/bin/env python
"""shardlint CLI — sharding, collective-safety & TPU memory/padding audit.

Unlike tracelint's AST pass, shardlint needs TRACED programs: each audit
target below builds one of the repo's real compiled programs (the GPT
hybrid-parallel train step from models/gpt.py + optimizer/, the serving
engine's bucketed prefill / single decode step from serving/engine.py),
traces it on CPU (shape-only — no TPU time, no compile), and runs the
SL-rule audit from paddle_tpu/analysis/shard_rules.py + cost_audit.py
against a HYPOTHETICAL production mesh.  Sharding facts come from the
dist_spec annotations the model/optimizer attach, so the audit is
meaningful on a single-device host.

Usage:
  python tools/shardlint.py                     # report everything
  python tools/shardlint.py --check             # vs baseline, CI gate
  python tools/shardlint.py --write-baseline
  python tools/shardlint.py --json -            # machine-readable report
  python tools/shardlint.py --rules             # SL rule catalogue
  python tools/shardlint.py --targets gpt_hybrid_train

Exit codes: 0 clean, 1 findings (plain) / NEW findings vs baseline
(--check), 2 usage error.

Suppression: the same `# tracelint: disable=SL201` per-line comments the
AST pass honors — shardlint resolves each finding back to a source line
through the eqn's jax source_info.  The checked-in baseline
(tools/shardlint_baseline.json) holds reviewed findings; `--check`
reports only regressions beyond it.  The JSON report schema is shared
with `tools/tracelint.py --json` (analysis/report.to_json).
"""
from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# static analysis must never claim (or wedge on) the TPU: the audit is
# shape-only, so the CPU backend is always the right one here
os.environ.setdefault("JAX_PLATFORMS", "cpu")

DEFAULT_BASELINE = os.path.join(REPO, "tools", "shardlint_baseline.json")

# the hypothetical production topology CPU-traced programs are audited
# against (a v5e-pod-slice-shaped dp x tp mesh)
AUDIT_MESH_AXES = {"dp": 8, "tp": 4}


# ------------------------------------------------------------- targets
def _audit_config(analysis, **kw):
    """Thresholds scaled to the tiny CI configs the targets build —
    small enough that the same defect classes fire on a 64-hidden model
    as would on the 1.3B config."""
    base = dict(large_replicated_bytes=1 << 20,
                opt_state_min_bytes=16 << 10,
                allgather_budget_bytes=256 << 20,
                padding_waste_threshold=0.25,
                mxu_min_bytes=16 << 10,
                f32_param_min_bytes=64 << 10)
    base.update(kw)
    return analysis.AuditConfig(**base)


def target_gpt_hybrid_train():
    """The hybrid-parallel flagship: tiny-config GPT (models/gpt.py,
    tp-annotated weights) + AdamW train step traced via to_static,
    audited against the dp x tp production mesh."""
    import numpy as np

    import paddle_tpu as P
    import paddle_tpu.nn.functional as F
    from paddle_tpu import analysis
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny

    P.seed(0)
    # the flagship config as shipped: bf16 activation residency, fused
    # single-pass AdamW, Pallas fused LN (the PR 10 bytes/step work) —
    # the audit covers the program that actually runs, so SL302 tile
    # shapes and SL303 storage findings gate the NEW paths
    cfg = gpt3_tiny(fused_ln=True)
    model = GPTForCausalLM(cfg)
    opt = P.optimizer.AdamW(learning_rate=1e-4,
                            parameters=model.parameters(), fused=True)

    @P.jit.to_static(amp_policy="bf16")
    def train_step(ids, labels):
        opt.clear_grad()
        logits = model(ids)
        loss = F.cross_entropy(logits.reshape([-1, cfg.vocab_size]),
                               labels.reshape([-1]))
        loss.backward()
        opt.step()
        return loss

    rng = np.random.default_rng(0)
    ids = P.to_tensor(rng.integers(0, cfg.vocab_size, (2, 32)),
                      dtype="int64")
    labels = P.to_tensor(rng.integers(0, cfg.vocab_size, (2, 32)),
                         dtype="int64")
    jaxpr, infos = train_step.traced_program(ids, labels)
    mesh = analysis.MeshInfo.of(axes=AUDIT_MESH_AXES)
    findings, rep = analysis.audit_jaxpr(
        jaxpr, where="<gpt_hybrid_train>", inputs=infos, mesh=mesh,
        config=_audit_config(analysis))
    return [("gpt_hybrid_train", findings, rep)]


def target_serving():
    """The serving engine's whole program set (bucketed prefill, the one
    decode step, both sampler widths) audited against the engine's own
    documented page/HBM budget."""
    import paddle_tpu as P
    from paddle_tpu import analysis, serving
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(0)
    mcfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=128, dropout=0.0,
                     attention_dropout=0.0)
    engine = serving.LLMEngine(
        GPTForCausalLM(mcfg),
        serving.EngineConfig(max_num_seqs=4, page_size=8, max_model_len=64,
                             prefill_buckets=(16, 32)))
    cfg = _audit_config(analysis,
                        hbm_budget_bytes=engine.hbm_budget_bytes)
    out = []
    for name, jaxpr in engine.audit_programs().items():
        findings, rep = analysis.audit_jaxpr(
            jaxpr, where=f"<serving {name}>", config=cfg)
        out.append((f"serving/{name}", findings, rep))
    engine.shutdown()
    return out


TARGETS = {
    "gpt_hybrid_train": target_gpt_hybrid_train,
    "serving": target_serving,
}


def run_targets(names=None):
    """[(program_name, [Finding], CostReport)] over the chosen targets."""
    results = []
    for name in (names or sorted(TARGETS)):
        if name not in TARGETS:
            raise SystemExit(f"shardlint: unknown target {name!r} "
                             f"(have: {', '.join(sorted(TARGETS))})")
        results.extend(TARGETS[name]())
    return results


def bench_report(targets=("gpt_hybrid_train", "serving")):
    """The bench.py report lane: estimated peak-HBM + MXU padding waste
    per flagship program, next to the finding count — so every BENCH
    run records the static cost picture alongside wall time."""
    t0 = time.time()
    results = run_targets(list(targets))
    out, total = {}, 0
    for name, findings, rep in results:
        total += len(findings)
        key = name.replace("/", "_").replace("gpt_hybrid_train", "gpt")
        out[f"shardlint_{key}_peak_hbm_mb"] = round(
            rep.peak_hbm_bytes / (1 << 20), 3)
        out[f"shardlint_{key}_padding_waste_pct"] = round(
            100.0 * rep.padding_waste, 2)
    out["shardlint_findings"] = total
    out["shardlint_elapsed_s"] = round(time.time() - t0, 2)
    return out


# ----------------------------------------------------------------- CLI
def main(argv=None):
    from paddle_tpu.analysis import common
    from paddle_tpu.analysis.rules import RULES, SHARDLINT_CODES

    ap = argparse.ArgumentParser(
        prog="shardlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--targets", nargs="*", default=None,
                    help=f"audit targets (default: all — "
                         f"{', '.join(sorted(TARGETS))})")
    common.add_baseline_args(ap, DEFAULT_BASELINE)
    ap.add_argument("--rules", action="store_true",
                    help="print the SL rule catalogue and exit")
    args = ap.parse_args(argv)

    if args.rules:
        return common.print_rules(RULES, codes=set(SHARDLINT_CODES))

    t0 = time.time()
    results = run_targets(args.targets)
    elapsed = time.time() - t0
    findings = [f for _, fs, _ in results for f in fs]

    if not args.write_baseline:
        for name, fs, rep in results:
            d = rep.to_dict()
            print(f"== {name}: peak HBM {d['peak_hbm_mb']} MiB (est), "
                  f"padding waste {d['padding_waste_pct']}%, "
                  f"{len(fs)} finding(s)")
    return common.run_baseline_flow(
        findings, args, tool="shardlint", repo=REPO, elapsed=elapsed,
        json_extra={"programs": {name: rep.to_dict()
                                 for name, _, rep in results}})


if __name__ == "__main__":
    sys.exit(main())
