"""Profile the bench's ResNet-50 train step on the real TPU.

Reports, per step: wall time, XLA cost-analysis FLOPs (so MFU can be
cross-checked against bench.py's analytic 3x4.1GF/img estimate), the
compiled HLO's convolution dtypes (fp32 pockets under O1 would show up
here), and optionally a jax.profiler trace for timeline inspection.

Usage: python tools/profile_resnet.py [--trace DIR] [--batch N] [--iters N]
"""
from __future__ import annotations

import argparse
import os
import re
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default=None)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--data-format", default="NHWC")
    ap.add_argument("--no-amp", action="store_true")
    args = ap.parse_args()

    import jax

    import paddle_tpu as P
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.models import resnet50

    P.seed(0)
    model = resnet50(num_classes=1000, data_format=args.data_format)
    opt = P.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                               parameters=model.parameters())

    @P.jit.to_static
    def train_step(x, y):
        opt.clear_grad()
        if args.no_amp:
            logits = model(x)
        else:
            with P.amp.auto_cast(level="O1", dtype="bfloat16"):
                logits = model(x)
        loss = F.cross_entropy(logits, y)
        loss.backward()
        opt.step()
        return loss

    rng = np.random.default_rng(0)
    shape = ((args.batch, 224, 224, 3) if args.data_format == "NHWC"
             else (args.batch, 3, 224, 224))
    x = P.to_tensor(rng.standard_normal(shape).astype(np.float32))
    y = P.to_tensor(rng.integers(0, 1000, (args.batch,)), dtype="int64")

    # warmup + grab the cached compiled executable for cost analysis
    loss = train_step(x, y)
    loss.block_until_ready()

    compiled = None
    try:
        entry = next(iter(train_step._compiled.values())); jitted, state_list = entry.jitted, entry.state_list
        compiled = jitted.lower([t._value for t in state_list],
                                [x._value, y._value]).compile()
    except Exception as e:
        print("could not re-lower compiled step:", e)
    if compiled is not None:
        try:
            cost = compiled.cost_analysis()
            cost = cost[0] if isinstance(cost, (list, tuple)) else cost
            print("xla cost_analysis flops:", cost.get("flops"))
            print("  bytes accessed:", cost.get("bytes accessed"))
        except Exception as e:
            print("cost_analysis failed:", e)
        try:
            hlo = compiled.as_text()
            convs = re.findall(r"(\S+) = (\S+) convolution\(", hlo)
            dt = {}
            for _, sig in re.findall(r"= ((?:bf16|f32|f16|s8|s32)[^ ]*) "
                                     r"(convolution|dot)\(", hlo):
                dt[sig.split("[")[0]] = dt.get(sig.split("[")[0], 0) + 1
            print("conv/dot output dtypes:", dt)
            n_f32_conv = len(re.findall(r"= f32[^=]*convolution\(", hlo))
            print("f32 convolutions:", n_f32_conv)
            print("fusions:", hlo.count(" fusion("),
                  " all-reduce:", hlo.count("all-reduce("),
                  " copies:", hlo.count(" copy("))
        except Exception as e:
            print("hlo inspect failed:", e)

    # per-step timing: individually synced (exposes per-call overhead) ...
    ts = []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        loss = train_step(x, y)
        loss.block_until_ready()
        ts.append(time.perf_counter() - t0)
    per_step_synced = float(np.median(ts))

    # ... vs free-running (the bench's measurement mode)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        loss = train_step(x, y)
    loss.block_until_ready()
    per_step_stream = (time.perf_counter() - t0) / args.iters

    dev = jax.devices()[0]
    import importlib.util as _u
    _spec = _u.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    _bench = _u.module_from_spec(_spec)
    _spec.loader.exec_module(_bench)
    peak = _bench._lookup(_bench._PEAK_TFLOPS,
                          getattr(dev, "device_kind", ""), 197.0) * 1e12
    flops_img = _bench._RESNET50_TRAIN_FLOPS  # FLOPs (2x MACs), like bench
    for name, t in [("synced", per_step_synced), ("stream", per_step_stream)]:
        img_s = args.batch / t
        print(f"{name}: {t*1e3:.1f} ms/step  {img_s:.0f} img/s  "
              f"mfu={img_s*flops_img/peak:.3f}")

    if args.trace:
        with jax.profiler.trace(args.trace):
            for _ in range(3):
                loss = train_step(x, y)
            loss.block_until_ready()
        print("trace written to", args.trace)


if __name__ == "__main__":
    main()
