#!/bin/bash
# Autonomous TPU watcher: probe -> on success run the one-shot capture.
# Leave running detached; it never kills anything (wedge rule), probes
# SEQUENTIALLY (one python at a time), and exits after a successful
# capture or --max-cycles attempts.
#
#   nohup bash tools/watch_tpu.sh [max_cycles] > watch_tpu.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
MAX=${1:-24}
for ((i = 1; i <= MAX; i++)); do
  echo "[watch_tpu] cycle $i/$MAX $(date -u +%H:%M:%S)"
  python tools/tpu_probe.py > .tpu_probe_r4.json 2> .tpu_probe_r4.err
  if grep -q '"ok": true' .tpu_probe_r4.json 2>/dev/null; then
    echo "[watch_tpu] TPU ALIVE — running silicon capture"
    bash tools/run_on_silicon.sh
    rc=$?
    if [ "$rc" -eq 0 ]; then
      echo "[watch_tpu] capture complete; exiting"
      exit 0
    fi
    echo "[watch_tpu] capture rc=$rc (transient wedge?); keep watching"
  fi
  echo "[watch_tpu] probe: $(head -c 120 .tpu_probe_r4.json)"
  sleep 60   # probes self-throttle (~25 min each on a dead backend)
done
echo "[watch_tpu] gave up after $MAX cycles"
exit 1
