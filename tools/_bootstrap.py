"""Shared CLI bootstrap: stub the `paddle_tpu` package namespace.

The stdlib-only analyzers (tracelint's AST pass, racelint) must import
`paddle_tpu.analysis` WITHOUT executing the real paddle_tpu/__init__.py
(which imports jax) — the gates have to stay fast enough to run on
every CI invocation, and a wedged accelerator claim must not hang a
lint.  Installing a bare package module with the right ``__path__``
lets submodule imports resolve normally.  No-op when paddle_tpu is
already imported (e.g. under pytest).
"""
from __future__ import annotations

import os
import sys
import types


def light_paddle_tpu(repo):
    """Make `paddle_tpu.*` submodules importable jax-free."""
    if "paddle_tpu" not in sys.modules:
        pkg = types.ModuleType("paddle_tpu")
        pkg.__path__ = [os.path.join(repo, "paddle_tpu")]
        sys.modules["paddle_tpu"] = pkg
