"""Roofline profile of the bench's BERT-base train step on real TPU.

Answers "where does the other ~70% of MFU go" with data rather than
guesswork: XLA cost analysis of the compiled step gives flops and HBM
bytes; bytes/step over the measured step time vs the ~819 GB/s v5e HBM
tells whether the step is bandwidth-bound (like ResNet) or occupancy-
bound; the dot-shape census from the compiled HLO shows how much of the
time sits in GEMMs too narrow to fill the 128x128 MXU.

Usage: python tools/profile_bert.py [--batch N] [--iters N]
"""
from __future__ import annotations

import argparse
import os
import re
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HBM_GBPS = 819.0   # v5e


def _fused_bert(P, cfg):
    """BERT-base MLM stack from the incubate fused blocks: each layer is
    FusedMultiHeadAttention (qkv+attn+proj+residual+LN in one region) +
    FusedFeedForward — the attention-epilogue-fusion A/B the r4 verdict
    asked for (#4). Same dims/flops as BertForPretraining; weights are
    freshly initialized (throughput comparison, not numerics)."""
    from paddle_tpu import nn
    from paddle_tpu.incubate.nn import (FusedFeedForward,
                                        FusedMultiHeadAttention)
    from paddle_tpu.models.bert import BertEmbeddings, BertLMHead

    class FusedBertMLM(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embeddings = BertEmbeddings(cfg)
            self.blocks = nn.LayerList()
            for _ in range(cfg.num_layers):
                self.blocks.append(FusedMultiHeadAttention(
                    cfg.hidden_size, cfg.num_heads, dropout_rate=0.0,
                    attn_dropout_rate=0.0, epsilon=cfg.layer_norm_epsilon))
                self.blocks.append(FusedFeedForward(
                    cfg.hidden_size, cfg.ffn_hidden_size,
                    dropout_rate=0.0, activation="gelu",
                    epsilon=cfg.layer_norm_epsilon))
            self.cls = BertLMHead(
                cfg, self.embeddings.word_embeddings.weight)

        def forward(self, ids):
            h = self.embeddings(ids)
            for blk in self.blocks:
                h = blk(h)
            return self.cls(h)

    return FusedBertMLM()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--fused", action="store_true",
                    help="A/B: encoder built from incubate "
                         "FusedMultiHeadAttention + FusedFeedForward "
                         "(attention-epilogue fusion experiment for the "
                         "mfu 0.35 push)")
    args = ap.parse_args()

    import jax

    import paddle_tpu as P
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models.bert import BertConfig, BertForPretraining

    P.seed(0)
    cfg = BertConfig(dropout=0.0, attention_dropout=0.0)
    if args.fused:
        model = _fused_bert(P, cfg)
        print("encoder: incubate fused (MHA+FFN epilogue fusion)")
    else:
        model = BertForPretraining(cfg)
    opt = P.optimizer.AdamW(learning_rate=1e-4,
                            parameters=model.parameters())

    @P.jit.to_static
    def train_step(ids, labels):
        opt.clear_grad()
        with P.amp.auto_cast(level="O1", dtype="bfloat16"):
            out = model(ids)
            pred = out[0] if isinstance(out, tuple) else out
        loss = F.cross_entropy(
            pred.reshape([-1, cfg.vocab_size]), labels.reshape([-1]))
        loss.backward()
        opt.step()
        return loss

    rng = np.random.default_rng(0)
    ids = P.to_tensor(rng.integers(0, cfg.vocab_size,
                                   (args.batch, args.seq)), dtype="int64")
    labels = P.to_tensor(rng.integers(0, cfg.vocab_size,
                                      (args.batch, args.seq)),
                         dtype="int64")
    loss = train_step(ids, labels)
    loss.block_until_ready()

    flops = bytes_acc = None
    try:
        entry = next(iter(train_step._compiled.values())); jitted, state_list = entry.jitted, entry.state_list
        compiled = jitted.lower([t._value for t in state_list],
                                [ids._value, labels._value]).compile()
        cost = compiled.cost_analysis()
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        flops = float(cost.get("flops", 0.0))
        bytes_acc = float(cost.get("bytes accessed", 0.0))
        print(f"xla flops/step: {flops:.3e}  bytes/step: {bytes_acc:.3e}")
        # dot-shape census: which GEMM shapes carry the flops
        hlo = compiled.as_text()
        shapes = {}
        for m in re.finditer(
                r"= (bf16|f32)\[([0-9,]+)\][^=]*? dot\(", hlo):
            key = f"{m.group(1)}[{m.group(2)}]"
            shapes[key] = shapes.get(key, 0) + 1
        top = sorted(shapes.items(), key=lambda kv: -kv[1])[:12]
        print("dot output shapes (count):")
        for k, c in top:
            print(f"  {c:4d}x {k}")
        print("fusions:", hlo.count(" fusion("),
              " custom-calls:", hlo.count("custom-call("),
              " copies:", hlo.count(" copy("))
    except Exception as e:  # noqa: BLE001
        print("cost/HLO analysis failed:", e)

    # free-running step time (bench's mode: serial dependence via state)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        loss = train_step(ids, labels)
    loss.block_until_ready()
    dt = (time.perf_counter() - t0) / args.iters

    tok_s = args.batch * args.seq / dt
    print(f"step {dt*1e3:.1f} ms  {tok_s:.0f} tokens/s")
    if flops:
        mfu = flops / dt / 197e12
        print(f"mfu (xla flops): {mfu:.3f}")
    if bytes_acc:
        bw = bytes_acc / dt / 1e9
        util = bw / HBM_GBPS
        print(f"hbm: {bytes_acc/1e9:.2f} GB/step -> {bw:.0f} GB/s "
              f"({util:.1%} of {HBM_GBPS:.0f})")
        if flops and bytes_acc:
            ai = flops / bytes_acc
            print(f"arithmetic intensity {ai:.0f} flop/byte "
                  f"(v5e ridge ~{197e12/HBM_GBPS/1e9:.0f}) -> "
                  f"{'COMPUTE' if ai > 197e12/(HBM_GBPS*1e9) else 'MEMORY'}"
                  "-bound in the roofline sense")


if __name__ == "__main__":
    main()
