"""Flash-attention block-size sweep (r4, VERDICT #3).

Measures fwd TFLOP/s of ops/pallas/flash_attention._flash_bhsd across
(block_q, block_k) at the headline shape (16k seq, d=128, bf16) plus a
BERT-shaped case, dense and causal, and appends the table to
BENCH_NOTES.md. Run ON TPU:  python tools/sweep_flash.py [--quick]

Measurement design (learned the hard way, twice): through the axon
relay (a) `block_until_ready()` can return before device execution
finishes, so wall-timing a dispatch loop reports impossible TFLOP/s
(the 04:04 grid hit 27000 "TFLOP/s" against a 197 TF/s peak), and
(b) every synced call pays a ~75 ms constant RPC floor, so single-call
timing undercounts small kernels ~50x (the 04:21 grid's BERT rows were
flat at the floor).  So: chain the kernel inside ONE jit with lax.scan
(output feeds the next input — no CSE, no overlap), sync by fetching a
scalar, and time the SAME computation at two scan lengths; the length
difference cancels every constant (RPC, dispatch, transfer) and the
delta is pure device time.

Never kill this process mid-run (TPU claim wedge); it bounds its own
work and exits.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _timed_scalar(fn, *args, reps=3):
    """Compile fn (returns a scalar), run once to warm, then take the
    min wall time of `reps` synced calls (min cuts relay jitter)."""
    import jax
    f = jax.jit(fn)
    float(f(*args))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(f(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def delta_time(make_chained, args, n1, n2):
    """Pure per-iteration device time via two-length subtraction:
    (t(n2-iter chain) - t(n1-iter chain)) / (n2 - n1)."""
    d1 = _timed_scalar(make_chained(n1), *args)
    d2 = _timed_scalar(make_chained(n2), *args)
    return max(d2 - d1, 1e-9) / (n2 - n1)


def vpu_probe(jax, jnp):
    """Measure the VPU's elementwise/transcendental throughput — the
    flash softmax (max, sub, exp2, sum, cast ≈ 6-8 VPU ops per score
    element) competes with the MXU dots (4·d flops per element). The
    attention ceiling is MXU_t / (MXU_t + VPU_t); whether ~26% kernel
    efficiency at d=128 is a defect or the roofline depends entirely on
    the real VPU rate, so measure it."""
    from jax import lax

    out = {}
    x0 = jnp.linspace(-4, 4, 4096 * 4096).reshape(4096, 4096)
    cases = (
        # clip keeps the scan chain bounded; counted as part of the
        # "exp2-class" op mix (softmax also pairs exp2 with a sub)
        ("exp2_f32", jnp.float32,
         lambda a: jnp.exp2(jnp.clip(a, -4.0, 4.0))),
        ("exp2_bf16", jnp.bfloat16,
         lambda a: jnp.exp2(jnp.clip(a, -4.0, 4.0))),
        ("addmul_f32", jnp.float32, lambda a: a * 1.5 + 0.5),
    )
    for name, dtype, op in cases:
        a0 = x0.astype(dtype)

        def make(n, op=op):
            def chained(a):
                def step(c, _):
                    return op(c), ()
                c, _ = lax.scan(step, a, None, length=n)
                return jnp.sum(c.astype(jnp.float32))
            return chained

        t_iter = delta_time(make, (a0,), 8, 520)
        out[name] = round(a0.size / t_iter / 1e9, 1)  # Gop/s
    return out


def bwd_sweep(jax, jnp, lax, _flash_bhsd, dev):
    """fwd+bwd (training-path) block sweep at the 16k headline shape.
    FLOP accounting from the kernel structure: fwd 2 dots + dq-kernel 3 +
    dkv-kernel 4 = 9 dots of 2·s²·d each per (b,h); causal halves."""
    b, h, s, d = 1, 4, 16384, 128
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
    scale = float(d) ** -0.5
    rows = []
    for causal in (False, True):
        flops = 18.0 * b * h * s * s * d * (0.5 if causal else 1.0)
        for bq in (512, 1024, 2048):
            for bk in (512, 1024, 2048):
                if bq == 2048 and bk == 2048:
                    continue  # fwd kernel VMEM-OOMs at this combo
                try:
                    def make(n, bq=bq, bk=bk, c=causal):
                        def chained(q, k, v):
                            def loss(qq, kk, vv):
                                o = _flash_bhsd(qq, kk, vv, c, scale,
                                                bq, bk, False)
                                return jnp.sum(o.astype(jnp.float32))

                            def step(carry, _):
                                qc, aux = carry
                                val, (dq, dk, dv) = jax.value_and_grad(
                                    loss, argnums=(0, 1, 2))(qc, k, v)
                                # dq feeds the next query; dk/dv fold into
                                # the carried scalar so DCE keeps them
                                qn = jnp.clip(dq, -3.0, 3.0).astype(
                                    qc.dtype)
                                aux = aux + val + jnp.sum(
                                    dk.astype(jnp.float32)) + jnp.sum(
                                    dv.astype(jnp.float32))
                                return (qn, aux), ()

                            (qf, aux), _ = lax.scan(
                                step, (q, jnp.float32(0.0)), None,
                                length=n)
                            return jnp.sum(qf.astype(jnp.float32)) + aux
                        return chained

                    t_iter = delta_time(make, (q, k, v), 1, 9)
                    tf = flops / t_iter / 1e12
                    rows.append(("16k-train", causal, bq, bk,
                                 round(tf, 1)))
                    print(f"16k fwd+bwd causal={causal} bq={bq} bk={bk}: "
                          f"{tf:.1f} TFLOP/s", flush=True)
                except Exception as e:  # noqa: BLE001
                    rows.append(("16k-train", causal, bq, bk,
                                 f"ERR {type(e).__name__}"))
                    print(f"16k fwd+bwd causal={causal} bq={bq} bk={bk}: "
                          f"ERROR {e}", flush=True)
    best = {}
    for name, causal, bq, bk, tf in rows:
        if isinstance(tf, float):
            key = causal
            if key not in best or tf > best[key][2]:
                best[key] = (bq, bk, tf)
    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    lines = [f"\n## Flash fwd+bwd block sweep ({stamp}, "
             f"{getattr(dev, 'device_kind', dev.platform)}, two-length "
             "delta timing; 9 dots = 18·bh·s²·d flops)\n"]
    for causal, (bq, bk, tf) in sorted(best.items()):
        lines.append(f"- 16k train causal={causal}: best {tf} TFLOP/s at "
                     f"block_q={bq}, block_k={bk}\n")
    lines.append("- full grid: " + json.dumps(rows) + "\n")
    notes = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_NOTES.md")
    with open(notes, "a") as fh:
        fh.writelines(lines)
    print("".join(lines))
    return 0


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from paddle_tpu.ops.pallas.flash_attention import _flash_bhsd

    dev = jax.devices()[0]
    if dev.platform == "cpu":
        print(json.dumps({"ok": False, "error": "cpu backend"}))
        return 1
    quick = "--quick" in sys.argv
    if "--bwd" in sys.argv:
        return bwd_sweep(jax, jnp, lax, _flash_bhsd, dev)

    vpu = vpu_probe(jax, jnp)
    print("VPU probe (Gop/s):", json.dumps(vpu), flush=True)
    # predicted attention ceiling at d=128, bf16 MXU 197 TF/s, ~7 VPU
    # ops per score element at the measured exp2-class rate
    try:
        vpu_rate = vpu["exp2_f32"] * 1e9
        mxu_t = 4 * 128 / 197e12
        vpu_t = 7 / vpu_rate
        ceiling = mxu_t / (mxu_t + vpu_t)
        print(f"predicted d=128 attention ceiling ≈ {ceiling:.2%} of MXU "
              f"peak ({ceiling * 197:.0f} TFLOP/s)", flush=True)
    except Exception:
        ceiling = None

    # (label, b, h, s, d, scan-length pair): the length delta targets
    # ~50-150 ms of pure kernel time so relay jitter (~ms) is noise
    shapes = [("16k", 1, 4, 16384, 128, (2, 18)),
              ("bert", 16, 12, 512, 64, (16, 272))]
    blocks = [256, 512, 1024] if quick else [128, 256, 512, 1024, 2048]
    rows = []
    for name, b, h, s, d, lens in shapes:
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
        k = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
        v = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
        scale = float(d) ** -0.5
        for causal in (False, True):
            # FLOPs: 2 matmuls of 2*s*s*d each per (b, h); causal halves
            flops = 4.0 * b * h * s * s * d * (0.5 if causal else 1.0)
            for bq in blocks:
                for bk in blocks:
                    if bq > s or bk > s:
                        continue
                    try:
                        def make(n, bq=bq, bk=bk, c=causal):
                            def chained(q, k, v):
                                # output feeds the next query: serial on
                                # the device stream, immune to CSE
                                def step(qc, _):
                                    o = _flash_bhsd(qc, k, v, c, scale,
                                                    bq, bk, False)
                                    return o.astype(qc.dtype), ()
                                qf, _ = lax.scan(step, q, None, length=n)
                                return jnp.sum(qf.astype(jnp.float32))
                            return chained

                        t_iter = delta_time(make, (q, k, v), *lens)
                        tf = flops / t_iter / 1e12
                        rows.append((name, causal, bq, bk, round(tf, 1)))
                        print(f"{name} causal={causal} bq={bq} bk={bk}: "
                              f"{tf:.1f} TFLOP/s", flush=True)
                    except Exception as e:  # noqa: BLE001
                        rows.append((name, causal, bq, bk,
                                     f"ERR {type(e).__name__}"))
                        print(f"{name} causal={causal} bq={bq} bk={bk}: "
                              f"ERROR {e}", flush=True)

    best = {}
    for name, causal, bq, bk, tf in rows:
        if isinstance(tf, float):
            key = (name, causal)
            if key not in best or tf > best[key][2]:
                best[key] = (bq, bk, tf)
    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    lines = [f"\n## Flash block sweep ({stamp}, "
             f"{getattr(dev, 'device_kind', dev.platform)}, "
             "scan-chained two-length delta timing)\n",
             f"- VPU probe (Gop/s): {json.dumps(vpu)}\n"]
    if ceiling is not None:
        lines.append(
            f"- measured-VPU roofline: d=128 attention ceiling ≈ "
            f"{ceiling:.2%} of MXU peak ({ceiling * 197:.0f} TFLOP/s) — "
            f"softmax VPU ops vs 4d MXU flops per score element\n")
    for (name, causal), (bq, bk, tf) in sorted(best.items()):
        lines.append(f"- {name} causal={causal}: best {tf} TFLOP/s at "
                     f"block_q={bq}, block_k={bk}\n")
    lines.append("- full grid: " + json.dumps(rows) + "\n")
    notes = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_NOTES.md")
    with open(notes, "a") as fh:
        fh.writelines(lines)
    print("".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
