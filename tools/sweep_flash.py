"""Flash-attention block-size sweep (r4, VERDICT #3).

Measures fwd TFLOP/s of ops/pallas/flash_attention._flash_bhsd across
(block_q, block_k) at the headline shape (16k seq, d=128, bf16) plus a
BERT-shaped case, dense and causal, and appends the table to
BENCH_NOTES.md. Run ON TPU:  python tools/sweep_flash.py [--quick]

Never kill this process mid-run (TPU claim wedge); it bounds its own
work and exits.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def vpu_probe(jax, jnp):
    """Measure the VPU's elementwise/transcendental throughput — the
    flash softmax (max, sub, exp2, sum, cast ≈ 6-8 VPU ops per score
    element) competes with the MXU dots (4·d flops per element). The
    attention ceiling is MXU_t / (MXU_t + VPU_t); whether 26% kernel
    efficiency at d=128 is a defect or the roofline depends entirely on
    the real VPU rate, so measure it."""
    import time as _t

    out = {}
    x = jnp.linspace(-4, 4, 4096 * 4096).reshape(4096, 4096)
    for name, dtype, fn in (
            ("exp2_f32", jnp.float32, lambda a: jnp.exp2(a)),
            ("exp2_bf16", jnp.bfloat16, lambda a: jnp.exp2(a)),
            ("addmul_f32", jnp.float32, lambda a: a * 1.5 + 0.5)):
        a = x.astype(dtype)
        f = jax.jit(fn)
        f(a).block_until_ready()
        t0 = _t.perf_counter()
        for _ in range(20):
            r = f(a)
        r.block_until_ready()
        dt = (_t.perf_counter() - t0) / 20
        out[name] = round(a.size / dt / 1e9, 1)  # Gop/s
    return out


def main():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.flash_attention import _flash_bhsd

    dev = jax.devices()[0]
    if dev.platform == "cpu":
        print(json.dumps({"ok": False, "error": "cpu backend"}))
        return 1
    quick = "--quick" in sys.argv

    vpu = vpu_probe(jax, jnp)
    print("VPU probe (Gop/s):", json.dumps(vpu), flush=True)
    # predicted attention ceiling at d=128, bf16 MXU 197 TF/s, ~7 VPU
    # ops per score element at the measured exp2-class rate
    try:
        vpu_rate = vpu["exp2_f32"] * 1e9
        mxu_t = 4 * 128 / 197e12
        vpu_t = 7 / vpu_rate
        ceiling = mxu_t / (mxu_t + vpu_t)
        print(f"predicted d=128 attention ceiling ≈ {ceiling:.2%} of MXU "
              f"peak ({ceiling * 197:.0f} TFLOP/s)", flush=True)
    except Exception:
        ceiling = None

    shapes = [("16k", 1, 4, 16384, 128), ("bert", 16, 12, 512, 64)]
    blocks = [256, 512, 1024] if quick else [128, 256, 512, 1024, 2048]
    rows = []
    for name, b, h, s, d in shapes:
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
        k = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
        v = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
        scale = float(d) ** -0.5
        for causal in (False, True):
            # FLOPs: 2 matmuls of 2*s*s*d each per (b, h); causal halves
            flops = 4.0 * b * h * s * s * d * (0.5 if causal else 1.0)
            for bq in blocks:
                for bk in blocks:
                    if bq > s or bk > s:
                        continue
                    try:
                        f = jax.jit(lambda q, k, v, bq=bq, bk=bk,
                                    c=causal: _flash_bhsd(
                                        q, k, v, c, scale, bq, bk, False))
                        f(q, k, v).block_until_ready()   # compile
                        iters = 5 if quick else 10
                        t0 = time.perf_counter()
                        for _ in range(iters):
                            out = f(q, k, v)
                        out.block_until_ready()
                        dt = (time.perf_counter() - t0) / iters
                        tf = flops / dt / 1e12
                        rows.append((name, causal, bq, bk, round(tf, 1)))
                        print(f"{name} causal={causal} bq={bq} bk={bk}: "
                              f"{tf:.1f} TFLOP/s", flush=True)
                    except Exception as e:  # noqa: BLE001
                        rows.append((name, causal, bq, bk,
                                     f"ERR {type(e).__name__}"))
                        print(f"{name} causal={causal} bq={bq} bk={bk}: "
                              f"ERROR {e}", flush=True)

    best = {}
    for name, causal, bq, bk, tf in rows:
        if isinstance(tf, float):
            key = (name, causal)
            if key not in best or tf > best[key][2]:
                best[key] = (bq, bk, tf)
    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    lines = [f"\n## Flash block sweep ({stamp}, "
             f"{getattr(dev, 'device_kind', dev.platform)})\n",
             f"- VPU probe (Gop/s): {json.dumps(vpu)}\n"]
    if ceiling is not None:
        lines.append(
            f"- measured-VPU roofline: d=128 attention ceiling ≈ "
            f"{ceiling:.2%} of MXU peak ({ceiling * 197:.0f} TFLOP/s) — "
            f"softmax VPU ops vs 4d MXU flops per score element\n")
    for (name, causal), (bq, bk, tf) in sorted(best.items()):
        lines.append(f"- {name} causal={causal}: best {tf} TFLOP/s at "
                     f"block_q={bq}, block_k={bk}\n")
    lines.append("- full grid: " + json.dumps(rows) + "\n")
    notes = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_NOTES.md")
    with open(notes, "a") as fh:
        fh.writelines(lines)
    print("".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
