"""Standalone TPU health probe: delegates to bench.py's probe() so the
device-init contract (one matmul, one JSON line, never kill a running
probe — a killed claim-holding python wedges the tunnel for hours) lives
in exactly one place.

Run detached; let it exit on its own.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if __name__ == "__main__":
    import json
    import time

    t0 = time.time()
    try:
        from bench import probe
        sys.exit(probe())
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}",
                          "t": round(time.time() - t0, 2)}), flush=True)
