"""Standalone TPU health probe. Prints one JSON line and exits.

Run detached; NEVER kill it — if the axon claim is wedged it will hang
until the relay releases, and killing it can wedge the claim further.
"""
import json, sys, time
t0 = time.time()
try:
    import jax, jax.numpy as jnp
    devs = jax.devices()
    x = jnp.ones((256, 256), jnp.bfloat16)
    y = (x @ x).block_until_ready()
    out = {"ok": True, "platform": devs[0].platform, "n": len(devs),
           "device": str(devs[0]), "t": round(time.time() - t0, 2)}
except Exception as e:  # noqa: BLE001
    out = {"ok": False, "error": f"{type(e).__name__}: {e}", "t": round(time.time() - t0, 2)}
print(json.dumps(out), flush=True)
