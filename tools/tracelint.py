#!/usr/bin/env python
"""tracelint CLI — trace-safety & TPU-compilability lint for paddle_tpu.

AST pass only (no jax import, no trace): fast enough to gate CI on CPU.

Usage:
  python tools/tracelint.py paddle_tpu examples        # report everything
  python tools/tracelint.py --check paddle_tpu examples  # vs baseline, CI gate
  python tools/tracelint.py --write-baseline paddle_tpu examples
  python tools/tracelint.py --json out.json examples
  python tools/tracelint.py --rules                    # rule catalogue

Exit codes: 0 clean, 1 findings (plain) / NEW findings vs baseline
(--check), 2 usage error.

Per-line suppression: `# tracelint: disable=TL101` — whole file:
`# tracelint: skip-file`.  The same comments silence shardlint's SLxxx
jaxpr findings at their resolved source lines (see tools/shardlint.py).
The checked-in baseline (tools/tracelint_baseline.json) holds reviewed
findings; `--check` reports only regressions beyond it.  The `--json`
report uses the same schema as shardlint's (analysis/report.to_json,
with a "tool" discriminator key).
"""
from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(1, os.path.join(REPO, "tools"))

DEFAULT_BASELINE = os.path.join(REPO, "tools", "tracelint_baseline.json")


def main(argv=None):
    # stdlib-only import path: the AST pass must not drag in jax
    from _bootstrap import light_paddle_tpu
    light_paddle_tpu(REPO)
    from paddle_tpu.analysis import common, lint_paths
    from paddle_tpu.analysis.rules import RULES

    ap = argparse.ArgumentParser(
        prog="tracelint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", help="files/directories to lint")
    common.add_baseline_args(ap, DEFAULT_BASELINE)
    ap.add_argument("--rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--no-source", action="store_true",
                    help="omit source lines from the text report")
    args = ap.parse_args(argv)

    if args.rules:
        # TL codes only: the SLxxx family shares the registry but is
        # checked by tools/shardlint.py (which has its own --rules)
        return common.print_rules(
            RULES, codes={c for c in RULES if c.startswith("TL")})
    if not args.paths:
        ap.print_usage()
        return 2

    t0 = time.time()
    findings = lint_paths(args.paths, base=REPO)
    elapsed = time.time() - t0

    return common.run_baseline_flow(
        findings, args, tool="tracelint", repo=REPO, elapsed=elapsed,
        show_source=not args.no_source)


if __name__ == "__main__":
    sys.exit(main())
