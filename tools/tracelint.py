#!/usr/bin/env python
"""tracelint CLI — trace-safety & TPU-compilability lint for paddle_tpu.

AST pass only (no jax import, no trace): fast enough to gate CI on CPU.

Usage:
  python tools/tracelint.py paddle_tpu examples        # report everything
  python tools/tracelint.py --check paddle_tpu examples  # vs baseline, CI gate
  python tools/tracelint.py --write-baseline paddle_tpu examples
  python tools/tracelint.py --json out.json examples
  python tools/tracelint.py --rules                    # rule catalogue

Exit codes: 0 clean, 1 findings (plain) / NEW findings vs baseline
(--check), 2 usage error.

Per-line suppression: `# tracelint: disable=TL101` — whole file:
`# tracelint: skip-file`.  The same comments silence shardlint's SLxxx
jaxpr findings at their resolved source lines (see tools/shardlint.py).
The checked-in baseline (tools/tracelint_baseline.json) holds reviewed
findings; `--check` reports only regressions beyond it.  The `--json`
report uses the same schema as shardlint's (analysis/report.to_json,
with a "tool" discriminator key).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "tools", "tracelint_baseline.json")


def _light_package():
    """Make `paddle_tpu.analysis` importable WITHOUT executing the real
    paddle_tpu/__init__.py (which imports jax): the AST pass is pure
    stdlib and the CLI must stay fast enough to gate CI on CPU.  No-op
    when paddle_tpu is already imported (e.g. under pytest)."""
    import types
    if "paddle_tpu" not in sys.modules:
        pkg = types.ModuleType("paddle_tpu")
        pkg.__path__ = [os.path.join(REPO, "paddle_tpu")]
        sys.modules["paddle_tpu"] = pkg


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tracelint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", help="files/directories to lint")
    ap.add_argument("--check", action="store_true",
                    help="compare against the baseline; fail only on NEW "
                         "findings")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"baseline file (default {DEFAULT_BASELINE})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings as the new baseline")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="also write findings as JSON ('-' for stdout)")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--no-source", action="store_true",
                    help="omit source lines from the text report")
    args = ap.parse_args(argv)

    # stdlib-only import path: the AST pass must not drag in jax
    _light_package()
    from paddle_tpu.analysis import lint_paths, report
    from paddle_tpu.analysis.rules import RULES

    if args.rules:
        # TL codes only: the SLxxx family shares the registry but is
        # checked by tools/shardlint.py (which has its own --rules)
        for r in RULES.values():
            if not r.code.startswith("TL"):
                continue
            print(f"{r.code}  {r.name}")
            print(f"    {r.message.format(detail='')}")
            print(f"    why: {r.rationale}")
            print(f"    fix: {r.fixit}")
        return 0
    if not args.paths:
        ap.print_usage()
        return 2

    t0 = time.time()
    findings = lint_paths(args.paths, base=REPO)
    elapsed = time.time() - t0

    if args.write_baseline:
        report.write_baseline(findings, args.baseline)
        print(f"wrote baseline: {len(findings)} finding(s) -> "
              f"{os.path.relpath(args.baseline, REPO)}")
        return 0

    shown = findings
    note = ""
    if args.check:
        baseline = report.load_baseline(args.baseline)
        shown = report.diff_vs_baseline(findings, baseline)
        note = (f" ({len(findings)} total, "
                f"{len(findings) - len(shown)} baselined)")

    if shown:
        print(report.format_text(shown, show_source=not args.no_source))
    print(f"tracelint: {len(shown)} finding(s){note} "
          f"[{report.summarize(shown)}] in {elapsed:.2f}s")

    if args.json:
        doc = report.to_json(shown, extra={"tool": "tracelint",
                                           "elapsed_s": round(elapsed, 3)})
        if args.json == "-":
            json.dump(doc, sys.stdout, indent=1)
            print()
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1)
                fh.write("\n")
    return 1 if shown else 0


if __name__ == "__main__":
    sys.exit(main())
