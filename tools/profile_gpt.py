"""Flagship GPT train step on real TPU: single-chip throughput + MFU.

Complements bench.py's ResNet/BERT headlines with the GPT family the
BASELINE.json Fleet configs center on. Default config is a ~350M-param
GPT (hidden 1024, 24 layers) at seq 2048 with recompute — the largest
that fits v5e HBM (16 GB) comfortably with AdamW fp32 states.

Run ON TPU (never kill it mid-run):
  python tools/profile_gpt.py [--hidden 1024] [--layers 24]
      [--batch 4] [--seq 2048] [--iters 6]

GPT-3 1.3B (BASELINE configs[3], hidden 2048 / 24 layers / seq 2048) on
ONE 16 GB v5e needs the fit levers the pod-mesh reference gets from
sharding stage2/3: bf16 params + bf16 Adam moments + remat + the
chunked fused LM-head CE (no [b,s,V] logits) + donation:
  python tools/profile_gpt.py --preset 1p3b [--batch 8]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--layers", type=int, default=24)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--iters", type=int, default=6)
    ap.add_argument("--no-recompute", action="store_true")
    ap.add_argument("--fused-head", action="store_true",
                    help="chunked fused LM-head+CE: no [b,s,V] logits")
    ap.add_argument("--param-dtype", default=None,
                    help="cast model params (e.g. bfloat16)")
    ap.add_argument("--moment-dtype", default=None,
                    help="Adam moment storage dtype (e.g. bfloat16)")
    ap.add_argument("--preset", default=None, choices=[None, "1p3b"],
                    help="1p3b = GPT-3 1.3B single-chip fit recipe")
    ap.add_argument("--ce-chunk", type=int, default=8192,
                    help="fused LM-head CE chunk size (memory/occupancy "
                         "tradeoff: smaller = less transient HBM)")
    args = ap.parse_args()
    if args.preset == "1p3b":
        args.hidden, args.layers, args.heads = 2048, 24, 16
        args.seq = 2048
        args.fused_head = True
        args.param_dtype = args.param_dtype or "bfloat16"
        args.moment_dtype = args.moment_dtype or "bfloat16"

    import jax

    import paddle_tpu as P
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

    dev = jax.devices()[0]
    print(f"device: {dev.platform} {getattr(dev, 'device_kind', '')}",
          flush=True)

    P.seed(0)
    cfg = GPTConfig(vocab_size=50304, hidden_size=args.hidden,
                    num_layers=args.layers, num_heads=args.heads,
                    max_seq_len=args.seq, dropout=0.0,
                    attention_dropout=0.0,
                    use_recompute=not args.no_recompute)
    model = GPTForCausalLM(cfg)
    if args.param_dtype:
        model.to(dtype=args.param_dtype)
    crit = GPTPretrainingCriterion()
    opt = P.optimizer.AdamW(learning_rate=1e-4,
                            parameters=model.parameters(),
                            moment_dtype=args.moment_dtype)
    n_params = sum(int(np.prod(q.shape)) for q in model.parameters())
    print(f"params: {n_params/1e6:.1f}M", flush=True)

    @P.jit.to_static
    def train_step(ids, labels):
        opt.clear_grad()
        with P.amp.auto_cast(level="O1", dtype="bfloat16"):
            if args.fused_head:
                loss = model.loss_with_fused_head(
                    ids, labels, chunk_size=args.ce_chunk)
            else:
                logits = model(ids)
                loss = crit(logits, labels)
        loss.backward()
        opt.step()
        return loss

    rng = np.random.default_rng(0)
    ids = P.to_tensor(rng.integers(0, cfg.vocab_size,
                                   (args.batch, args.seq)), dtype="int64")
    labels = P.to_tensor(rng.integers(0, cfg.vocab_size,
                                      (args.batch, args.seq)),
                         dtype="int64")

    t0 = time.time()
    loss = train_step(ids, labels)
    loss.block_until_ready()
    print(f"compile+first step {time.time()-t0:.1f}s "
          f"loss={float(loss.numpy()):.3f}", flush=True)

    t0 = time.perf_counter()
    for _ in range(args.iters):
        loss = train_step(ids, labels)
    loss.block_until_ready()   # steps chain through optimizer state
    dt = (time.perf_counter() - t0) / args.iters

    tokens = args.batch * args.seq
    tok_s = tokens / dt
    # PaLM-style accounting: 6N matmul flops/token (fwd+bwd) plus causal
    # attention 6*L*h*s flops/token (dense would be 12*L*h*s; causal
    # halves it). Recompute re-runs the fwd, so HARDWARE flops are ~33%
    # higher — this reports MODEL mfu (useful work), like the bench.
    flops_per_token = 6.0 * n_params + \
        6.0 * args.layers * args.hidden * args.seq
    mfu = tok_s * flops_per_token / 197e12
    out = {"metric": "gpt_train_tokens_s", "value": round(tok_s, 1),
           "unit": "tokens/sec/chip", "platform": dev.platform,
           "params_m": round(n_params / 1e6, 1),
           "batch": args.batch, "seq": args.seq,
           "ms_per_step": round(dt * 1e3, 1),
           "recompute": cfg.use_recompute,
           "fused_head": bool(args.fused_head),
           "param_dtype": args.param_dtype or "float32",
           "moment_dtype": args.moment_dtype or "float32",
           "ce_chunk": args.ce_chunk if args.fused_head else None,
           "flops_per_token_g": round(flops_per_token / 1e9, 2),
           "mfu": round(mfu, 4)}
    print(json.dumps(out), flush=True)
    notes = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_NOTES.md")
    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    with open(notes, "a") as fh:
        fh.write(f"\n- tools/profile_gpt.py {stamp}: `{json.dumps(out)}`\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
