"""ViT-B/16 train step on real TPU: throughput + MFU.

Completes the BASELINE configs[1] lane ("PaddleClas ResNet-50 / ViT-B
(to_static whole-graph -> XLA)") — bench.py owns the ResNet half; this
is the ViT half. bf16 autocast, to_static whole-graph compile,
cost-analysis-backed MFU.

Run ON TPU (never kill it mid-run):
  python tools/profile_vit.py [--batch 128] [--iters 8]
Tiny CPU smoke:
  python tools/profile_vit.py --tiny --iters 1
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_PEAK_TFLOPS = {"TPU v4": 275.0, "TPU v5 lite": 197.0, "TPU v5e": 197.0,
                "TPU v5p": 459.0, "TPU v6 lite": 918.0, "TPU v6e": 918.0}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--tiny", action="store_true",
                    help="tiny config smoke (CPU)")
    args = ap.parse_args()

    import jax

    import paddle_tpu as P
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models.vit import (VisionTransformer, ViTConfig,
                                      vit_b_16)

    dev = jax.devices()[0]
    print(f"device: {dev.platform} {getattr(dev, 'device_kind', '')}",
          flush=True)

    P.seed(0)
    if args.tiny:
        cfg = ViTConfig(image_size=32, patch_size=8, hidden_size=64,
                        num_layers=2, num_heads=4, num_classes=10,
                        dropout=0.0, attention_dropout=0.0)
        args.batch = min(args.batch, 4)
    else:
        cfg = vit_b_16(dropout=0.0, attention_dropout=0.0)
    model = VisionTransformer(cfg)
    opt = P.optimizer.AdamW(learning_rate=1e-4,
                            parameters=model.parameters())
    n_params = sum(int(np.prod(q.shape)) for q in model.parameters())
    print(f"params: {n_params/1e6:.1f}M", flush=True)

    @P.jit.to_static
    def train_step(x, y):
        opt.clear_grad()
        with P.amp.auto_cast(level="O1", dtype="bfloat16"):
            logits = model(x)
        loss = F.cross_entropy(logits, y)
        loss.backward()
        opt.step()
        return loss

    rng = np.random.default_rng(0)
    x = P.to_tensor(rng.standard_normal(
        (args.batch, cfg.in_channels, cfg.image_size,
         cfg.image_size)).astype(np.float32))
    y = P.to_tensor(rng.integers(0, cfg.num_classes, (args.batch,)),
                    dtype="int64")

    t0 = time.time()
    loss = train_step(x, y)
    loss.block_until_ready()
    print(f"compile+first step {time.time()-t0:.1f}s "
          f"loss={float(loss.numpy()):.3f}", flush=True)

    t0 = time.perf_counter()
    for _ in range(args.iters):
        loss = train_step(x, y)
    loss.block_until_ready()       # steps chain through optimizer state
    dt = (time.perf_counter() - t0) / args.iters
    img_s = args.batch / dt

    extra = {}
    try:
        entry = next(iter(train_step._compiled.values()))
        cost = entry.jitted.lower(
            [t._value for t in entry.state_list],
            [x._value, y._value]).compile().cost_analysis()
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        fpi = cost["flops"] / args.batch
        extra["xla_flops_per_img_g"] = round(fpi / 1e9, 2)
        if dev.platform != "cpu":
            peak = next((v for k, v in _PEAK_TFLOPS.items()
                         if k in getattr(dev, "device_kind", "")), 197.0)
            extra["mfu"] = round(img_s * fpi / (peak * 1e12), 4)
    except Exception:
        pass

    out = {"metric": "vit_b16_train_throughput", "value": round(img_s, 2),
           "unit": "images/sec/chip", "platform": dev.platform,
           "params_m": round(n_params / 1e6, 1), "batch": args.batch,
           "ms_per_step": round(dt * 1e3, 1), **extra}
    print(json.dumps(out), flush=True)
    if dev.platform != "cpu":
        notes = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_NOTES.md")
        stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
        with open(notes, "a") as fh:
            fh.write(f"\n- tools/profile_vit.py {stamp}: "
                     f"`{json.dumps(out)}`\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
