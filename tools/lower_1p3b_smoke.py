"""TPU-lowering smoke of the 1.3B-shaped GPT train step on the CPU host:
2 layers at full width (hidden 2048, seq 2048, 50304 vocab, bf16 params,
bf16 moments, remat, fused chunked CE) exported for platform=tpu — the
wedge-safe pre-check before the watcher runs the 24-layer compile on
silicon."""
import numpy as np
import jax
from jax import export

import paddle_tpu as P
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

P.seed(0)
cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=2,
                num_heads=16, max_seq_len=2048, dropout=0.0,
                attention_dropout=0.0, use_recompute=True)
model = GPTForCausalLM(cfg)
model.to(dtype="bfloat16")
opt = P.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                        moment_dtype="bfloat16")

@P.jit.to_static
def train_step(ids, labels):
    opt.clear_grad()
    with P.amp.auto_cast(level="O1", dtype="bfloat16"):
        loss = model.loss_with_fused_head(ids, labels)
    loss.backward()
    opt.step()
    return loss

rng = np.random.default_rng(0)
ids = P.to_tensor(rng.integers(0, cfg.vocab_size, (4, 2048)), dtype="int64")
labels = P.to_tensor(rng.integers(0, cfg.vocab_size, (4, 2048)), dtype="int64")

# trace WITHOUT executing: reach the pure fn via a discovery lower, then
# export for tpu
train_step(ids, labels)   # cpu compile+run once (also numerics sanity)
entry = next(iter(train_step._compiled.values()))
print("cpu step ran; loss finite:", True)

exp = export.export(entry.jitted, platforms=["tpu"])(
    [t._value for t in entry.state_list], [ids._value, labels._value])
txt = exp.mlir_module()
print("TPU lowering OK — mlir bytes:", len(txt))
print("has flash kernel:", "tpu_custom_call" in txt)
