#!/usr/bin/env python
"""obs_report — render paddle_tpu.observability telemetry for humans.

Reads a JSONL dump written by ``observability.export.dump_jsonl`` (or
captures one live with ``--demo``) and renders:

- the RECOMPILE LOG: every compile event with its attribution — which
  argument's shape/dtype/static leaf (or the state registry) changed,
  and the wall-clock trace + compile cost;
- the SPAN TIMELINE: the ring buffer of nested trace spans, indented by
  nesting depth, with durations;
- the METRICS snapshot: every Counter/Gauge/Histogram in the registry.

Usage:
  python tools/obs_report.py obs.jsonl           # render a dump
  python tools/obs_report.py --demo              # gpt-hybrid forced-
                                                 # retrace demo, live
  python tools/obs_report.py obs.jsonl --json -  # machine-readable
  python tools/obs_report.py --demo --prom       # Prometheus text

The demo compiles the tiny-config GPT hybrid train step, perturbs ONE
input's shape to force a retrace, and shows the resulting recompile
event naming the perturbed argument — the "why did this recompile"
workflow end to end (CPU-only; never touches a TPU claim).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


# ------------------------------------------------------------------ demo
def run_demo():
    """Forced retrace of the gpt hybrid train step: perturb one input
    shape, leave every other argument alone."""
    import numpy as np

    import paddle_tpu as P
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny

    P.seed(0)
    cfg = gpt3_tiny()
    model = GPTForCausalLM(cfg)
    opt = P.optimizer.AdamW(learning_rate=1e-4,
                            parameters=model.parameters())

    # ONE tensor input: next-token labels are derived from `ids` by
    # shifting inside the step, so perturbing the input shape names
    # exactly one argument in the recompile attribution
    @P.jit.to_static
    def train_step(ids):
        opt.clear_grad()
        logits = model(ids)
        loss = F.cross_entropy(
            logits[:, :-1].reshape([-1, cfg.vocab_size]),
            ids[:, 1:].reshape([-1]))
        loss.backward()
        opt.step()
        return loss

    rng = np.random.default_rng(0)
    ids = P.to_tensor(rng.integers(0, cfg.vocab_size, (2, 32)),
                      dtype="int64")
    train_step(ids)                               # first compile
    train_step(ids)                               # cache hit
    # perturb the ONE argument's shape: seq len 32 -> 48
    ids_wide = P.to_tensor(rng.integers(0, cfg.vocab_size, (2, 48)),
                           dtype="int64")
    train_step(ids_wide)                          # forced retrace


def live_doc():
    from paddle_tpu import observability as obs
    return {
        "meta": {"version": 1, "capture": "live"},
        "spans": [s.to_dict() for s in obs.recorder().spans()],
        "recompiles": [e.to_dict()
                       for e in obs.recompile_log().events()],
        "metrics": [
            {"name": m.name, "type": m.kind, "labels": m.labels,
             "value": (m.summary() if m.kind == "histogram" else m.value)}
            for m in obs.registry().collect()],
    }


# ---------------------------------------------------------------- render
def render_recompiles(recompiles, limit):
    print(f"== recompile log ({len(recompiles)} events) " + "=" * 24)
    if not recompiles:
        print("  (no compile events recorded)")
    for e in recompiles[-limit:]:
        timing = []
        if e.get("trace_ms") is not None:
            timing.append(f"trace {e['trace_ms']:.0f}ms")
        if e.get("compile_ms") is not None:
            timing.append(f"compile {e['compile_ms']:.0f}ms")
        print(f"  #{e['seq']:<3d} [{e['kind']}] {e['fn']}: {e['cause']}"
              + (f"  ({', '.join(timing)})" if timing else ""))
        for c in e.get("changes", []):
            print(f"        {c['arg']}: {c['kind']} "
                  f"{c['before']} -> {c['after']}")
    print()


def render_spans(spans, limit):
    print(f"== span timeline (last {min(limit, len(spans))} of "
          f"{len(spans)} buffered) " + "=" * 12)
    if not spans:
        print("  (no spans recorded)")
    shown = sorted(spans, key=lambda s: s["start_ns"])[-limit:]
    t0 = shown[0]["start_ns"] if shown else 0
    for s in shown:
        indent = "  " * s.get("depth", 0)
        attrs = s.get("attrs") or {}
        attr_s = ("  " + " ".join(f"{k}={v}" for k, v in attrs.items())
                  if attrs else "")
        print(f"  +{(s['start_ns'] - t0) / 1e6:9.3f}ms "
              f"{indent}{s['name']:<32s} {s['dur_ns'] / 1e6:9.3f} ms"
              f"{attr_s}")
    print()


def render_metrics(metric_rows):
    print(f"== metrics ({len(metric_rows)}) " + "=" * 34)
    for m in metric_rows:
        label = "" if not m.get("labels") else "{" + ",".join(
            f"{k}={v}" for k, v in sorted(m["labels"].items())) + "}"
        v = m["value"]
        if isinstance(v, dict):
            v = " ".join(f"{k}={x}" for k, x in v.items())
        print(f"  {m['type']:<9s} {m['name']}{label} = {v}")
    print()


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="obs_report", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("dump", nargs="?", default=None,
                    help="JSONL file from observability.export.dump_jsonl")
    ap.add_argument("--demo", action="store_true",
                    help="run the gpt-hybrid forced-retrace demo and "
                         "report its live telemetry (CPU-only)")
    ap.add_argument("--limit", type=int, default=40,
                    help="max spans/events to render (default 40)")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="also write the report as JSON ('-' = stdout)")
    ap.add_argument("--prom", action="store_true",
                    help="print the Prometheus text exposition instead")
    args = ap.parse_args(argv)

    if args.demo:
        run_demo()
        doc = live_doc()
    elif args.dump:
        from paddle_tpu.observability import export
        doc = export.load_jsonl(args.dump)
    else:
        ap.error("give a JSONL dump path or --demo")

    if args.prom:
        if args.dump and not args.demo:
            print("obs_report: --prom renders the LIVE registry; "
                  "combine it with --demo", file=sys.stderr)
            return 2
        from paddle_tpu.observability import export
        sys.stdout.write(export.prometheus_text())
        return 0

    render_recompiles(doc.get("recompiles", []), args.limit)
    render_spans(doc.get("spans", []), args.limit)
    render_metrics(doc.get("metrics", []))

    if args.json:
        payload = json.dumps(doc, indent=1, sort_keys=True)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
