#!/usr/bin/env python
"""obs_report — render paddle_tpu.observability telemetry for humans.

Reads a JSONL dump written by ``observability.export.dump_jsonl`` (or
captures one live with ``--demo``) and renders:

- the RECOMPILE LOG: every compile event with its attribution — which
  argument's shape/dtype/static leaf (or the state registry) changed,
  and the wall-clock trace + compile cost;
- the SPAN TIMELINE: the ring buffer of nested trace spans, indented by
  nesting depth, with durations;
- the METRICS snapshot: every Counter/Gauge/Histogram in the registry.

With ``--roofline`` it instead renders RooflineReport records
(observability.profile): the per-layer bytes/flops attribution table
sorted by bytes, with compute- vs memory-bound classification — from a
JSONL dump's ``roofline`` records, or captured live from the gpt
hybrid train target with ``--demo`` (traces, runs two steps for the
measured span time, and reconciles predicted vs measured).

With ``--fleet <spool_dir>`` it instead merges every per-rank
telemetry spool (observability.fleettrace) into one fleet view: the
per-process inventory on aligned clocks, per-request distributed
timelines with the TTFT stage decomposition (``--request <id>``
focuses one request by router rid / engine rid / trace id), the
rank-labeled merged metrics exposition (``--prom``), and a merged
Chrome trace (``--trace FILE``).

Usage:
  python tools/obs_report.py obs.jsonl           # render a dump
  python tools/obs_report.py --demo              # gpt-hybrid forced-
                                                 # retrace demo, live
  python tools/obs_report.py obs.jsonl --json -  # machine-readable
  python tools/obs_report.py --demo --prom       # Prometheus text
  python tools/obs_report.py --demo --roofline   # live roofline table
  python tools/obs_report.py obs.jsonl --roofline  # from dump records
  python tools/obs_report.py obs.jsonl --capacity  # CapacityReport
                                                 # tables from a dump
  python tools/obs_report.py --fleet spools/     # merged fleet view
  python tools/obs_report.py --fleet spools/ --request rr-3
  python tools/obs_report.py --fleet spools/ --trace fleet.json

The demo compiles the tiny-config GPT hybrid train step, perturbs ONE
input's shape to force a retrace, and shows the resulting recompile
event naming the perturbed argument — the "why did this recompile"
workflow end to end (CPU-only; never touches a TPU claim).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


# ------------------------------------------------------------------ demo
def run_demo():
    """Forced retrace of the gpt hybrid train step: perturb one input
    shape, leave every other argument alone."""
    import numpy as np

    import paddle_tpu as P
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny

    P.seed(0)
    cfg = gpt3_tiny()
    model = GPTForCausalLM(cfg)
    opt = P.optimizer.AdamW(learning_rate=1e-4,
                            parameters=model.parameters())

    # ONE tensor input: next-token labels are derived from `ids` by
    # shifting inside the step, so perturbing the input shape names
    # exactly one argument in the recompile attribution
    @P.jit.to_static
    def train_step(ids):
        opt.clear_grad()
        logits = model(ids)
        loss = F.cross_entropy(
            logits[:, :-1].reshape([-1, cfg.vocab_size]),
            ids[:, 1:].reshape([-1]))
        loss.backward()
        opt.step()
        return loss

    rng = np.random.default_rng(0)
    ids = P.to_tensor(rng.integers(0, cfg.vocab_size, (2, 32)),
                      dtype="int64")
    train_step(ids)                               # first compile
    train_step(ids)                               # cache hit
    # perturb the ONE argument's shape: seq len 32 -> 48
    ids_wide = P.to_tensor(rng.integers(0, cfg.vocab_size, (2, 48)),
                           dtype="int64")
    train_step(ids_wide)                          # forced retrace


def live_roofline():
    """Roofline-profile the gpt hybrid train target live: trace for the
    cost model, run two real steps so the span layer has a measured
    wall time, reconcile the two in one report."""
    import perfgate  # sibling tools/ module (sys.path[0] is tools/)

    from paddle_tpu.observability import profile

    train_step, ids, labels = perfgate.build_gpt_train_step()
    train_step(ids, labels)                 # compile + step 1
    train_step(ids, labels)                 # warm step 2
    jaxpr, _ = train_step.traced_program(ids, labels)
    report = profile.profile_traced(jaxpr, where="<gpt_hybrid_train>",
                                    include_interiors=True)
    return profile.reconcile(report, "jit.train_step")


def render_rooflines(reports):
    for d in reports:
        chip = d.get("chip", {})
        print(f"== roofline {d.get('where', '?')} — chip "
              f"{chip.get('name', '?')} ({chip.get('peak_tflops', '?')} "
              f"TF/s, {chip.get('hbm_gbs', '?')} GB/s, ridge "
              f"{chip.get('ridge_flop_per_byte', '?')} flop/B) " + "=" * 8)
        total_b = d.get("total_bytes") or 1
        print(f"  {'layer':<52s} {'KiB':>10s} {'MFLOP':>9s} "
              f"{'flop/B':>7s} {'bound':>8s} {'%bytes':>7s}")
        for row in d.get("layers", []):
            print(f"  {row['name'][:52]:<52s} "
                  f"{row['bytes'] / 1024:>10.1f} "
                  f"{row['flops'] / 1e6:>9.3f} "
                  f"{row.get('intensity', 0):>7.2f} "
                  f"{row.get('bound', '?'):>8s} "
                  f"{100.0 * row['bytes'] / total_b:>6.1f}%")
        line = (f"  total {d['total_bytes'] / 1024:.1f} KiB, "
                f"{d['total_flops'] / 1e6:.3f} MFLOP; attributed "
                f"{d.get('attributed_bytes_pct', '?')}% bytes / "
                f"{d.get('attributed_flops_pct', '?')}% flops; "
                f"memory-bound fraction {d.get('bound_fraction', '?')}; "
                f"predicted {d.get('predicted_ms', 0):.4f} ms")
        if d.get("measured_ms") is not None:
            line += (f"; measured {d['measured_ms']} ms "
                     f"({d.get('measured_source', '')}) — on a CPU host "
                     f"the ratio is diagnostic only")
        print(line)
        if d.get("xla"):
            print(f"  xla cost_analysis: flops {d['xla']['flops']:.4g}, "
                  f"bytes accessed {d['xla']['bytes_accessed']:.4g}")
        if d.get("interiors"):
            print(f"  -- kernel interiors (per-grid-step VMEM traffic "
                  f"vs the call-boundary row) --")
            print(f"  {'kernel':<28s} {'grid':>6s} {'KiB/step':>9s} "
                  f"{'MFLOP':>9s} {'flop/B':>7s} {'bound':>8s} "
                  f"{'reuse':>6s} {'VMEM KiB':>9s}")
            for k in d["interiors"]:
                print(f"  {k['kernel'][:28]:<28s} "
                      f"{k['grid_steps']:>6d} "
                      f"{k['vmem_step_bytes'] / 1024:>9.1f} "
                      f"{k['flops'] / 1e6:>9.3f} "
                      f"{k.get('interior_intensity', 0):>7.2f} "
                      f"{k.get('bound', '?'):>8s} "
                      f"{k.get('reuse_factor', 0):>5.1f}x "
                      f"{k.get('vmem_total_bytes', 0) / 1024:>9.1f}")
        print()


def live_doc():
    from paddle_tpu import observability as obs
    return {
        "meta": {"version": 1, "capture": "live"},
        "spans": [s.to_dict() for s in obs.recorder().spans()],
        "recompiles": [e.to_dict()
                       for e in obs.recompile_log().events()],
        "metrics": [
            {"name": m.name, "type": m.kind, "labels": m.labels,
             "value": (m.summary() if m.kind == "histogram" else m.value)}
            for m in obs.registry().collect()],
    }


# ----------------------------------------------------------------- fleet
def render_fleet(tel, limit):
    s = tel.summary()
    print(f"== fleet telemetry ({s['processes']} processes, ranks "
          f"{s['ranks']}) " + "=" * 12)
    print(f"  spans {s['spans']}  recompiles {s['recompiles']}  "
          f"metric snapshots {s['metric_snapshots']}  torn lines "
          f"{s['torn_lines']}")
    print(f"  traces {s['traces']}  ref rank {s['ref_rank']}  "
          f"clock skew bound {s['clock_skew_ms']} ms")
    for p in tel.processes:
        off = "?" if p.clock is None else f"{p.offset_ns / 1e6:+.3f}"
        print(f"  {p.label:<24s} {len(p.spans):>6d} spans  "
              f"{len(p.recompiles):>3d} recompiles  "
              f"{len(p.metrics):>3d} snapshots  offset {off} ms"
              + (f"  [{p.torn_lines} torn]" if p.torn_lines else ""))
    print()


def render_timeline(tl, limit):
    print(f"== request {tl['request']} (trace {tl['trace']}) " + "=" * 8)
    print(f"  complete={tl['complete']}  admissions={tl['admissions']}"
          f"  finishes={tl['finishes']}  migrations={tl['migrations']}"
          f"  handoffs={tl['handoffs']}  processes={tl['processes']}")
    for k in ("queue_wait_s", "prefill_s", "handoff_s", "adoption_s",
              "decode_s", "total_s"):
        if k in tl["stages"]:
            print(f"  {k:<13s} {tl['stages'][k] * 1e3:10.3f} ms")
    spans = tl["spans"][:limit]
    t0 = spans[0]["start_ns"] if spans else 0
    for e in spans:
        attrs = e.get("attrs") or {}
        attr_s = ("  " + " ".join(f"{k}={v}"
                                  for k, v in sorted(attrs.items()))
                  if attrs else "")
        print(f"  +{(e['start_ns'] - t0) / 1e6:9.3f}ms "
              f"r{e['rank'] if e['rank'] is not None else '?'} "
              f"{e['name']:<28s} {e['dur_ns'] / 1e6:9.3f} ms{attr_s}")
    print()


def run_fleet(args, ap):
    from paddle_tpu.observability import fleettrace
    if not os.path.isdir(args.fleet):
        ap.error(f"--fleet: {args.fleet} is not a directory")
    tel = fleettrace.merge_spools(args.fleet)
    if not tel.processes:
        print(f"obs_report: no spool-*.jsonl files in {args.fleet}",
              file=sys.stderr)
        return 1
    if args.prom:
        sys.stdout.write(tel.prometheus_text())
        return 0
    render_fleet(tel, args.limit)
    timelines = []
    if args.request:
        tl = tel.timeline(args.request)
        if tl is None:
            print(f"obs_report: no trace for request {args.request!r} "
                  f"in {args.fleet}", file=sys.stderr)
            return 1
        timelines = [tl]
    else:
        # no --request: render every complete distributed timeline
        # (bounded by --limit), most-travelled first
        tls = [tel.timeline(t) for t in tel.traces()]
        tls = [t for t in tls if t and t["complete"]]
        tls.sort(key=lambda t: (-t["migrations"], str(t["request"])))
        timelines = tls[:max(1, args.limit // 8)]
    for tl in timelines:
        render_timeline(tl, args.limit)
    if args.trace:
        tel.write_chrome_trace(args.trace)
        print(f"merged chrome trace -> {args.trace}")
    if args.json:
        payload = json.dumps(
            {"summary": tel.summary(), "timelines": timelines,
             "recompiles_by_rank": tel.recompiles_by_rank()},
            indent=1, sort_keys=True, default=str)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    return 0


# ---------------------------------------------------------------- render
def render_recompiles(recompiles, limit):
    print(f"== recompile log ({len(recompiles)} events) " + "=" * 24)
    if not recompiles:
        print("  (no compile events recorded)")
    for e in recompiles[-limit:]:
        timing = []
        if e.get("trace_ms") is not None:
            timing.append(f"trace {e['trace_ms']:.0f}ms")
        if e.get("compile_ms") is not None:
            timing.append(f"compile {e['compile_ms']:.0f}ms")
        print(f"  #{e['seq']:<3d} [{e['kind']}] {e['fn']}: {e['cause']}"
              + (f"  ({', '.join(timing)})" if timing else ""))
        for c in e.get("changes", []):
            print(f"        {c['arg']}: {c['kind']} "
                  f"{c['before']} -> {c['after']}")
    print()


def render_spans(spans, limit):
    print(f"== span timeline (last {min(limit, len(spans))} of "
          f"{len(spans)} buffered) " + "=" * 12)
    if not spans:
        print("  (no spans recorded)")
    shown = sorted(spans, key=lambda s: s["start_ns"])[-limit:]
    t0 = shown[0]["start_ns"] if shown else 0
    for s in shown:
        indent = "  " * s.get("depth", 0)
        attrs = s.get("attrs") or {}
        attr_s = ("  " + " ".join(f"{k}={v}" for k, v in attrs.items())
                  if attrs else "")
        print(f"  +{(s['start_ns'] - t0) / 1e6:9.3f}ms "
              f"{indent}{s['name']:<32s} {s['dur_ns'] / 1e6:9.3f} ms"
              f"{attr_s}")
    print()


def render_metrics(metric_rows):
    print(f"== metrics ({len(metric_rows)}) " + "=" * 34)
    for m in metric_rows:
        label = "" if not m.get("labels") else "{" + ",".join(
            f"{k}={v}" for k, v in sorted(m["labels"].items())) + "}"
        v = m["value"]
        if isinstance(v, dict):
            v = " ".join(f"{k}={x}" for k, x in v.items())
        print(f"  {m['type']:<9s} {m['name']}{label} = {v}")
    print()


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="obs_report", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("dump", nargs="?", default=None,
                    help="JSONL file from observability.export.dump_jsonl")
    ap.add_argument("--demo", action="store_true",
                    help="run the gpt-hybrid forced-retrace demo and "
                         "report its live telemetry (CPU-only)")
    ap.add_argument("--limit", type=int, default=40,
                    help="max spans/events to render (default 40)")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="also write the report as JSON ('-' = stdout)")
    ap.add_argument("--prom", action="store_true",
                    help="print the Prometheus text exposition instead")
    ap.add_argument("--roofline", action="store_true",
                    help="render roofline reports (per-layer bytes/flops "
                         "attribution) instead: from the dump's roofline "
                         "records, or live from the gpt target with --demo")
    ap.add_argument("--capacity", action="store_true",
                    help="render serving CapacityReport tables (max "
                         "sustained QPS at the TTFT SLO per replica "
                         "count) from the dump's capacity records "
                         "(dump_jsonl(..., capacities=[report]))")
    ap.add_argument("--fleet", metavar="SPOOL_DIR", default=None,
                    help="merge per-rank telemetry spools "
                         "(PTPU_OBS_SPOOL_DIR) into one fleet view")
    ap.add_argument("--request", metavar="ID", default=None,
                    help="with --fleet: focus one request's distributed "
                         "timeline (router rid, engine rid, or trace id)")
    ap.add_argument("--trace", metavar="FILE", default=None,
                    help="with --fleet: write the merged multi-process "
                         "Chrome trace here")
    args = ap.parse_args(argv)

    if args.fleet:
        return run_fleet(args, ap)

    if args.capacity:
        if not args.dump:
            ap.error("--capacity needs a JSONL dump path")
        from paddle_tpu.observability import export
        from paddle_tpu.serving.traffic import CapacityReport
        reports = export.load_jsonl(args.dump).get("capacities", [])
        if not reports:
            print(f"obs_report: no capacity records in {args.dump} "
                  f"(dump_jsonl(..., capacities=[report]) writes them)",
                  file=sys.stderr)
            return 1
        for d in reports:
            print(CapacityReport.from_dict(d).render())
            print()
        if args.json:
            payload = json.dumps({"capacities": reports}, indent=1,
                                 sort_keys=True)
            if args.json == "-":
                print(payload)
            else:
                with open(args.json, "w", encoding="utf-8") as fh:
                    fh.write(payload + "\n")
        return 0

    if args.roofline:
        if args.demo:
            reports = [live_roofline().to_dict()]
        elif args.dump:
            from paddle_tpu.observability import export
            reports = export.load_jsonl(args.dump).get("rooflines", [])
            if not reports:
                print(f"obs_report: no roofline records in {args.dump} "
                      f"(dump_jsonl(..., rooflines=[report]) writes them)",
                      file=sys.stderr)
                return 1
        else:
            ap.error("--roofline needs a JSONL dump path or --demo")
        render_rooflines(reports)
        if args.json:
            payload = json.dumps({"rooflines": reports}, indent=1,
                                 sort_keys=True)
            if args.json == "-":
                print(payload)
            else:
                with open(args.json, "w", encoding="utf-8") as fh:
                    fh.write(payload + "\n")
        return 0

    if args.demo:
        run_demo()
        doc = live_doc()
    elif args.dump:
        from paddle_tpu.observability import export
        doc = export.load_jsonl(args.dump)
    else:
        ap.error("give a JSONL dump path or --demo")

    if args.prom:
        if args.dump and not args.demo:
            print("obs_report: --prom renders the LIVE registry; "
                  "combine it with --demo", file=sys.stderr)
            return 2
        from paddle_tpu.observability import export
        sys.stdout.write(export.prometheus_text())
        return 0

    render_recompiles(doc.get("recompiles", []), args.limit)
    render_spans(doc.get("spans", []), args.limit)
    render_metrics(doc.get("metrics", []))

    if args.json:
        payload = json.dumps(doc, indent=1, sort_keys=True)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
