#!/usr/bin/env python
"""numlint CLI — numerics & precision-flow audit of the traced programs.

shardlint asks whether the flagship programs SCALE; numlint asks
whether their NUMBERS survive: a dtype-provenance dataflow pass
(paddle_tpu/analysis/dtype_flow.py) over the same traced jaxprs, judged
by the NL rule catalog (analysis/num_rules.py) —

- NL1xx precision loss: narrow-dtype accumulation in reductions and
  dot contractions (NL101), f32->bf16->f32 double-rounding round trips
  (NL102), narrow master weights / moments without the moment_dtype
  opt-in (NL103);
- NL2xx stability: unstabilized exp/log/div/rsqrt on narrow dtypes
  (NL201), scan carries narrower than their body math (NL202);
- NL3xx quantization readiness: int8/fp8 codes consumed scale-free
  (NL301) and dequant->requant chains that should fuse (NL302) —
  written against HYPOTHETICAL quantized pools so the rules gate
  ROADMAP item 2's KV-quantization PR before it lands.

Audit targets: the optimized gpt_hybrid_train step (perfgate's shared
builder — bf16 activation residency, fused AdamW, Pallas fused LN: the
program that ships), every serving-engine program via
`LLMEngine.audit_programs()`, the same serving set at bf16-residency
pool dtype (`serving_bf16`), and the set over per-page-scaled int8 KV
pools (`serving_quant` — EngineConfig(kv_cache_dtype="int8"), the
quantized plane ROADMAP item 2 shipped; docs/quantization.md).

Usage:
  python tools/numlint.py                     # report everything
  python tools/numlint.py --check             # vs baseline, CI gate
  python tools/numlint.py --write-baseline
  python tools/numlint.py --diff              # per-rule counts vs baseline
  python tools/numlint.py --json -            # machine-readable report
  python tools/numlint.py --rules             # NL rule catalogue
  python tools/numlint.py --targets gpt_hybrid_train

Exit codes: 0 clean, 1 findings (plain) / NEW findings vs baseline
(--check), 2 usage error.

Suppression: the same `# tracelint: disable=NL101` per-line comments
the other analyzers honor (`# numlint: disable=...` is an accepted
alias, scoped to NL codes).  The checked-in baseline
(tools/numlint_baseline.json) holds the reviewed findings — today the
flagship's forward/activation-cotangent bf16 dots, which stay in
residency dtype by design (the MXU accumulates them wide in hardware;
docs/numlint.md records the rationale).  `--check` reports only
regressions beyond it.  Deliberate narrow accumulation registers once
via `core.dispatch.allow_narrow_accum`.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(1, os.path.join(REPO, "tools"))

# static analysis must never claim (or wedge on) the TPU: the audit is
# shape-only, so the CPU backend is always the right one here
os.environ.setdefault("JAX_PLATFORMS", "cpu")

DEFAULT_BASELINE = os.path.join(REPO, "tools", "numlint_baseline.json")


def _audit_config(analysis):
    """Thresholds scaled to the tiny CI configs the targets build —
    the flagship contracts over 64 tokens where the 1.3B config
    contracts over thousands, so the same defect classes fire (the
    shardlint `_audit_config` pattern)."""
    return analysis.NumConfig(reduce_min_elems=32)


# ------------------------------------------------------------- targets
def target_gpt_hybrid_train():
    """The optimized flagship train step (perfgate's shared builder:
    bf16 activation residency + fused AdamW + Pallas fused LN), traced
    via traced_program — the one numlint self-audit that found (and PR
    12 fixed) the narrow weight-/bias-grad accumulations."""
    from perfgate import build_gpt_train_step

    from paddle_tpu import analysis

    train_step, ids, labels = build_gpt_train_step(optimized=True)
    jaxpr, infos = train_step.traced_program(ids, labels)
    findings = analysis.check_numerics(
        jaxpr, where="<gpt_hybrid_train>", inputs=infos,
        config=_audit_config(analysis))
    return [("gpt_hybrid_train", findings)]


def _serving_targets(dtype_name, label, kv_cache_dtype=None):
    import jax.numpy as jnp

    import paddle_tpu as P
    from paddle_tpu import analysis, serving
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(0)
    mcfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=128, dropout=0.0,
                     attention_dropout=0.0)
    engine = serving.LLMEngine(
        GPTForCausalLM(mcfg),
        serving.EngineConfig(max_num_seqs=4, page_size=8,
                             max_model_len=64, prefill_buckets=(16, 32),
                             dtype=getattr(jnp, dtype_name),
                             kv_cache_dtype=kv_cache_dtype))
    cfg = _audit_config(analysis)
    out = []
    try:
        for name, jaxpr in engine.audit_programs().items():
            findings = analysis.check_numerics(
                jaxpr, where=f"<{label} {name}>", config=cfg)
            out.append((f"{label}/{name}", findings))
    finally:
        engine.shutdown()
    return out


def target_serving():
    """Every serving program at the default f32 pool dtype."""
    return _serving_targets("float32", "serving")


def target_serving_bf16():
    """The same program set at bf16 pool residency — the dtype plane
    ROADMAP item 2's KV quantization starts from.  The attention cores
    accumulate wide under it (PR 12's serving fix); this target keeps
    that invariant gated before the quantized pools land."""
    return _serving_targets("bfloat16", "serving_bf16")


def target_serving_quant():
    """The serving program set over per-page-scaled int8 KV pools
    (EngineConfig(kv_cache_dtype="int8") — the quantized plane ROADMAP
    item 2 shipped).  The NL3xx rules were written against hypothetical
    quantized pools BEFORE this plane landed; here they audit the real
    thing: every dequant must ride adjacent to its per-page scale
    (NL301) and the only dequant->requant chain is the documented
    page-rescale-on-append (NL302-silent by construction, see
    docs/quantization.md).  Zero findings, zero baseline growth."""
    return _serving_targets("float32", "serving_quant",
                            kv_cache_dtype="int8")


TARGETS = {
    "gpt_hybrid_train": target_gpt_hybrid_train,
    "serving": target_serving,
    "serving_bf16": target_serving_bf16,
    "serving_quant": target_serving_quant,
}


def run_targets(names=None):
    """[(program_name, [Finding])] over the chosen targets."""
    results = []
    for name in (names or sorted(TARGETS)):
        if name not in TARGETS:
            raise SystemExit(f"numlint: unknown target {name!r} "
                             f"(have: {', '.join(sorted(TARGETS))})")
        results.extend(TARGETS[name]())
    return results


def bench_report(targets=None):
    """The bench.py --worker-numlint lane: finding count + per-rule
    breakdown over the flagship programs, so every BENCH run records
    the numerics-hazard picture next to the cost audit."""
    t0 = time.time()
    results = run_targets(targets)
    breakdown = {}
    for _name, findings in results:
        for f in findings:
            breakdown[f.code] = breakdown.get(f.code, 0) + 1
    return {
        "numlint_finding_count": sum(len(fs) for _, fs in results),
        "numlint_rule_breakdown": dict(sorted(breakdown.items())),
        "numlint_elapsed_s": round(time.time() - t0, 2),
    }


# ----------------------------------------------------------------- CLI
def main(argv=None):
    from paddle_tpu.analysis import common
    from paddle_tpu.analysis.rules import NUMLINT_CODES, RULES

    ap = argparse.ArgumentParser(
        prog="numlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--targets", nargs="*", default=None,
                    help=f"audit targets (default: all — "
                         f"{', '.join(sorted(TARGETS))})")
    common.add_baseline_args(ap, DEFAULT_BASELINE)
    ap.add_argument("--rules", action="store_true",
                    help="print the NL rule catalogue and exit")
    args = ap.parse_args(argv)

    if args.rules:
        return common.print_rules(RULES, codes=set(NUMLINT_CODES))

    t0 = time.time()
    results = run_targets(args.targets)
    elapsed = time.time() - t0
    findings = [f for _, fs in results for f in fs]

    if not args.write_baseline and not args.diff:
        for name, fs in results:
            print(f"== {name}: {len(fs)} finding(s)")
    return common.run_baseline_flow(
        findings, args, tool="numlint", repo=REPO, elapsed=elapsed)


if __name__ == "__main__":
    sys.exit(main())
