#!/usr/bin/env python
"""protolint CLI — coordination-KV protocol audit for paddle_tpu.

Whole-package AST pass (no jax import, no trace): models every
coordination-KV key the package constructs — identity from the
construction-site f-string/helper, normalized to a
``prefix/<seq>/<rank>``-shaped pattern — with its set/get/delete flow
and the process role of each site (controller, replica-server,
worker, monitor, discovered from entry-point naming the way racelint
discovers thread roots), and reports the PLxxx family — leaked keys
(PL101), consume-without-delete double-delivery hazards (PL102),
unbounded blocking gets (PL103), cross-role wait cycles (PL104),
heartbeat/deadline budget mismatches (PL105), wire responses without
a typed-error envelope (PL201), and non-monotonic seq reuse (PL202).

Usage:
  python tools/protolint.py paddle_tpu            # report everything
  python tools/protolint.py --check paddle_tpu    # vs baseline, CI gate
  python tools/protolint.py --write-baseline paddle_tpu
  python tools/protolint.py --json - paddle_tpu
  python tools/protolint.py --rules               # PL rule catalogue

Exit codes: 0 clean, 1 findings (plain) / NEW findings vs baseline
(--check), 2 usage error.

Suppression: the same `# tracelint: disable=PL101` per-line comments
the other analyzers honor (`# protolint: disable=...` is an accepted
alias, scoped to PL codes; foreign spellings like `# racelint:`
cannot waive PL rules).  The checked-in baseline
(tools/protolint_baseline.json) holds reviewed findings; `--check`
reports only regressions beyond it.  The `--json` report uses the
shared analyzer schema (analysis/report.to_json, "tool": "protolint").

The dynamic half — the KV event tracer that records per-process
set/get/delete streams during the chaos suite and cross-checks them
against this model — lives in paddle_tpu/analysis/kv_tracer.py and is
armed by the chaos-marked tests (see docs/protolint.md).
"""
from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(1, os.path.join(REPO, "tools"))

DEFAULT_BASELINE = os.path.join(REPO, "tools", "protolint_baseline.json")


def main(argv=None):
    from _bootstrap import light_paddle_tpu
    light_paddle_tpu(REPO)
    from paddle_tpu.analysis import common, proto_rules
    from paddle_tpu.analysis.rules import PROTOLINT_CODES, RULES

    ap = argparse.ArgumentParser(
        prog="protolint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", help="files/directories to lint")
    common.add_baseline_args(ap, DEFAULT_BASELINE)
    ap.add_argument("--rules", action="store_true",
                    help="print the PL rule catalogue and exit")
    ap.add_argument("--no-source", action="store_true",
                    help="omit source lines from the text report")
    args = ap.parse_args(argv)

    if args.rules:
        return common.print_rules(RULES, codes=set(PROTOLINT_CODES))
    if not args.paths:
        ap.print_usage()
        return 2

    t0 = time.time()
    findings = proto_rules.lint_package(args.paths, base=REPO)
    elapsed = time.time() - t0

    return common.run_baseline_flow(
        findings, args, tool="protolint", repo=REPO, elapsed=elapsed,
        show_source=not args.no_source)


if __name__ == "__main__":
    sys.exit(main())
