#!/usr/bin/env python
"""racelint CLI — host-runtime concurrency audit for paddle_tpu.

Whole-package AST pass (no jax import, no trace): discovers thread
roots (threading.Thread targets, executor submissions, signal handlers,
multiprocessing workers, installed preemption handlers, and the public
API as the main-thread root), infers per-function lock sets, and
reports the RLxxx family — unguarded shared attributes (RL101),
lock-order inversion cycles (RL102), blocking calls under a lock
(RL103), unsafe signal handlers (RL104), thread/executor lifecycle
leaks (RL105), and check-then-act TOCTOU (RL201).

Usage:
  python tools/racelint.py paddle_tpu             # report everything
  python tools/racelint.py --check paddle_tpu     # vs baseline, CI gate
  python tools/racelint.py --write-baseline paddle_tpu
  python tools/racelint.py --json - paddle_tpu
  python tools/racelint.py --rules                # RL rule catalogue

Exit codes: 0 clean, 1 findings (plain) / NEW findings vs baseline
(--check), 2 usage error.

Suppression: the same `# tracelint: disable=RL101` per-line comments
the other analyzers honor (`# racelint: disable=...` is an accepted
alias, scoped to RL codes).  The checked-in baseline
(tools/racelint_baseline.json) holds reviewed findings; `--check`
reports only regressions beyond it.  The `--json` report uses the
shared analyzer schema (analysis/report.to_json, "tool": "racelint").

The dynamic half — the lock-order sanitizer that records the ACTUAL
acquisition graph during the chaos suite and cross-checks it against
the static RL102 model — lives in paddle_tpu/analysis/lock_tracer.py
and is enabled by the chaos-marked tests (see docs/racelint.md).
"""
from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(1, os.path.join(REPO, "tools"))

DEFAULT_BASELINE = os.path.join(REPO, "tools", "racelint_baseline.json")


def main(argv=None):
    from _bootstrap import light_paddle_tpu
    light_paddle_tpu(REPO)
    from paddle_tpu.analysis import common, race_rules
    from paddle_tpu.analysis.rules import RACELINT_CODES, RULES

    ap = argparse.ArgumentParser(
        prog="racelint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", help="files/directories to lint")
    common.add_baseline_args(ap, DEFAULT_BASELINE)
    ap.add_argument("--rules", action="store_true",
                    help="print the RL rule catalogue and exit")
    ap.add_argument("--no-source", action="store_true",
                    help="omit source lines from the text report")
    args = ap.parse_args(argv)

    if args.rules:
        return common.print_rules(RULES, codes=set(RACELINT_CODES))
    if not args.paths:
        ap.print_usage()
        return 2

    t0 = time.time()
    findings = race_rules.lint_package(args.paths, base=REPO)
    elapsed = time.time() - t0

    return common.run_baseline_flow(
        findings, args, tool="racelint", repo=REPO, elapsed=elapsed,
        show_source=not args.no_source)


if __name__ == "__main__":
    sys.exit(main())
