"""Training-path compositions compiled on real TPU.

The r4 lesson behind this file: recompute()+flash crashed the first
time it ran on silicon because jax.checkpoint JVP-linearized a raw
pallas_call (CPU tests route attention away from pallas, so the gate
could not see it). These tests pin the compositions that only exist on
TPU: remat-wrapped flash blocks and the fused chunked LM-head CE inside
a full to_static train step.
"""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn.functional as F


class _Block(P.nn.Layer):
    def __init__(self, h, heads):
        super().__init__()
        self.ln = P.nn.LayerNorm(h)
        self.qkv = P.nn.Linear(h, 3 * h)
        self.out = P.nn.Linear(h, h)
        self.heads = heads

    def forward(self, x):
        b, s, h = x.shape
        qkv = self.qkv(self.ln(x)).reshape(
            [b, s, 3, self.heads, h // self.heads])
        q, k, v = (qkv[:, :, i] for i in range(3))
        a = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        return x + self.out(a.reshape([b, s, h]))


def test_recompute_flash_block_trains_on_tpu():
    """remat around a flash-attention block, compiled + executed."""
    from paddle_tpu.distributed.recompute import recompute
    P.seed(0)
    blk = _Block(256, 4)
    opt = P.optimizer.SGD(learning_rate=0.1,
                          parameters=blk.parameters())

    @P.jit.to_static
    def step(x):
        opt.clear_grad()
        with P.amp.auto_cast(level="O1", dtype="bfloat16"):
            h = recompute(blk, x)
        loss = (h.astype("float32") ** 2).mean()
        loss.backward()
        opt.step()
        return loss

    x = P.to_tensor(np.random.RandomState(0)
                    .randn(2, 512, 256).astype(np.float32))
    losses = [float(step(x).numpy()) for _ in range(3)]
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_fused_linear_ce_compiled_matches_oracle():
    P.seed(0)
    rng = np.random.RandomState(0)
    hid = P.to_tensor(rng.randn(384, 128).astype(np.float32))
    hid.stop_gradient = False
    w = P.to_tensor((rng.randn(128, 1024) * 0.05).astype(np.float32))
    w.stop_gradient = False
    y = P.to_tensor(rng.randint(0, 1024, 384), dtype="int64")
    loss = F.fused_linear_cross_entropy(hid, w, y, chunk_size=128)
    ref = F.cross_entropy(P.matmul(P.to_tensor(hid.numpy()),
                                   P.to_tensor(w.numpy())), y)
    np.testing.assert_allclose(float(loss.numpy()), float(ref.numpy()),
                               rtol=5e-3)
    loss.backward()
    assert np.isfinite(w.grad.numpy()).all()
