"""Round-4 compiled-mode coverage (VERDICT #4): the kernels that had
never been compiled on silicon — Pallas ring attention blocks, the int8
quantized-linear MXU dot, and the fused incubate ops.

Auto-skips off-TPU (conftest). These run the REAL Mosaic compiler / MXU
int8 path; interpret-mode passes do not count (the r2 lesson).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.flash_attention import _flash_bhsd
from paddle_tpu.ops.pallas.ring_attention import (_flash_block, _merge,
                                                 ring_flash_attention)


def ref_attn(q, k, v, causal, scale):
    with jax.default_matmul_precision("highest"):
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        if causal:
            mask = jnp.tril(jnp.ones((s.shape[-2], s.shape[-1]), bool))
            s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)


def _rel_err(a, b):
    d = float(jnp.max(jnp.abs(b.astype(jnp.float32)))) + 1e-6
    return float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                 - b.astype(jnp.float32)))) / d


# --------------------------------------------------- ring attention blocks
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_ring_block_and_merge_compiled(dtype):
    """The ring's per-chunk flash block + online-softmax merge, Mosaic-
    compiled: two half-sequence blocks merged must equal full attention."""
    b, h, s, d = 1, 2, 256, 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, s, d), dtype)
    k = jnp.asarray(rng.randn(b, h, 2 * s, d), dtype)
    v = jnp.asarray(rng.randn(b, h, 2 * s, d), dtype)
    scale = float(d) ** -0.5

    o1, lse1 = _flash_block(q, k[:, :, :s], v[:, :, :s], False, scale,
                            1024, 1024, False)
    o2, lse2 = _flash_block(q, k[:, :, s:], v[:, :, s:], False, scale,
                            1024, 1024, False)
    o, _ = _merge(o1, lse1, o2, lse2)
    want = ref_attn(q, k, v, False, scale)
    assert _rel_err(o, want) < (3e-2 if dtype == jnp.bfloat16 else 6e-3)


def test_ring_block_grads_compiled():
    b, h, s, d = 1, 2, 256, 64
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    scale = float(d) ** -0.5

    def f(q, k, v):
        o, _ = _flash_block(q, k, v, True, scale, 1024, 1024, False)
        return jnp.sum(o.astype(jnp.float32))

    def g(q, k, v):
        return jnp.sum(ref_attn(q, k, v, True, scale).astype(jnp.float32))

    got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(got, want):
        assert _rel_err(a, b_) < 2e-2


def test_ring_attention_shard_map_single_chip():
    """The exact compile environment the flagship uses: shard_map over an
    sp mesh (size 1 on a single chip) with the Pallas blocks inside —
    must Mosaic-compile and match full attention."""
    from jax.sharding import Mesh, PartitionSpec as P

    b, h, s, d = 1, 2, 512, 64
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
    mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
    fn = jax.jit(jax.shard_map(
        lambda q, k, v: ring_flash_attention(
            q, k, v, axis_name="sp", causal=True, axis_size=1,
            interpret=False),
        mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
        check_vma=False))
    o = fn(q, k, v)
    want = ref_attn(q, k, v, True, float(d) ** -0.5)
    assert _rel_err(o, want) < 4e-2


# --------------------------------------------------------- int8 MXU dot
def test_quantized_linear_int8_dot_compiled():
    """The converted linear's int8 x int8 -> int32 dot must run compiled
    (the MXU executes int8 at 2x bf16 rate) and match the fp oracle to
    quantization tolerance."""
    import paddle_tpu as p
    from paddle_tpu.quantization import QuantizedLinear

    rng = np.random.RandomState(3)
    lin = p.nn.Linear(256, 512)
    w = rng.randn(256, 512).astype(np.float32) * 0.1
    lin.weight._set_value(jnp.asarray(w))
    lin.bias._set_value(jnp.asarray(np.zeros(512, np.float32)))
    w_scales = np.abs(w).max(axis=0) / 127.0
    act_scale = 3.0 / 127.0
    qlin = QuantizedLinear(lin, w_scales, act_scale)

    x = np.clip(rng.randn(64, 256), -3, 3).astype(np.float32)
    got = qlin(p.to_tensor(x)).numpy()
    want = x @ w
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    assert rel < 0.05, rel

    # the compiled HLO must contain a non-fp dot (s32/s8 operands)
    def raw(v):
        q = jnp.clip(jnp.round(v / act_scale), -127, 127).astype(jnp.int8)
        return jax.lax.dot_general(
            q, qlin.w_int8._value, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)

    txt = jax.jit(raw).lower(jnp.asarray(x)).compile().as_text()
    assert "s32" in txt and ("s8" in txt or "convert" in txt)


def test_int8_dot_throughput_sanity():
    """int8 MXU dot should not be SLOWER than the bf16 dot at the same
    shape (it is rated 2x; allow generous slack for small shapes)."""
    import time

    m = k_ = n = 2048
    rng = np.random.RandomState(4)
    a8 = jnp.asarray(rng.randint(-127, 127, (m, k_)), jnp.int8)
    b8 = jnp.asarray(rng.randint(-127, 127, (k_, n)), jnp.int8)
    abf = jnp.asarray(rng.randn(m, k_), jnp.bfloat16)
    bbf = jnp.asarray(rng.randn(k_, n), jnp.bfloat16)

    f8 = jax.jit(lambda a, b: jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32))
    fbf = jax.jit(lambda a, b: a @ b)

    f8(a8, b8).block_until_ready()
    fbf(abf, bbf).block_until_ready()

    def bench(f, a, b, iters=50):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(a, b)
        out.block_until_ready()
        return (time.perf_counter() - t0) / iters

    t8, tbf = bench(f8, a8, b8), bench(fbf, abf, bbf)
    assert t8 < tbf * 1.5, (t8, tbf)


# ------------------------------------------------------ fused incubate ops
def test_fused_feedforward_compiled():
    import paddle_tpu as p
    import paddle_tpu.incubate.nn.functional as IF

    rng = np.random.RandomState(5)
    x = rng.randn(8, 32, 128).astype(np.float32)
    w1 = rng.randn(128, 512).astype(np.float32) * 0.05
    w2 = rng.randn(512, 128).astype(np.float32) * 0.05
    g = np.ones(128, np.float32)
    b = np.zeros(128, np.float32)
    out = IF.fused_feedforward(
        p.to_tensor(x), p.to_tensor(w1), p.to_tensor(w2),
        ln1_scale=p.to_tensor(g), ln1_bias=p.to_tensor(b),
        dropout1_rate=0.0, dropout2_rate=0.0, activation="gelu",
        pre_layer_norm=True, training=False)
    xf = x.astype(np.float32)
    mean = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    h = (xf - mean) / np.sqrt(var + 1e-5)
    a = h @ w1
    # tanh-approx gelu (the fused kernels' convention)
    a = 0.5 * a * (1 + np.tanh(0.79788456 * a * (1 + 0.044715 * a * a)))
    want = xf + a @ w2
    rel = np.abs(out.numpy() - want).max() / (np.abs(want).max() + 1e-6)
    assert rel < 5e-3, rel


def test_fused_mha_flash_path_compiled():
    """No mask + no attention dropout routes through the Pallas flash
    kernel — must compile and match the dense oracle."""
    import paddle_tpu as p
    import paddle_tpu.incubate.nn.functional as IF

    rng = np.random.RandomState(6)
    b, s, e, n = 2, 128, 128, 4
    hd = e // n
    x = rng.randn(b, s, e).astype(np.float32) * 0.3
    qkvw = rng.randn(3, n, hd, e).astype(np.float32) * 0.05
    lw = rng.randn(e, e).astype(np.float32) * 0.05
    out = IF.fused_multi_head_attention(
        p.to_tensor(x), p.to_tensor(qkvw), p.to_tensor(lw),
        pre_layer_norm=True,
        pre_ln_scale=p.to_tensor(np.ones(e, np.float32)),
        pre_ln_bias=p.to_tensor(np.zeros(e, np.float32)),
        dropout_rate=0.0, attn_dropout_rate=0.0, training=False)
    assert out.shape == [b, s, e]
    assert np.isfinite(out.numpy()).all()


def test_fused_multi_transformer_compiled():
    import paddle_tpu as p
    import paddle_tpu.incubate.nn.functional as IF

    rng = np.random.RandomState(7)
    b, s, e, n, hd, L, f = 2, 64, 128, 4, 32, 2, 256
    x = rng.randn(b, s, e).astype(np.float32) * 0.3

    def mk(shape):
        return rng.randn(*shape).astype(np.float32) * 0.05

    out = IF.fused_multi_transformer(
        p.to_tensor(x),
        [np.ones(e, np.float32)] * L, [np.zeros(e, np.float32)] * L,
        [mk((3, n, hd, e)) for _ in range(L)],
        [mk((3, n, hd)) for _ in range(L)],
        [mk((n * hd, e)) for _ in range(L)], [mk((e,)) for _ in range(L)],
        [np.ones(e, np.float32)] * L, [np.zeros(e, np.float32)] * L,
        [mk((e, f)) for _ in range(L)], [mk((f,)) for _ in range(L)],
        [mk((f, e)) for _ in range(L)], [mk((e,)) for _ in range(L)])
    assert out.shape == [b, s, e]
    assert np.isfinite(out.numpy()).all()


def test_fused_bias_dropout_residual_ln_compiled():
    import paddle_tpu as p
    import paddle_tpu.incubate.nn.functional as IF

    rng = np.random.RandomState(8)
    x = rng.randn(16, 256).astype(np.float32)
    r = rng.randn(16, 256).astype(np.float32)
    out = IF.fused_bias_dropout_residual_layer_norm(
        p.to_tensor(x), p.to_tensor(r),
        ln_scale=p.to_tensor(np.ones(256, np.float32)),
        ln_bias=p.to_tensor(np.zeros(256, np.float32)),
        dropout_rate=0.0, training=False)
    h = x + r
    want = (h - h.mean(-1, keepdims=True)) / \
        np.sqrt(h.var(-1, keepdims=True) + 1e-5)
    assert np.abs(out.numpy() - want).max() < 1e-3


# --------------------------------------------- block-sparse attention
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_block_sparse_attention_compiled(dtype):
    """Splash-style table-driven kernel through Mosaic: scalar-prefetch
    index maps must lower and the active-block walk must match the
    dense-masked oracle."""
    from paddle_tpu.ops.pallas.block_sparse_attention import (
        block_sparse_attention, make_sliding_window_mask)

    b, h, s, d = 1, 2, 1024, 64
    bq = bk = 256
    rng = np.random.RandomState(10)
    q = jnp.asarray(rng.randn(b, h, s, d), dtype)
    k = jnp.asarray(rng.randn(b, h, s, d), dtype)
    v = jnp.asarray(rng.randn(b, h, s, d), dtype)
    nq = s // bq
    bm = make_sliding_window_mask(nq, nq, 2, causal=True)
    out = block_sparse_attention(q, k, v, bm, block_q=bq, block_k=bk,
                                 interpret=False)
    big = jnp.asarray(np.kron(bm, np.ones((bq, bk))).astype(bool))
    sc = jnp.einsum("bhid,bhjd->bhij", q.astype(jnp.float32),
                    k.astype(jnp.float32)) / np.sqrt(d)
    sc = jnp.where(big, sc, -1e30)
    ref = jnp.einsum("bhij,bhjd->bhid", jax.nn.softmax(sc, -1),
                     v.astype(jnp.float32))
    assert _rel_err(out, ref) < (3e-2 if dtype == jnp.bfloat16 else 6e-3)


def test_block_sparse_attention_grads_compiled():
    from paddle_tpu.ops.pallas.block_sparse_attention import (
        block_sparse_attention, make_sliding_window_mask)

    b, h, s, d = 1, 1, 512, 64
    bq = bk = 128
    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    bm = make_sliding_window_mask(s // bq, s // bq, 2, causal=True)
    big = jnp.asarray(np.kron(bm, np.ones((bq, bk))).astype(bool))

    def f(q, k, v):
        return jnp.sum(block_sparse_attention(
            q, k, v, bm, block_q=bq, block_k=bk,
            interpret=False).astype(jnp.float32))

    def g(q, k, v):
        sc = jnp.einsum("bhid,bhjd->bhij", q, k) / np.sqrt(d)
        sc = jnp.where(big, sc, -1e30)
        return jnp.sum(jnp.einsum("bhij,bhjd->bhid",
                                  jax.nn.softmax(sc, -1), v))

    got = jax.jit(jax.grad(f, argnums=(0, 1, 2)))(q, k, v)
    want = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for a, w in zip(got, want):
        assert _rel_err(a, w) < 2e-2
