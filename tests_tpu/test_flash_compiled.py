"""Flash-attention + fused-norm kernels, compiled by Mosaic on real TPU.

Tolerances are TPU-native: fp32 matmuls at default precision run bf16
passes on the MXU (~1e-3 relative), so oracles compare at bf16-scale
tolerance even for fp32 inputs.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.flash_attention import _flash_bhsd
from paddle_tpu.ops.pallas.norm import fused_layer_norm, fused_rms_norm


def ref_attn(q, k, v, causal, scale):
    with jax.default_matmul_precision("highest"):
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        if causal:
            mask = jnp.tril(jnp.ones((s.shape[-2], s.shape[-1]), bool))
            s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)


CASES = [
    ((2, 3, 192, 512, 64), jnp.float32, False),
    ((2, 3, 192, 512, 64), jnp.float32, True),
    ((1, 2, 512, 512, 128), jnp.bfloat16, True),
    ((1, 2, 200, 333, 64), jnp.float32, False),   # ragged, needs edge mask
    ((1, 1, 64, 64, 64), jnp.float32, True),      # single-block path
]


@pytest.mark.parametrize("shape,dtype,causal", CASES)
def test_flash_forward_compiled(shape, dtype, causal):
    b, h, sq, sk, d = shape
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, sq, d), dtype)
    k = jnp.asarray(rng.randn(b, h, sk, d), dtype)
    v = jnp.asarray(rng.randn(b, h, sk, d), dtype)
    scale = 1.0 / np.sqrt(d)
    o = _flash_bhsd(q, k, v, causal, scale, 1024, 1024, False)
    o_ref = ref_attn(q, k, v, causal, scale)
    denom = float(jnp.max(jnp.abs(o_ref.astype(jnp.float32)))) + 1e-6
    err = float(jnp.max(jnp.abs(o.astype(jnp.float32)
                                - o_ref.astype(jnp.float32)))) / denom
    assert err < (2e-2 if dtype == jnp.bfloat16 else 6e-3), err


@pytest.mark.parametrize("shape,dtype,causal", CASES)
def test_flash_grads_compiled(shape, dtype, causal):
    b, h, sq, sk, d = shape
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, h, sq, d), dtype)
    k = jnp.asarray(rng.randn(b, h, sk, d), dtype)
    v = jnp.asarray(rng.randn(b, h, sk, d), dtype)
    scale = 1.0 / np.sqrt(d)
    w = jnp.cos(jnp.arange(d, dtype=jnp.float32))

    def f(q, k, v):
        return jnp.sum(
            _flash_bhsd(q, k, v, causal, scale, 1024, 1024,
                        False).astype(jnp.float32) * w)

    def g(q, k, v):
        return jnp.sum(ref_attn(q, k, v, causal, scale).astype(
            jnp.float32) * w)

    got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(got, want):
        denom = float(jnp.max(jnp.abs(b_.astype(jnp.float32)))) + 1e-6
        err = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                    - b_.astype(jnp.float32)))) / denom
        assert err < (5e-2 if dtype == jnp.bfloat16 else 2e-2), err


def test_flash_long_sequence_16k():
    """16k-token causal attention: K/V must stream through VMEM (the r2
    kernel pinned the whole K/V per (batch,head) and could not even hold
    4k tokens); output and grads must be finite."""
    b, h, s, d = 1, 4, 16384, 128
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
    f = jax.jit(lambda q, k, v: _flash_bhsd(
        q, k, v, True, float(d) ** -0.5, 1024, 1024, False))
    o = f(q, k, v)
    assert o.shape == (b, h, s, d)
    assert bool(jnp.all(jnp.isfinite(o.astype(jnp.float32))))
    # spot-check rows against the oracle on a slice (full 16k² oracle
    # would materialize 4*16384² bytes per head — slice keeps it cheap)
    o_head = ref_attn(q[:, :1, :256], k[:, :1, :256], v[:, :1, :256],
                      True, float(d) ** -0.5)
    err = float(jnp.max(jnp.abs(
        o[:, :1, :256].astype(jnp.float32) - o_head.astype(jnp.float32))))
    assert err < 3e-2, err

    grads = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(_flash_bhsd(
            q, k, v, True, float(d) ** -0.5, 1024, 1024,
            False).astype(jnp.float32)), argnums=(0, 1, 2)))(q, k, v)
    for gx in grads:
        assert bool(jnp.all(jnp.isfinite(gx.astype(jnp.float32))))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_layer_norm_compiled(dtype):
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(64, 384), dtype)
    w = jnp.asarray(rng.randn(384), dtype)
    b = jnp.asarray(rng.randn(384), dtype)
    y = fused_layer_norm(x, w, b, 1e-5, None, False)
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    want = (xf - mean) * jax.lax.rsqrt(var + 1e-5) * w.astype(
        jnp.float32) + b.astype(jnp.float32)
    # bf16 tol is one output ulp at max |want| (~4 * 2^-8 here)
    tol = 4e-2 if dtype == jnp.bfloat16 else 1e-4
    assert float(jnp.max(jnp.abs(y.astype(jnp.float32) - want))) < tol


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_rms_norm_compiled(dtype):
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(64, 256), dtype)
    w = jnp.asarray(rng.randn(256), dtype)
    y = fused_rms_norm(x, w, 1e-6, None, False)
    xf = x.astype(jnp.float32)
    ms = (xf * xf).mean(-1, keepdims=True)
    want = xf * jax.lax.rsqrt(ms + 1e-6) * w.astype(jnp.float32)
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-4
    assert float(jnp.max(jnp.abs(y.astype(jnp.float32) - want))) < tol
