"""Compiled-mode (real TPU) tests for the r5 surfaces: sparse conv
gather paths and the ERNIE bench lane model. Auto-skip off-TPU."""
import numpy as np

import paddle_tpu as P
from paddle_tpu import sparse
import paddle_tpu.nn.functional as F
import paddle_tpu.sparse.nn as spnn


def _site_sparse(rng, shape, k):
    N, D, H, W, C = shape
    dense = np.zeros(shape, np.float32)
    sites = rng.choice(N * D * H * W, size=k, replace=False)
    n, z, y, x = np.unravel_index(sites, (N, D, H, W))
    dense[n, z, y, x] = rng.standard_normal((k, C))
    return dense


class TestSparseConvOnSilicon:
    def test_subm_gather_matches_dense(self):
        rng = np.random.default_rng(0)
        dense = _site_sparse(rng, (2, 8, 8, 8, 4), 60)
        xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
        P.seed(0)
        conv = spnn.SubmConv3D(4, 8, kernel_size=3, padding=1)
        out_g = conv(xt)
        out_d = conv.forward_dense(xt)
        np.testing.assert_allclose(np.asarray(out_g._value),
                                   np.asarray(out_d._value),
                                   rtol=1e-3, atol=1e-4)

    def test_strided_stack_trains(self):
        rng = np.random.default_rng(1)
        P.seed(0)
        c1 = spnn.Conv3D(3, 8, kernel_size=3, stride=2, padding=1)
        bn = spnn.BatchNorm(8)
        c2 = spnn.SubmConv3D(8, 4, kernel_size=3, padding=1)
        head = P.nn.Linear(4, 2)
        opt = P.optimizer.Adam(
            learning_rate=1e-2,
            parameters=c1.parameters() + bn.parameters()
            + c2.parameters() + head.parameters())
        losses = []
        for _ in range(4):
            opt.clear_grad()
            dense = _site_sparse(rng, (2, 10, 10, 10, 3), 60)
            xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
            h = c2(spnn.ReLU()(bn(c1(xt))))
            loss = ((head(h.values().mean(axis=0))
                     - P.to_tensor(np.array([1.0, -1.0],
                                            np.float32))) ** 2).sum()
            loss.backward()
            opt.step()
            losses.append(float(loss))
        assert np.isfinite(losses).all()


class TestErnieOnSilicon:
    def test_ernie_train_step_compiles(self):
        from paddle_tpu.models.ernie import ErnieForPretraining, ernie_tiny

        P.seed(0)
        cfg = ernie_tiny()
        model = ErnieForPretraining(cfg)
        opt = P.optimizer.AdamW(learning_rate=1e-4,
                                parameters=model.parameters())

        @P.jit.to_static
        def step(ids, task_ids, labels):
            opt.clear_grad()
            with P.amp.auto_cast(level="O1", dtype="bfloat16"):
                pred = model(ids, task_type_ids=task_ids)
            loss = F.cross_entropy(
                pred.reshape([-1, cfg.vocab_size]), labels.reshape([-1]))
            loss.backward()
            opt.step()
            return loss

        rng = np.random.default_rng(0)
        ids = P.to_tensor(rng.integers(0, cfg.vocab_size, (2, 64)),
                          dtype="int64")
        task = P.to_tensor(np.zeros((2, 64)), dtype="int64")
        labels = P.to_tensor(rng.integers(0, cfg.vocab_size, (2, 64)),
                             dtype="int64")
        l1 = float(step(ids, task, labels))
        l2 = float(step(ids, task, labels))
        assert np.isfinite([l1, l2]).all()
        assert l2 < l1 * 1.5
