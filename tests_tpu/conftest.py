"""Compiled-mode (real TPU) kernel tests.

Unlike `tests/` (which pins JAX to an 8-virtual-device CPU mesh so sharding
semantics run anywhere), this suite runs the Pallas kernels through the real
Mosaic compiler on an actual TPU chip. Round 2 shipped a kernel that passed
every interpret-mode test and died on silicon with a tiling error — this
suite exists so that class of bug fails in CI, not in the benchmark.

Run: `python -m pytest tests_tpu/ -q` on a host with a TPU attached.
The whole suite auto-skips when no TPU backend is available.
"""
import pytest


def _tpu_available():
    try:
        import jax
        return any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        return False


_HAS_TPU = _tpu_available()


def pytest_collection_modifyitems(config, items):
    if _HAS_TPU:
        return
    skip = pytest.mark.skip(reason="no TPU backend available")
    for item in items:
        item.add_marker(skip)
