"""DeepFM CTR training with the beyond-HBM parameter-server embedding.

Usage: python examples/train_deepfm_ps.py [--vocab 100000] [--steps 20]

Covers: distributed.ps (host-RAM SparseTable with server-side adagrad,
pull/push through jit-safe callbacks, native C++ table kernels when the
toolchain is present) — the table lives in host DRAM, so its size is
bounded by RAM, not by HBM.
"""
import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.models.deepfm import DeepFMPS


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=100_000)
    ap.add_argument("--fields", type=int, default=8)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    paddle.seed(0)
    model = DeepFMPS(vocab_size=args.vocab, num_fields=args.fields,
                     embedding_dim=args.dim, dense_dim=8)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())

    @paddle.jit.to_static
    def train_step(ids, dense, y):
        opt.clear_grad()
        logits = model(ids, dense)
        loss = F.binary_cross_entropy_with_logits(
            logits.reshape([-1]), y)
        loss.backward()
        opt.step()
        return loss

    rng = np.random.default_rng(0)
    for step in range(args.steps):
        ids = paddle.to_tensor(
            rng.integers(0, args.vocab, (args.batch_size, args.fields)),
            dtype="int64")
        dense = paddle.to_tensor(
            rng.standard_normal((args.batch_size, 8)).astype(np.float32))
        y = paddle.to_tensor(
            (rng.random(args.batch_size) > 0.5).astype(np.float32))
        loss = train_step(ids, dense, y)
        if step % 5 == 0:
            table = model.embedding.table
            print(f"step {step}: loss {float(loss):.4f} "
                  f"(pulls {table.pull_count}, pushes {table.push_count}, "
                  f"host table {table.memory_bytes / 1e6:.0f} MB)")


if __name__ == "__main__":
    main()
