"""Dy2Static + custom ops + train-on-your-own-images, end to end.

Usage: python examples/train_with_control_flow.py

Covers the round-4 surface:
- a model whose forward BRANCHES ON A TENSOR and a tensor-bounded while
  loop, compiled by `@paddle.jit.to_static` through the Dy2Static AST
  conversion (jit/dy2static.py) — no hand rewriting to lax.cond;
- a user-registered custom op with a custom VJP
  (utils.custom_op.register_custom_op);
- DatasetFolder training on a generated on-disk image directory
  (vision/folder.py) with read_file/decode_jpeg.
"""
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


# ---- a custom activation with a custom gradient (straight-through) ----
def _binary_fwd(x):
    import jax.numpy as jnp
    return jnp.where(x > 0, 1.0, -1.0).astype(x.dtype)


def _binary_bwd(saved, cots):
    import jax.numpy as jnp
    (x,), (g,) = saved, cots
    # straight-through estimator: pass the gradient inside |x| <= 1
    return (g * (jnp.abs(x) <= 1.0).astype(g.dtype),)


from paddle_tpu.utils.custom_op import register_custom_op  # noqa: E402

binary_ste = register_custom_op("binary_ste", _binary_fwd,
                                backward=_binary_bwd)


class GatedNet(paddle.nn.Layer):
    """Forward with data-dependent control flow: Dy2Static converts the
    tensor `if` into a differentiable select and the `while` into a
    lax.while_loop when this compiles under to_static."""

    def __init__(self, num_classes):
        super().__init__()
        self.conv = paddle.nn.Conv2D(3, 8, 3, padding=1)
        self.fc = paddle.nn.Linear(8 * 14 * 14, num_classes)
        self.pool = paddle.nn.MaxPool2D(2)

    def forward(self, x):
        h = F.relu(self.conv(x))
        if h.mean() > 0.3:          # tensor condition -> select lowering
            h = h * 0.8
        else:
            h = h * 1.2
        # tensor-bounded while -> lax.while_loop: halve until bounded
        # (runs on activations only, so no gradient needs to cross it)
        m = h.max().detach()
        while m > 4.0:
            m = m * 0.5
        h = h * (m / (h.max().detach() + 1e-6))
        h = self.pool(h)
        b = h.shape[0]
        h = h.reshape([b, -1])
        h = binary_ste(h) * 0.1 + h  # custom op in the middle
        return self.fc(h)


def make_image_folder(root, n_per_class=16):
    from PIL import Image
    rng = np.random.default_rng(0)
    for cls in (0, 1):
        d = os.path.join(root, f"class_{cls}")
        os.makedirs(d, exist_ok=True)
        for i in range(n_per_class):
            img = rng.integers(90, 160, (28, 28, 3)).astype(np.uint8)
            if cls == 0:
                img[:14] //= 3
            else:
                img[14:] //= 3
            Image.fromarray(img).save(os.path.join(d, f"{i:03d}.jpg"))
    return root


def main():
    paddle.seed(0)
    root = make_image_folder(tempfile.mkdtemp(prefix="imgs_"))

    T = paddle.vision.transforms
    ds = paddle.vision.datasets.DatasetFolder(
        root, transform=T.Compose([T.ToTensor()]))
    loader = paddle.io.DataLoader(ds, batch_size=8, shuffle=True)
    print(f"dataset: {len(ds)} images, classes={ds.classes}")

    net = GatedNet(num_classes=2)
    opt = paddle.optimizer.Adam(5e-3, parameters=net.parameters())

    @paddle.jit.to_static
    def train_step(x, y):
        loss = F.cross_entropy(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    losses = []
    for epoch in range(4):
        for x, y in loader:
            losses.append(float(train_step(x, y).numpy()))
    print(f"loss {losses[0]:.3f} -> {losses[-1]:.3f} "
          f"({len(losses)} steps, tensor-if + custom op, one XLA program)")
    assert losses[-1] < losses[0]

    # image IO round trip on one file
    path = ds.samples[0][0]
    raw = paddle.vision.ops.read_file(path)
    img = paddle.vision.ops.decode_jpeg(raw)
    print(f"read_file/decode_jpeg: {path} -> {tuple(img.shape)} uint8")


if __name__ == "__main__":
    main()
