"""tracelint demo — a DELIBERATELY trace-unsafe `@to_static` step.

This example exists to be caught: `python tools/tracelint.py examples/`
must flag the hazards below with rule codes and file:line.  Running the
module shows the same diagnostics surfacing the two other ways —
`to_static(check=True)` warnings ahead of trace, and the NAMED runtime
error (`analysis.rules.TraceHazardError`, same wording as the CLI) when
a tensor condition actually hits an unconvertible loop.

The hazards, on purpose:
  - TL101: `loss.numpy()` host sync inside the traced step
  - TL104: `print` of a tensor inside the traced step
  - TL106: appending a tensor to a module-level list at trace time
  - TL001: `return` inside a `while` — the loop stays plain Python, and
    a tensor-valued condition there raises the named diagnostic
"""
import warnings

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

net = paddle.nn.Linear(8, 4)
opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())

history = []  # mutated from inside the traced step: TL106


@paddle.jit.to_static
def broken_train_step(x, y):
    loss = F.cross_entropy(net(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    print("loss is", loss)            # TL104: prints a tracer, once
    history.append(loss)              # TL106: trace-time side effect
    return float(loss.numpy())        # TL101: host sync under the trace


def clip_until(m):
    # TL001: `return` inside the loop keeps it plain Python — fine
    # eagerly, a named TraceHazardError when `m` is traced
    while m > 4.0:
        if m < 8.0:
            return m
        m = m * 0.5
    return m


def main():
    from paddle_tpu import analysis

    print("== AST findings for this file ==")
    findings = analysis.lint_paths([__file__])
    for f in findings:
        print(" ", f.format())

    print("\n== the same hazards via to_static(check=True) ==")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        paddle.jit.to_static(broken_train_step.dygraph_function, check=True)
    for w in caught:
        print(" ", str(w.message).splitlines()[0])

    print("\n== named runtime diagnostic (TL001) ==")

    @paddle.jit.to_static
    def traced_clip(x):
        return clip_until(x.mean() * 100.0)

    try:
        traced_clip(paddle.to_tensor(np.ones((4, 4), np.float32)))
    except analysis.TraceHazardError as e:
        print(" ", str(e).splitlines()[0])


if __name__ == "__main__":
    main()
