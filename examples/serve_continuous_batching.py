"""Continuous batching proper: requests ARRIVE while the batch decodes,
join at step boundaries, stream tokens as they land, and finished rows
free their pages immediately for the queue.

Drives `LLMEngine.step()` directly (the async-serving surface beneath
`generate()`): a toy arrival schedule trickles requests in, a streaming
callback prints tokens the moment they are sampled, and the metrics
snapshot at the end shows queue/page/compile behavior.

Usage:
  JAX_PLATFORMS=cpu python examples/serve_continuous_batching.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

VOCAB = 97


def main():
    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=VOCAB, hidden_size=128, num_layers=4, num_heads=8,
        max_seq_len=128, dropout=0.0, attention_dropout=0.0))
    engine = serving.LLMEngine(model, serving.EngineConfig(
        max_num_seqs=3, page_size=8, max_model_len=64,
        prefill_buckets=(8, 16, 32)))

    rng = np.random.default_rng(1)
    # (arrival_step, prompt_len, max_new_tokens): more requests than
    # slots, arriving over time — later arrivals wait in the FCFS queue
    schedule = [(0, 5, 10), (0, 12, 6), (1, 3, 8), (2, 25, 4),
                (4, 7, 6), (6, 2, 5)]

    def stream(req, token, finished):
        tag = " <done>" if finished else ""
        print(f"    {req.request_id} += {token}{tag}")

    pending = list(schedule)
    step = 0
    while pending or engine.has_unfinished():
        while pending and pending[0][0] <= step:
            _, plen, mnt = pending.pop(0)
            rid = engine.add_request(
                list(rng.integers(1, VOCAB, plen)),
                serving.SamplingParams(max_new_tokens=mnt, temperature=0.7,
                                       seed=step),
                stream=stream)
            print(f"step {step}: arrived {rid} (prompt {plen} tokens)")
        events = engine.step()
        done = [rid for rid, _, fin in events if fin]
        if done:
            print(f"step {step}: finished {', '.join(done)} "
                  f"(pages freed for the queue)")
        step += 1

    snap = engine.metrics.snapshot()
    print("\nmetrics snapshot:")
    print(f"  requests: {snap['requests']}")
    print(f"  tokens:   {snap['tokens']}")
    print(f"  ttft ms:  {snap['ttft_ms']}")
    print(f"  itl ms:   {snap['inter_token_ms']}")
    print(f"  compiles: {snap['compiles']['count']} "
          f"(bound {snap['compiles']['bound']})")
    assert snap["requests"]["finished"] == len(schedule)
    assert snap["compiles"]["count"] <= snap["compiles"]["bound"]
    print("OK: arrivals joined the running batch at step boundaries; "
          "no recompile storm")
    engine.shutdown()


if __name__ == "__main__":
    main()
