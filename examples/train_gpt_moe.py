"""GPT with Mixture-of-Experts FFNs and expert parallelism.

Usage (8 virtual CPU devices):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/train_gpt_moe.py --steps 5

Covers: distributed.moe (GShard dispatch/combine, gates + aux loss),
expert weights sharded over the `ep` mesh axis (XLA inserts the
all-to-all), moe_aux_loss collection in the training objective.
"""
import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F  # noqa: F401
from paddle_tpu.distributed.mesh import init_mesh
from paddle_tpu.models.gpt import (
    GPTForCausalLM,
    GPTPretrainingCriterion,
    gpt3_tiny,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--ep", type=int, default=4)
    ap.add_argument("--experts", type=int, default=4)
    ap.add_argument("--top-k", type=int, default=2)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--aux-weight", type=float, default=0.01)
    args = ap.parse_args()

    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = init_mesh(dict(dp=args.dp, ep=args.ep))
    paddle.seed(0)
    cfg = gpt3_tiny(moe_num_experts=args.experts, moe_top_k=args.top_k,
                    moe_every=2)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

    @paddle.jit.to_static
    def step(ids, labels):
        opt.clear_grad()
        loss = crit(model(ids), labels) \
            + args.aux_weight * model.gpt.moe_aux_loss()
        loss.backward()
        opt.step()
        return loss

    rng = np.random.default_rng(0)
    b = 4 * args.dp
    sh = NamedSharding(mesh, PartitionSpec("dp", None))
    ids = paddle.Tensor(jax.device_put(
        rng.integers(0, cfg.vocab_size, (b, 32)).astype(np.int32), sh))
    labels = paddle.Tensor(jax.device_put(
        rng.integers(0, cfg.vocab_size, (b, 32)).astype(np.int32), sh))

    for i in range(args.steps):
        loss = step(ids, labels)
        print(f"step {i}: loss {float(loss):.4f} "
              f"({args.experts} experts over ep={args.ep})")


if __name__ == "__main__":
    main()
