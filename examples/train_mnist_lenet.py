"""LeNet on MNIST — the smallest full training loop.

Usage: python examples/train_mnist_lenet.py [--epochs 1] [--batch-size 64]

Covers: vision.datasets (offline), io.DataLoader (native C++ prefetch
engages automatically), jit.to_static (whole step -> one XLA program),
save/load round-trip.
"""
import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--steps", type=int, default=0,
                    help="cap steps per epoch (0 = full epoch)")
    args = ap.parse_args()

    paddle.seed(0)
    model = LeNet(num_classes=10)
    opt = paddle.optimizer.Adam(learning_rate=args.lr,
                                parameters=model.parameters())

    @paddle.jit.to_static
    def train_step(x, y):
        opt.clear_grad()
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        return loss

    loader = paddle.io.DataLoader(MNIST(mode="train"),
                                  batch_size=args.batch_size, shuffle=True)
    for epoch in range(args.epochs):
        for step, (x, y) in enumerate(loader):
            loss = train_step(x, y)
            if step % 50 == 0:
                print(f"epoch {epoch} step {step}: loss {float(loss):.4f}")
            if args.steps and step + 1 >= args.steps:
                break

    # eval accuracy on the test split
    model.eval()
    correct = total = 0
    for x, y in paddle.io.DataLoader(MNIST(mode="test"),
                                     batch_size=256):
        pred = model(x).numpy().argmax(-1)
        correct += int((pred == y.numpy().reshape(-1)).sum())
        total += pred.shape[0]
    print(f"test accuracy: {correct / total:.3f}")

    paddle.save(model.state_dict(), "/tmp/lenet_example.ptpu")
    model2 = LeNet(num_classes=10)
    model2.set_state_dict(paddle.load("/tmp/lenet_example.ptpu"))
    print("checkpoint round-trip OK")


if __name__ == "__main__":
    main()
