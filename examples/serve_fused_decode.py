"""Serving-path walkthrough: fused transformer stack, KV-cache decode
with CONTINUATION BATCHING (ragged per-sequence positions), and
tensor-parallel weight sharding over a mesh.

Usage:
  python examples/serve_fused_decode.py                      # 1 device
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
      python examples/serve_fused_decode.py --mp 4           # mp mesh

Covers: incubate.nn.functional.fused_multi_transformer (the N-layer
serving stack as ONE op; static KV caches; prefill + ragged decode),
GSPMD weight sharding (Megatron column/row layouts — the same specs
HybridParallelInferenceHelper applies to Layers).
"""
import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.incubate.nn.functional as IF

B, S_MAX, E, N_HEAD, HD, L = 4, 64, 128, 8, 16, 4
FFN = 4 * E
VOCAB = 97


def make_weights(rng):
    def mk(shape, s=0.06):
        return rng.standard_normal(shape).astype(np.float32) * s

    return dict(
        emb=mk((VOCAB, E), 0.1),
        ln_s=[np.ones(E, np.float32)] * L,
        ln_b=[np.zeros(E, np.float32)] * L,
        qkvw=[mk((3, N_HEAD, HD, E)) for _ in range(L)],
        qkvb=[mk((3, N_HEAD, HD)) for _ in range(L)],
        lw=[mk((N_HEAD * HD, E)) for _ in range(L)],
        lb=[mk((E,)) for _ in range(L)],
        fln_s=[np.ones(E, np.float32)] * L,
        fln_b=[np.zeros(E, np.float32)] * L,
        w1=[mk((E, FFN)) for _ in range(L)],
        b1=[mk((FFN,)) for _ in range(L)],
        w2=[mk((FFN, E)) for _ in range(L)],
        b2=[mk((E,)) for _ in range(L)],
        head=mk((E, VOCAB), 0.1),
    )


def shard_weights(w, mp):
    """Megatron layouts over an mp mesh — GSPMD inserts the collectives."""
    if mp <= 1:
        return w, None
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:mp]), ("mp",))

    def put(a, spec):
        return jax.device_put(np.asarray(a), NamedSharding(mesh, spec))

    w = dict(w)
    w["qkvw"] = [put(a, P(None, "mp", None, None)) for a in w["qkvw"]]
    w["qkvb"] = [put(a, P(None, "mp", None)) for a in w["qkvb"]]
    w["lw"] = [put(a, P("mp", None)) for a in w["lw"]]
    w["w1"] = [put(a, P(None, "mp")) for a in w["w1"]]
    w["b1"] = [put(a, P("mp")) for a in w["b1"]]
    w["w2"] = [put(a, P("mp", None)) for a in w["w2"]]
    return w, mesh


def stack(w, x, caches=None, time_step=None, mask=None):
    return IF.fused_multi_transformer(
        x, w["ln_s"], w["ln_b"], w["qkvw"], w["qkvb"], w["lw"], w["lb"],
        w["fln_s"], w["fln_b"], w["w1"], w["b1"], w["w2"], w["b2"],
        attn_mask=mask, cache_kvs=caches, time_step=time_step)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mp", type=int, default=1)
    ap.add_argument("--steps", type=int, default=12)
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    w, mesh = shard_weights(make_weights(rng), args.mp)
    print(f"fused stack: {L} layers, {N_HEAD} heads, mp={args.mp}")

    # mixed-length prompts: continuation batching from the first step
    prompt_lens = np.array([3, 7, 5, 9], np.int32)
    ids = rng.integers(1, VOCAB, (B, S_MAX))
    for b in range(B):
        ids[b, prompt_lens[b]:] = 0

    emb = w["emb"]
    caches = [paddle.to_tensor(
        np.zeros((2, B, N_HEAD, S_MAX, HD), np.float32))
        for _ in range(L)]

    # ---- prefill: run the longest prompt length once; per-row causal +
    # padding mask keeps short rows clean ----
    s0 = int(prompt_lens.max())
    x = paddle.to_tensor(emb[ids[:, :s0]])
    causal = np.tril(np.ones((s0, s0), np.float32))
    pad = (np.arange(s0)[None, :] < prompt_lens[:, None]).astype(np.float32)
    mask = np.where(causal[None, None] * pad[:, None, None, :] > 0,
                    0.0, -1e9).astype(np.float32)
    h, caches = stack(w, x, caches=caches, mask=paddle.to_tensor(mask))
    print(f"prefill: {s0} steps, caches primed at per-row lengths "
          f"{prompt_lens.tolist()}")

    # last REAL token's hidden state per row seeds generation
    h_np = h.numpy()
    last = h_np[np.arange(B), prompt_lens - 1]
    tok = np.argmax(last @ np.asarray(w["head"]), axis=-1)

    # ---- ragged decode: every row appends at ITS OWN length ----
    lens = prompt_lens.copy()
    outputs = [[] for _ in range(B)]
    for step in range(args.steps):
        x_t = paddle.to_tensor(emb[tok][:, None, :])
        h, caches = stack(w, x_t, caches=caches,
                          time_step=paddle.to_tensor(lens))
        logits = h.numpy()[:, 0] @ np.asarray(w["head"])
        tok = np.argmax(logits, axis=-1)
        for b in range(B):
            outputs[b].append(int(tok[b]))
        lens = lens + 1
    print("ragged decode:", args.steps, "steps")
    for b in range(B):
        print(f"  row {b} (prompt {prompt_lens[b]:2d} tokens) -> "
              f"{outputs[b][:8]}…")
    assert all(len(o) == args.steps for o in outputs)
    print("OK: mixed-length batch served with one static-shape program "
          "per phase (no re-padding between steps)")


if __name__ == "__main__":
    main()
