"""Serving-path walkthrough, rebuilt on `paddle_tpu.serving.LLMEngine`.

The old version of this example hand-rolled the serving loop: manual
prefill masks, a python decode loop appending at per-row lengths, argmax
on host.  All of that is now the engine's job — this file shows the same
mixed-length continuation-batched decode driven through the real
subsystem: paged KV cache, bucketed prefill (bounded compiles), one
compiled decode step, per-request sampling.

Usage:
  JAX_PLATFORMS=cpu python examples/serve_fused_decode.py
  python examples/serve_fused_decode.py --steps 24 --temperature 0.8

See examples/serve_continuous_batching.py for requests ARRIVING while
the batch decodes (admission at step boundaries + streaming callbacks).
"""
import argparse

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

VOCAB = 97


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=12,
                    help="tokens to generate per request")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="0 = greedy (the old example's argmax)")
    args = ap.parse_args()

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=VOCAB, hidden_size=128, num_layers=4, num_heads=8,
        max_seq_len=128, dropout=0.0, attention_dropout=0.0))

    cfg = serving.EngineConfig(max_num_seqs=4, page_size=8,
                               max_model_len=64,
                               prefill_buckets=(16, 32))
    engine = serving.LLMEngine(model, cfg)
    print(f"engine: {cfg.max_num_seqs} slots, page={cfg.page_size}, "
          f"buckets={cfg.prefill_buckets}, "
          f"compile bound={cfg.compile_bound}")

    # mixed-length prompts: continuation batching from the first step
    rng = np.random.default_rng(0)
    prompt_lens = [3, 7, 5, 9]
    prompts = [list(rng.integers(1, VOCAB, n)) for n in prompt_lens]
    sps = [serving.SamplingParams(max_new_tokens=args.steps,
                                  temperature=args.temperature, seed=i)
           for i in range(len(prompts))]

    results = engine.generate(prompts, sps)
    print(f"prefill: {len(prompts)} requests bucketed over "
          f"{sorted(set(engine.scheduler.bucket_for_len(n) for n in prompt_lens))}")
    print("ragged decode:", args.steps, "steps")
    for i, r in enumerate(results):
        print(f"  row {i} (prompt {prompt_lens[i]:2d} tokens) -> "
              f"{r.output_token_ids[:8]}…")

    snap = engine.metrics.snapshot()
    assert all(len(r.output_token_ids) == args.steps for r in results)
    assert snap["compiles"]["count"] <= snap["compiles"]["bound"]
    print(f"OK: mixed-length batch served with "
          f"{snap['compiles']['count']} compiled programs "
          f"(bound {snap['compiles']['bound']}); "
          f"{snap['tokens']['per_s']} tok/s, "
          f"ttft p50 {snap['ttft_ms']['p50']} ms")
    engine.shutdown()


if __name__ == "__main__":
    main()
