"""GPT with explicit 4-D hybrid parallelism (dp x pp x tp x sp) on the
1F1B pipeline schedule — the flagship distributed configuration.

Usage (8 virtual CPU devices; on a pod the same code uses real chips):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/train_gpt_hybrid.py --steps 5
  # full 5-axis with MoE experts over ep:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/train_gpt_hybrid.py --sp 1 --ep 2 --experts 4

Covers: distributed.mesh, models.gpt_hybrid (shard_map + ppermute
pipeline + Megatron tp psums + sp ring attention + vocab-parallel CE),
schedule="1f1b" | "interleave" | "gpipe".
"""
import argparse

import numpy as np

import paddle_tpu  # noqa: F401  (registers the framework)
from paddle_tpu.distributed.mesh import init_mesh
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import (
    init_hybrid_gpt_params,
    make_hybrid_train_step,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--sp", type=int, default=2)
    ap.add_argument("--ep", type=int, default=1)
    ap.add_argument("--experts", type=int, default=0,
                    help="MoE experts per layer (0 = dense FFN)")
    ap.add_argument("--schedule", default="1f1b",
                    choices=["gpipe", "1f1b", "interleave"])
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--microbatches", type=int, default=2)
    args = ap.parse_args()

    mesh = init_mesh(dict(dp=args.dp, pp=args.pp, tp=args.tp, sp=args.sp,
                          ep=args.ep))
    cfg = GPTConfig(vocab_size=256, hidden_size=64,
                    num_layers=2 * args.pp, num_heads=max(4, 2 * args.tp),
                    max_seq_len=64 * args.sp, dropout=0.0,
                    moe_num_experts=args.experts, moe_top_k=2,
                    moe_capacity_factor=(2.0, 2.0))
    params = init_hybrid_gpt_params(cfg, mesh, seed=0)
    step = make_hybrid_train_step(cfg, mesh, lr=1e-2,
                                  num_microbatches=args.microbatches,
                                  schedule=args.schedule)

    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    rng = np.random.default_rng(0)
    b = 2 * args.dp * args.microbatches
    s = 32 * args.sp
    sh = NamedSharding(mesh, PartitionSpec("dp", "sp"))
    ids = jax.device_put(
        rng.integers(0, cfg.vocab_size, (b, s)).astype(np.int32), sh)
    labels = jax.device_put(
        rng.integers(0, cfg.vocab_size, (b, s)).astype(np.int32), sh)

    for i in range(args.steps):
        params, loss = step(params, ids, labels)
        kind = f"{args.experts} experts/ep{args.ep}" if args.experts \
            else "dense"
        print(f"step {i} [{args.schedule}, {kind}] loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
