"""Train a small model, export it (StableHLO + ONNX), serve it through
the inference Predictor, and quantize it to int8.

Usage: python examples/export_and_serve.py

Covers: jit.save (non-executable PTPU container + StableHLO),
inference.create_predictor (AOT compile + warmup), onnx.export (+ bundled
numpy runtime check), quantization ImperativePTQ -> int8 MXU linears.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def main():
    paddle.seed(0)
    net = paddle.nn.Sequential(
        paddle.nn.Linear(16, 64), paddle.nn.ReLU(),
        paddle.nn.Linear(64, 8))
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((32, 16)).astype(np.float32))
    y = paddle.to_tensor(rng.integers(0, 8, (32,)), dtype="int64")
    opt = paddle.optimizer.Adam(1e-2, parameters=net.parameters())
    for _ in range(30):
        opt.clear_grad()
        loss = F.cross_entropy(net(x), y)
        loss.backward()
        opt.step()
    print(f"trained: loss {float(loss):.4f}")
    net.eval()
    ref = net(x).numpy()

    # 1. TPU-native serialized program (StableHLO inside a PTPU container)
    from paddle_tpu.static import InputSpec
    paddle.jit.save(net, "/tmp/served_model",
                    input_spec=[InputSpec([None, 16], "float32")])
    loaded = paddle.jit.load("/tmp/served_model")
    np.testing.assert_allclose(loaded(x).numpy(), ref, rtol=1e-5)
    print("jit.save/load (StableHLO) round-trip OK")

    # 2. Predictor (AOT compiled, donated buffers, warmed up)
    from paddle_tpu import inference
    config = inference.Config("/tmp/served_model")
    predictor = inference.create_predictor(config)
    names = predictor.get_input_names()
    handle = predictor.get_input_handle(names[0])
    handle.copy_from_cpu(x.numpy())
    predictor.run()
    out = predictor.get_output_handle(
        predictor.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    print("inference Predictor OK")

    # 3. ONNX export for non-JAX serving + numpy-runtime verification
    import paddle_tpu.onnx as ponnx
    path = ponnx.export(net, "/tmp/served_model_onnx",
                        input_spec=[InputSpec([32, 16], "float32")])
    from paddle_tpu.onnx import numpy_runtime
    onnx_out = numpy_runtime.run(path, [x.numpy()])[0]
    np.testing.assert_allclose(onnx_out, ref, rtol=1e-4, atol=1e-5)
    print("ONNX export + bundled runtime OK")

    # 4. Post-training int8 quantization (real int8xint8->int32 MXU dots)
    from paddle_tpu.quantization import ImperativePTQ, default_ptq_config
    ptq = ImperativePTQ(default_ptq_config())
    qnet = ptq.quantize(net)
    qnet(x)  # calibrate
    qnet = ptq.convert(qnet)
    qout = qnet(x).numpy()
    rel = np.abs(qout - ref).max() / (np.abs(ref).max() + 1e-6)
    print(f"int8 PTQ relative error: {rel:.4f}")


if __name__ == "__main__":
    main()
