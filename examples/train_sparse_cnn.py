"""Sparse 3-D CNN on synthetic point-cloud voxels (r5).

The canonical sparse stack — strided sparse Conv3D (true nnz compute:
candidate-site discovery + sorted-coalescing join + one MXU GEMM),
mask-aware BatchNorm, ReLU, SubmConv3D, sparse MaxPool3D — trained
end to end on a two-class "which octant is denser" task. Compute
scales with active sites, not volume (reference:
python/paddle/sparse/nn/layer/conv.py rulebook kernels).

Run: python examples/train_sparse_cnn.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.sparse.nn as spnn
from paddle_tpu import sparse

VOL = (1, 16, 16, 16, 3)      # [N, D, H, W, C], ~2% occupancy


def make_sample(rng, label):
    """Scatter 80 active sites; class 1 biases them into the +z half."""
    dense = np.zeros(VOL, np.float32)
    n_sites = 80
    z = rng.integers(8, 16, n_sites) if label else rng.integers(0, 16,
                                                                n_sites)
    y, x = rng.integers(0, 16, (2, n_sites))
    dense[0, z, y, x] = rng.standard_normal((n_sites, 3)) + 0.5
    return sparse.to_sparse_coo(paddle.to_tensor(dense), sparse_dim=4)


def main():
    paddle.seed(0)
    rng = np.random.default_rng(0)
    conv1 = spnn.Conv3D(3, 16, kernel_size=3, stride=2, padding=1)
    bn1 = spnn.BatchNorm(16)
    conv2 = spnn.SubmConv3D(16, 16, kernel_size=3, padding=1)
    pool = spnn.MaxPool3D(kernel_size=2, stride=2)
    head = paddle.nn.Linear(16, 2)
    params = (conv1.parameters() + bn1.parameters() + conv2.parameters()
              + head.parameters())
    opt = paddle.optimizer.Adam(learning_rate=5e-3, parameters=params)
    relu = spnn.ReLU()

    losses, correct = [], 0
    for step in range(40):
        label = step % 2
        x = make_sample(rng, label)
        opt.clear_grad()
        h = pool(conv2(relu(bn1(conv1(x)))))
        # masked global mean over ACTIVE sites only
        vals, mask = h.values(), paddle.to_tensor(
            np.asarray(h._live_mask, np.float32))
        pooled = (vals * mask.unsqueeze(-1)).sum(axis=0) / mask.sum()
        logits = head(pooled)
        loss = paddle.nn.functional.cross_entropy(
            logits.unsqueeze(0),
            paddle.to_tensor(np.array([label]), dtype="int64"))
        loss.backward()
        opt.step()
        losses.append(float(loss))
        if step >= 30:
            correct += int(np.argmax(logits.numpy()) == label)
    print(f"loss {np.mean(losses[:8]):.3f} -> {np.mean(losses[-8:]):.3f}"
          f"; last-10 accuracy {correct}/10")
    assert np.mean(losses[-8:]) < np.mean(losses[:8])
    print("OK: sparse Conv-BN-ReLU-SubmConv-MaxPool stack trained "
          "(work scales with ~2% active sites, not the volume)")


if __name__ == "__main__":
    main()
