"""paddle.sparse.nn.layer submodule path parity (reference:
python/paddle/sparse/nn/layer/{activation,norm,conv,pooling}.py) — the
classes live in paddle_tpu.sparse.nn; this package mirrors the
reference's import paths."""
from paddle_tpu.sparse.nn import (  # noqa: F401
    BatchNorm,
    Conv3D,
    LeakyReLU,
    MaxPool3D,
    ReLU,
    ReLU6,
    Softmax,
    SubmConv3D,
    SyncBatchNorm,
)
