"""paddle.sparse.nn.functional — functional ops over sparse tensors.

Reference: python/paddle/sparse/nn/functional/ (conv.py conv3d/subm_conv3d,
pooling.py max_pool3d, activation.py relu/relu6/leaky_relu/softmax,
transformer.py attention).

TPU-native: activations are zero-preserving maps over BCOO stored values;
convs/pooling run through the dense mirror (XLA windows); `attention`
computes QK^T only at the CSR-stored positions via gathers + segment
softmax — static shapes (nnz is fixed at trace time), so the whole thing
jits and differentiates through jax.grad / the tape.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "relu",
    "relu6",
    "leaky_relu",
    "softmax",
    "conv3d",
    "subm_conv3d",
    "max_pool3d",
    "attention",
]


def relu(x, name=None):
    from paddle_tpu import sparse
    return sparse.relu(x)


def relu6(x, name=None):
    from paddle_tpu import sparse
    return sparse._unary_on_values(lambda v: jnp.clip(v, 0.0, 6.0))(x)


def leaky_relu(x, negative_slope=0.01, name=None):
    from paddle_tpu import sparse
    return sparse._unary_on_values(
        lambda v: jnp.where(v >= 0, v, negative_slope * v))(x)


def softmax(x, axis=-1, name=None):
    from paddle_tpu.sparse import nn as sparse_nn
    return sparse_nn.Softmax(axis=axis)(x)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse 3-D conv, NDHWC (reference sparse/nn/functional/conv.py:conv3d).

    weight follows the dense Conv3D layout [out_c, in_c/groups, kD, kH, kW]
    (the layer's parameterization); x is a SparseCooTensor.
    """
    from paddle_tpu import sparse
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.nn.functional.conv import conv3d as dense_conv3d
    w = weight if isinstance(weight, Tensor) else Tensor(jnp.asarray(weight))
    v = jnp.moveaxis(x._value, -1, 1)  # NDHWC -> NCDHW
    out = dense_conv3d(Tensor(v), w, bias=bias, stride=stride,
                       padding=padding, dilation=dilation, groups=groups)
    out = Tensor(jnp.moveaxis(out._value, 1, -1))
    return sparse.to_sparse_coo(out)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse conv: outputs only at input active sites."""
    from paddle_tpu import sparse
    from paddle_tpu.core.tensor import Tensor
    active = (x._value != 0).any(axis=-1, keepdims=True)
    out = conv3d(x, weight, bias=bias, stride=stride, padding=padding,
                 dilation=dilation, groups=groups)
    masked = jnp.where(active, out._value, 0.0)
    return sparse.to_sparse_coo(Tensor(masked))


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    from paddle_tpu.sparse import nn as sparse_nn
    return sparse_nn.MaxPool3D(kernel_size, stride=stride, padding=padding,
                               data_format=data_format)(x)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse attention: softmax(QK^T/sqrt(d)) * V computed ONLY at the
    positions stored in ``sparse_mask`` (a SparseCsrTensor of dense shape
    [batch*num_heads, seq, seq]).

    Reference: python/paddle/sparse/nn/functional/transformer.py:attention
    (phi kernel sparse_fused_attention). Mask conventions match the phi
    kernel: entries where key_padding_mask[b, j] == 0 or
    attn_mask[i, j] == 0 score -inf before the softmax.

    TPU-native: one gather per stored entry for q-rows/k-cols, a fused
    dot over head_dim, segment-softmax over each (bh, i) row, and a
    segment-sum of p * V — all static-shaped (nnz fixed at trace time),
    so it jits and the VJP falls out of jax.grad. Memory is O(nnz * d)
    instead of O(seq^2 * d) — the same win the reference gets from CSR.
    """
    from paddle_tpu.core.dispatch import apply
    from paddle_tpu.core.tensor import Tensor

    def _arr(t):
        return t._value if isinstance(t, Tensor) else jnp.asarray(t)

    q, k, v = _arr(query), _arr(key), _arr(value)
    b, h, s, d = q.shape
    # mask entries as (bh, i, j) coordinates; CSR construction already
    # produced batch-major 3-row BCOO indices, and the reference requires
    # equal nnz per bh batch ("nnz of each batch must be the same"), so
    # the per-batch reshape below is exact
    idx = jnp.asarray(sparse_mask._bcoo.indices)          # [nnz_total, 3]
    nnz_total = idx.shape[0]
    if nnz_total % (b * h) != 0:
        raise ValueError(
            "sparse attention requires equal nnz per batch*head "
            f"(got total nnz {nnz_total} over {b * h} batches)")
    nnz = nnz_total // (b * h)
    # divisible-but-unequal per-batch counts would silently shift entries
    # across batches in the reshape below; validate when concrete
    try:
        batch_ids = np.asarray(idx[:, 0])
        counts = np.bincount(batch_ids, minlength=b * h)
        if not (counts == nnz).all():
            raise ValueError(
                "sparse attention requires EQUAL nnz per batch*head "
                f"(per-batch counts {counts.tolist()}); the reference has "
                "the same contract ('nnz of each batch must be the same')")
    except (TypeError, jax.errors.TracerArrayConversionError):
        pass  # traced mask: shape contract already enforced above
    row_id = idx[:, 1].reshape(b * h, nnz)
    cols = idx[:, 2].reshape(b * h, nnz)

    bh = b * h
    scale = 1.0 / np.sqrt(d)

    kp = None if key_padding_mask is None else _arr(key_padding_mask)
    am = None if attn_mask is None else _arr(attn_mask)

    def per_batch(args):
        qi, ki, vi, rows, js, bidx = args
        scores = jnp.einsum("ed,ed->e", qi[rows], ki[js]) * scale
        neg = jnp.asarray(-jnp.inf, scores.dtype)
        if kp is not None:
            scores = jnp.where(kp[bidx][js] == 0, neg, scores)
        if am is not None:
            scores = jnp.where(am[rows, js] == 0, neg, scores)
        mx = jax.ops.segment_max(scores, rows, num_segments=s)
        # a fully-masked row has mx = -inf; exp(-inf - -inf) would be NaN —
        # zero the row instead (same as a softmax over an empty support)
        safe_mx = jnp.where(jnp.isfinite(mx), mx, 0.0)
        e = jnp.exp(scores - safe_mx[rows])
        denom = jax.ops.segment_sum(e, rows, num_segments=s)
        p = e / jnp.maximum(denom[rows], 1e-30)
        out = jax.ops.segment_sum(p[:, None] * vi[js], rows, num_segments=s)
        return out

    batch_of_bh = jnp.arange(bh) // h

    def _fwd(qa, ka, va):
        qf_, kf_, vf_ = (a.reshape(bh, s, d) for a in (qa, ka, va))
        o = jax.vmap(lambda qi, ki, vi, rows, js, bidx: per_batch(
            (qi, ki, vi, rows, js, bidx)))(qf_, kf_, vf_, row_id, cols, batch_of_bh)
        return o.reshape(b, h, s, d).astype(qa.dtype)

    return apply(_fwd, query, key, value)
