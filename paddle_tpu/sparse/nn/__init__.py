"""paddle.sparse.nn — layers over sparse tensors.

Reference: python/paddle/sparse/nn/ (ReLU, Conv3D/SubmConv3D, BatchNorm).
TPU-native: zero-preserving activations act on BCOO stored values; the 3-D
convs run as gathered dense windows (XLA scatter/gather) over the dense
mirror — correct semantics, with true submanifold masking for SubmConv3D.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.nn.layer.layers import Layer

from paddle_tpu.sparse.nn import functional  # noqa: F401


class ReLU(Layer):
    def forward(self, x):
        from paddle_tpu import sparse
        return sparse.relu(x)


class Softmax(Layer):
    """Row-wise softmax over stored values (CSR semantics)."""

    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        from paddle_tpu import sparse
        if not isinstance(x, sparse.SparseCooTensor):
            import paddle_tpu.nn.functional as F
            return F.softmax(x, axis=self.axis)
        if self.axis not in (-1, x._value.ndim - 1):
            raise ValueError("sparse softmax supports only the last axis")
        # softmax over the STORED entries of each row (CSR nnz semantics:
        # explicitly-stored zeros participate; implicit zeros do not)
        bcoo = x._bcoo
        vals = bcoo.data
        idx = bcoo.indices  # (nnz, ndim)
        shape = bcoo.shape
        # linearize all leading dims into one segment id per row
        row = jnp.zeros(idx.shape[0], dtype=jnp.int32)
        for d in range(len(shape) - 1):
            row = row * shape[d] + idx[:, d].astype(jnp.int32)
        nrows = int(np.prod(shape[:-1])) or 1
        mx = jax.ops.segment_max(vals, row, num_segments=nrows)
        e = jnp.exp(vals - mx[row])
        denom = jax.ops.segment_sum(e, row, num_segments=nrows)
        out = e / denom[row]
        return sparse.SparseCooTensor(jnp.swapaxes(idx, 0, 1), out, shape)


class Conv3D(Layer):
    """Sparse 3-D conv (NDHWC, like the reference's sparse Conv3D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        from paddle_tpu.nn.layer.conv import Conv3D as DenseConv3D
        # reuse the dense conv's parameterization; compute runs NCDHW
        self._conv = DenseConv3D(in_channels, out_channels, kernel_size,
                                 stride=stride, padding=padding,
                                 dilation=dilation, groups=groups,
                                 weight_attr=weight_attr,
                                 bias_attr=bias_attr)
        self.weight = self._conv.weight
        self.bias = self._conv.bias

    def _dense_ncdhw(self, x):
        from paddle_tpu import sparse
        from paddle_tpu.core.tensor import Tensor
        v = x._value if isinstance(x, sparse.SparseCooTensor) else x._value
        return Tensor(jnp.moveaxis(v, -1, 1))     # NDHWC -> NCDHW

    def forward(self, x):
        from paddle_tpu import sparse
        from paddle_tpu.core.tensor import Tensor
        out = self._conv(self._dense_ncdhw(x))
        out = Tensor(jnp.moveaxis(out._value, 1, -1))  # -> NDHWC
        return sparse.to_sparse_coo(out)


class SubmConv3D(Conv3D):
    """Submanifold conv: outputs only at input active sites.

    r5 (VERDICT #5) TRUE SPARSE COMPUTE: the TPU-native analogue of the
    reference rulebook (python/paddle/sparse/nn/layer/conv.py + phi
    sparse gather-gemm-scatter kernels). Per kernel offset, the input
    site holding each neighbor is located by a sorted-coordinate join
    (argsort + searchsorted — O(nnz·K³·log nnz) VPU work, no
    volume-sized buffer), the neighbor features gather into
    [nnz, K³·Cin], and ONE dense MXU dot against [K³·Cin, Cout]
    produces every active output. Work scales with nnz, not volume.
    The dense mirror stays as the oracle (`forward_dense`) and serves
    grouped convs.
    """

    def forward(self, x):
        from paddle_tpu import sparse
        # fast path needs SITE-layout COO: 4 sparse dims (N,D,H,W) with
        # a dense channel (to_sparse_coo(x, sparse_dim=4), the
        # reference's sparse-conv input format). Scalar COO / grouped /
        # strided fall back to the dense-mirror oracle.
        if (not isinstance(x, sparse.SparseCooTensor)
                or x._bcoo.indices.shape[-1] != 4
                or x._bcoo.data.ndim != 2
                or self._conv._groups != 1
                or any(s != 1 for s in self._conv._stride)):
            return self.forward_dense(x)
        return self._forward_gather(x)

    def forward_dense(self, x):
        from paddle_tpu import sparse
        from paddle_tpu.core.tensor import Tensor
        active = (x._value != 0).any(axis=-1, keepdims=True)
        out = self._conv(self._dense_ncdhw(x))
        out = jnp.moveaxis(out._value, 1, -1)
        out = jnp.where(active, out, 0.0)
        return sparse.to_sparse_coo(Tensor(out))

    def _forward_gather(self, x):
        from paddle_tpu import sparse
        from paddle_tpu.core.dispatch import apply

        bcoo = x._bcoo
        N, D, H, W, _ = bcoo.shape
        if N * D * H * W >= 2 ** 31:
            # the sorted-join key is an int32 flattened site id (jax
            # x64 is off); beyond 2^31 sites it would wrap and silently
            # match wrong neighbors — refuse loudly. Tile the volume or
            # enable jax x64 for larger extents.
            raise ValueError(
                f"SubmConv3D gather path: volume {N}x{D}x{H}x{W} "
                f"exceeds int32 site indexing ({N * D * H * W:.2e} >= "
                f"2^31)")
        Cout = self.weight.shape[0]
        idx = jnp.asarray(bcoo.indices, jnp.int32)       # [nnz, 4]
        kd, kh, kw = self._conv._kernel_size
        dil = self._conv._dilation
        offs = [((dz - kd // 2) * dil[0], (dy - kh // 2) * dil[1],
                 (dx - kw // 2) * dil[2])
                for dz in range(kd) for dy in range(kh) for dx in range(kw)]

        def fn(vals, w, b):
            n, z, y, xx = (idx[:, i] for i in range(4))
            flat = ((n * D + z) * H + y) * W + xx
            order = jnp.argsort(flat)
            sflat = flat[order]
            cols = []
            for dz, dy, dx in offs:
                zq, yq, xq = z + dz, y + dy, xx + dx
                valid = ((zq >= 0) & (zq < D) & (yq >= 0) & (yq < H) &
                         (xq >= 0) & (xq < W))
                qflat = ((n * D + jnp.clip(zq, 0, D - 1)) * H +
                         jnp.clip(yq, 0, H - 1)) * W + jnp.clip(xq, 0, W - 1)
                pos = jnp.clip(jnp.searchsorted(sflat, qflat),
                               0, sflat.shape[0] - 1)
                found = (sflat[pos] == qflat) & valid
                src = order[pos]
                cols.append(jnp.where(found[:, None], vals[src], 0))
            g = jnp.concatenate(cols, axis=-1)           # [nnz, K3*Cin]
            # weight [Cout, Cin, kd, kh, kw] -> [K3*Cin, Cout] matching
            # the offs-major, Cin-minor gather layout
            wmat = jnp.transpose(w, (2, 3, 4, 1, 0)).reshape(
                g.shape[-1], Cout)
            out = jax.lax.dot_general(
                g, wmat, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(vals.dtype)
            return out + b.astype(out.dtype) if b is not None else out

        if self.bias is not None:
            out_vals = apply(fn, x.values(), self.weight, self.bias)
        else:
            out_vals = apply(lambda v, w: fn(v, w, None),
                             x.values(), self.weight)
        out = sparse.SparseCooTensor(jnp.swapaxes(idx, 0, 1),
                                     out_vals._value,
                                     (N, D, H, W, Cout),
                                     x.stop_gradient)
        # values() must stay ON the tape (the constructor wraps raw
        # arrays): grads flow sparse-layer-to-sparse-layer through the
        # stored values, exactly like the reference's sparse autograd
        out._values = out_vals
        return out


class BatchNorm(Layer):
    """BatchNorm over the channel (last) dim of sparse NDHWC activations;
    statistics over stored (active) sites only."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        from paddle_tpu.nn.layer.norm import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon)

    def forward(self, x):
        from paddle_tpu import sparse
        from paddle_tpu.core.tensor import Tensor
        vals = x.values()                       # [nnz, C]
        out_vals = self._bn(vals)
        idx = jnp.swapaxes(x._bcoo.indices, 0, 1)
        return sparse.SparseCooTensor(idx, out_vals._value, x._bcoo.shape,
                                      x.stop_gradient)


class LeakyReLU(Layer):
    """Zero-preserving leaky ReLU on stored values (reference
    sparse/nn/layer/activation.py LeakyReLU)."""

    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        from paddle_tpu import sparse
        slope = self.negative_slope
        return sparse._unary_on_values(
            lambda v: jnp.where(v >= 0, v, slope * v))(x)


class ReLU6(Layer):
    """min(max(0, v), 6) on stored values (reference ReLU6)."""

    def forward(self, x):
        from paddle_tpu import sparse
        return sparse._unary_on_values(
            lambda v: jnp.clip(v, 0.0, 6.0))(x)


class SyncBatchNorm(BatchNorm):
    """Cross-replica BatchNorm over sparse activations: on TPU the
    statistics sync falls out of jit over the mesh (the same design as
    dense nn.SyncBatchNorm), so this shares BatchNorm's implementation
    (reference sparse/nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class MaxPool3D(Layer):
    """Max pool over sparse NDHWC activations (reference
    sparse/nn/layer/pooling.py MaxPool3D): like the reference's rulebook
    kernel, the max runs over ACTIVE (stored) sites only — implicit
    zeros do not participate, so all-negative active windows keep their
    true (negative) max — and windows with no active site produce no
    output entry."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        if data_format != "NDHWC":
            raise ValueError(
                "sparse MaxPool3D only supports data_format='NDHWC' "
                "(the reference kernel has the same contract)")
        self.kernel_size = kernel_size
        self.stride = stride or kernel_size
        self.padding = padding

    def forward(self, x):
        from paddle_tpu import sparse
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.nn.functional.pooling import max_pool3d

        dense = x._value                         # [N, D, H, W, C]
        idx = x._bcoo.indices                    # [nnz, 5]
        mask = jnp.zeros(dense.shape, jnp.float32).at[
            tuple(idx[:, i] for i in range(idx.shape[1]))].set(1.0)
        neg_inf = jnp.asarray(-jnp.inf, dense.dtype)
        masked = jnp.where(mask > 0, dense, neg_inf)
        pooled = max_pool3d(Tensor(masked), self.kernel_size,
                            stride=self.stride, padding=self.padding,
                            data_format="NDHWC")._value
        pooled_mask = max_pool3d(Tensor(mask), self.kernel_size,
                                 stride=self.stride, padding=self.padding,
                                 data_format="NDHWC")._value
        out = jnp.where(pooled_mask > 0, pooled, 0.0)
        return sparse.to_sparse_coo(Tensor(out), out.ndim)
