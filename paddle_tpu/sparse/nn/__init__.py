"""paddle.sparse.nn — layers over sparse tensors.

Reference: python/paddle/sparse/nn/ (ReLU, Conv3D/SubmConv3D, BatchNorm).
TPU-native: zero-preserving activations act on BCOO stored values; the 3-D
convs run as gathered dense windows (XLA scatter/gather) over the dense
mirror — correct semantics, with true submanifold masking for SubmConv3D.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.nn.layer.layers import Layer

from paddle_tpu.sparse.nn import functional  # noqa: F401


class ReLU(Layer):
    def forward(self, x):
        from paddle_tpu import sparse
        return sparse.relu(x)


class Softmax(Layer):
    """Row-wise softmax over stored values (CSR semantics)."""

    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        from paddle_tpu import sparse
        if not isinstance(x, sparse.SparseCooTensor):
            import paddle_tpu.nn.functional as F
            return F.softmax(x, axis=self.axis)
        if self.axis not in (-1, x.ndim - 1):    # .ndim never densifies
            raise ValueError("sparse softmax supports only the last axis")
        # softmax over the STORED entries of each row (CSR nnz semantics:
        # explicitly-stored zeros participate; implicit zeros do not).
        # Rows masked dead by a cap-padding producer are ABSENT: they
        # neither shift the max nor join the denominator, and emit 0.
        from paddle_tpu.core.dispatch import apply
        bcoo = x._bcoo
        idx = bcoo.indices  # (nnz, ndim)
        shape = bcoo.shape
        mask = x._live_mask

        def fn(vals):
            if vals.ndim == 2:
                # site-layout COO (dense trailing channel): axis=-1 is
                # the DENSE dim — softmax is per-row over channels (no
                # segment ids needed on this path)
                out = jax.nn.softmax(vals, axis=-1)
                if mask is not None:
                    out = jnp.where(mask[:, None], out, 0)
                return out
            # scalar COO: linearize leading dims into a segment per row
            row = jnp.zeros(idx.shape[0], dtype=jnp.int32)
            for d in range(len(shape) - 1):
                row = row * shape[d] + idx[:, d].astype(jnp.int32)
            nrows = int(np.prod(shape[:-1])) or 1
            if mask is not None:
                row = jnp.where(mask, row, nrows)   # dead -> spill row
                nseg = nrows + 1
            else:
                nseg = nrows
            mx = jax.ops.segment_max(vals, row, num_segments=nseg)
            e = jnp.exp(vals - mx[row])
            denom = jax.ops.segment_sum(e, row, num_segments=nseg)
            out = e / denom[row]
            return jnp.where(mask, out, 0) if mask is not None else out

        tv = apply(fn, x.values())   # on the tape: chains backprop
        res = sparse.SparseCooTensor(jnp.swapaxes(idx, 0, 1), tv._value,
                                     shape, x.stop_gradient)
        res._values = tv
        res._live_mask = mask
        return res


def _flat_sites(idx, D, H, W):
    n, z, y, x = (idx[:, i] for i in range(4))
    return ((n * D + z) * H + y) * W + x


def _prep_join(idx, vals, D, H, W, sent, mask=None):
    """Sort + COALESCE the input sites: returns (cflat, cvals, rep).
    cflat is ascending unique flat site ids padded with `sent`, cvals
    the per-site SUMMED features at matching positions, and rep a bool
    over the ORIGINAL rows marking each live site's first occurrence.
    Coalescing makes the join exact for inputs carrying duplicate
    coordinates or explicit zeros (e.g. the cap-padded output of a
    strided sparse conv); rows masked dead by `mask` are excluded
    entirely (their flats sort to `sent`)."""
    flat = _flat_sites(idx, D, H, W)
    if mask is not None:
        flat = jnp.where(mask, flat, sent)
    order = jnp.argsort(flat)
    sf = flat[order]
    sv = vals[order]
    first = jnp.concatenate([jnp.ones((1,), bool), sf[1:] != sf[:-1]])
    seg = jnp.cumsum(first) - 1
    n = flat.shape[0]
    cvals = jax.ops.segment_sum(sv, seg, num_segments=n)
    pos_live = (jnp.arange(n) < seg[-1] + 1)
    cflat = jnp.where(pos_live, jax.ops.segment_max(sf, seg,
                                                    num_segments=n), sent)
    # dead rows grouped under `sent` must not elect a representative
    rep = jnp.zeros(n, bool).at[order].set(first & (sf < sent))
    return cflat, cvals, rep


def _join_gather(cflat, cvals, qflat, valid):
    """Features of the input site at each query flat id (0 when the
    site is inactive or the query invalid)."""
    pos = jnp.clip(jnp.searchsorted(cflat, qflat), 0, cflat.shape[0] - 1)
    found = (cflat[pos] == qflat) & valid
    return jnp.where(found[:, None], cvals[pos], 0)


def _candidate_out_sites(idx, in_mask, offs, dims, strides, pads,
                         out_dims, cap, out_sent):
    """Output-site discovery shared by the strided conv and pooling:
    every kernel tap's image of every LIVE input site, deduped by
    unique() under the safe static cap. Returns (live, on, oz, oy, ox)
    — decoded out coordinates with `live` marking real (non-padding)
    rows."""
    D, H, W = dims
    sd, sh, sw = strides
    pd, ph, pw = pads
    Do, Ho, Wo = out_dims
    n, z, y, xx = (idx[:, i] for i in range(4))
    cands = []
    for dz, dy, dx in offs:
        oz_n = z + pd - dz
        oy_n = y + ph - dy
        ox_n = xx + pw - dx
        v = ((oz_n >= 0) & (oz_n % sd == 0) &
             (oy_n >= 0) & (oy_n % sh == 0) &
             (ox_n >= 0) & (ox_n % sw == 0))
        if in_mask is not None:
            v &= in_mask
        oz, oy, ox = oz_n // sd, oy_n // sh, ox_n // sw
        v &= (oz < Do) & (oy < Ho) & (ox < Wo)
        cand = ((n * Do + oz) * Ho + oy) * Wo + ox
        cands.append(jnp.where(v, cand, out_sent))
    uniq = jnp.unique(jnp.concatenate(cands), size=cap,
                      fill_value=out_sent)
    live = uniq < out_sent
    on = uniq // (Do * Ho * Wo)
    rem = uniq % (Do * Ho * Wo)
    return (live, on, rem // (Ho * Wo), (rem // Wo) % Ho, rem % Wo)


def _tap_query(site, off, dims, strides, pads, live):
    """Input-site query flat id + validity for one output site set and
    one kernel tap."""
    on, oz, oy, ox = site
    dz, dy, dx = off
    D, H, W = dims
    sd, sh, sw = strides
    pd, ph, pw = pads
    iz = oz * sd - pd + dz
    iy = oy * sh - ph + dy
    ix = ox * sw - pw + dx
    v = (live & (iz >= 0) & (iz < D) & (iy >= 0) & (iy < H) &
         (ix >= 0) & (ix < W))
    qflat = ((on * D + jnp.clip(iz, 0, D - 1)) * H +
             jnp.clip(iy, 0, H - 1)) * W + jnp.clip(ix, 0, W - 1)
    return qflat, v


def _pad_oidx(live, site):
    """Out-index array with cap-padded rows duplicating the FIRST live
    site's coords (coalesces away downstream; falls back to coord 0
    when nothing is live — every value is 0 and the mask all-dead)."""
    return jnp.stack([jnp.where(live, c, jnp.where(live[0], c[0], 0))
                      for c in site], 0)


def _empty_site_coo(sparse_mod, shape, dtype, stop_gradient):
    """Zero-nnz site-layout COO (empty sparse input short-circuit)."""
    idx = jnp.zeros((4, 0), jnp.int32)
    vals = jnp.zeros((0, shape[-1]), dtype)
    return sparse_mod.SparseCooTensor(idx, vals, shape, stop_gradient)


def _pad3(p):
    if isinstance(p, int):
        return (p, p, p)
    if isinstance(p, (list, tuple)) and len(p) == 3 and \
            all(isinstance(v, int) for v in p):
        return tuple(p)
    return None


class Conv3D(Layer):
    """Sparse 3-D conv (NDHWC, like the reference's sparse Conv3D).

    r5: strided/non-submanifold sparse compute — output active sites are
    the union of every kernel tap's image (the reference rulebook's
    out-index set), built as a unique() over the nnz·K³ candidate ids
    with a mathematically safe static cap (min(nnz·K³, out volume) ≥
    the true count, so no site is ever silently dropped); features
    gather through the same sorted-join as SubmConv3D into ONE
    [cap, K³·Cin] × [K³·Cin, Cout] MXU dot. Cap-padded rows carry
    (site-0, value-0) entries — summed away by any consumer that
    coalesces (to_dense, the next sparse conv's join)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        from paddle_tpu.nn.layer.conv import Conv3D as DenseConv3D
        # reuse the dense conv's parameterization; compute runs NCDHW
        self._conv = DenseConv3D(in_channels, out_channels, kernel_size,
                                 stride=stride, padding=padding,
                                 dilation=dilation, groups=groups,
                                 weight_attr=weight_attr,
                                 bias_attr=bias_attr)
        self.weight = self._conv.weight
        self.bias = self._conv.bias

    def _dense_ncdhw(self, x):
        from paddle_tpu import sparse
        from paddle_tpu.core.tensor import Tensor
        v = x._value if isinstance(x, sparse.SparseCooTensor) else x._value
        return Tensor(jnp.moveaxis(v, -1, 1))     # NDHWC -> NCDHW

    def forward(self, x):
        from paddle_tpu import sparse
        pad = _pad3(self._conv._padding)
        if (isinstance(x, sparse.SparseCooTensor)
                and x._bcoo.indices.shape[-1] == 4
                and x._bcoo.data.ndim == 2
                and self._conv._groups == 1 and pad is not None):
            return self._forward_gather_strided(x, pad)
        return self.forward_dense(x)

    def forward_dense(self, x):
        from paddle_tpu import sparse
        from paddle_tpu.core.tensor import Tensor
        out = self._conv(self._dense_ncdhw(x))
        out = Tensor(jnp.moveaxis(out._value, 1, -1))  # -> NDHWC
        return sparse.to_sparse_coo(out)

    def _forward_gather_strided(self, x, pad):
        from paddle_tpu import sparse
        from paddle_tpu.core.dispatch import apply

        bcoo = x._bcoo
        N, D, H, W, _ = bcoo.shape
        kd, kh, kw = self._conv._kernel_size
        sd, sh, sw = self._conv._stride
        dd, dh, dw = self._conv._dilation
        pd, ph, pw = pad
        Do = (D + 2 * pd - dd * (kd - 1) - 1) // sd + 1
        Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        Cout = self.weight.shape[0]
        if min(Do, Ho, Wo) <= 0:
            # kernel larger than the padded input: no output sites
            return _empty_site_coo(
                sparse, (N, max(Do, 0), max(Ho, 0), max(Wo, 0), Cout),
                bcoo.data.dtype, x.stop_gradient)
        if max(N * D * H * W, N * Do * Ho * Wo) >= 2 ** 31:
            raise ValueError(
                "sparse Conv3D gather path: volume exceeds int32 site "
                "indexing; tile the volume")
        idx = jnp.asarray(bcoo.indices, jnp.int32)
        nnz = idx.shape[0]
        # dilation-scaled tap offsets; order matches the wmat reshape
        offs = [(dz * dd, dy * dh, dx * dw) for dz in range(kd)
                for dy in range(kh) for dx in range(kw)]
        in_sent = N * D * H * W
        out_sent = N * Do * Ho * Wo
        cap = min(nnz * len(offs), out_sent)
        if nnz == 0 or cap <= 0:
            return _empty_site_coo(sparse, (N, Do, Ho, Wo, Cout),
                                   bcoo.data.dtype, x.stop_gradient)
        in_mask = x._live_mask
        dims, strides, pads = (D, H, W), (sd, sh, sw), (pd, ph, pw)

        def fn(vals, w, b):
            cflat, cvals, _ = _prep_join(idx, vals, D, H, W, in_sent,
                                         in_mask)
            site = _candidate_out_sites(idx, in_mask, offs, dims, strides,
                                        pads, (Do, Ho, Wo), cap, out_sent)
            live, *coords = site
            cols = []
            for off in offs:
                qflat, v = _tap_query(coords, off, dims, strides, pads,
                                      live)
                cols.append(_join_gather(cflat, cvals, qflat, v))
            g = jnp.concatenate(cols, axis=-1)
            wmat = jnp.transpose(w, (2, 3, 4, 1, 0)).reshape(
                g.shape[-1], Cout)
            out = jax.lax.dot_general(
                g, wmat, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(vals.dtype)
            if b is not None:
                out = out + b.astype(out.dtype)
            out = jnp.where(live[:, None], out, 0)
            return out, _pad_oidx(live, coords), live

        if self.bias is not None:
            out_vals, oidx, live = apply(fn, x.values(), self.weight,
                                         self.bias)
        else:
            out_vals, oidx, live = apply(lambda v, w: fn(v, w, None),
                                         x.values(), self.weight)
        out = sparse.SparseCooTensor(oidx._value, out_vals._value,
                                     (N, Do, Ho, Wo, Cout),
                                     x.stop_gradient)
        out._values = out_vals
        out._live_mask = live._value
        return out


class SubmConv3D(Conv3D):
    """Submanifold conv: outputs only at input active sites.

    r5 (VERDICT #5) TRUE SPARSE COMPUTE: the TPU-native analogue of the
    reference rulebook (python/paddle/sparse/nn/layer/conv.py + phi
    sparse gather-gemm-scatter kernels). Per kernel offset, the input
    site holding each neighbor is located by a sorted-coordinate join
    (argsort + searchsorted — O(nnz·K³·log nnz) VPU work, no
    volume-sized buffer), the neighbor features gather into
    [nnz, K³·Cin], and ONE dense MXU dot against [K³·Cin, Cout]
    produces every active output. Work scales with nnz, not volume.
    The dense mirror stays as the oracle (`forward_dense`) and serves
    grouped convs.
    """

    def forward(self, x):
        from paddle_tpu import sparse
        # fast path needs SITE-layout COO: 4 sparse dims (N,D,H,W) with
        # a dense channel (to_sparse_coo(x, sparse_dim=4), the
        # reference's sparse-conv input format). Scalar COO / grouped /
        # strided fall back to the dense-mirror oracle.
        if (not isinstance(x, sparse.SparseCooTensor)
                or x._bcoo.indices.shape[-1] != 4
                or x._bcoo.data.ndim != 2
                or self._conv._groups != 1
                or any(s != 1 for s in self._conv._stride)):
            return self.forward_dense(x)
        return self._forward_gather(x)

    def forward_dense(self, x):
        from paddle_tpu import sparse
        from paddle_tpu.core.tensor import Tensor
        active = (x._value != 0).any(axis=-1, keepdims=True)
        out = self._conv(self._dense_ncdhw(x))
        out = jnp.moveaxis(out._value, 1, -1)
        out = jnp.where(active, out, 0.0)
        return sparse.to_sparse_coo(Tensor(out))

    def _forward_gather(self, x):
        from paddle_tpu import sparse
        from paddle_tpu.core.dispatch import apply

        bcoo = x._bcoo
        N, D, H, W, _ = bcoo.shape
        if N * D * H * W >= 2 ** 31:
            # the sorted-join key is an int32 flattened site id (jax
            # x64 is off); beyond 2^31 sites it would wrap and silently
            # match wrong neighbors — refuse loudly. Tile the volume or
            # enable jax x64 for larger extents.
            raise ValueError(
                f"SubmConv3D gather path: volume {N}x{D}x{H}x{W} "
                f"exceeds int32 site indexing ({N * D * H * W:.2e} >= "
                f"2^31)")
        Cout = self.weight.shape[0]
        idx = jnp.asarray(bcoo.indices, jnp.int32)       # [nnz, 4]
        if idx.shape[0] == 0:
            return _empty_site_coo(sparse, (N, D, H, W, Cout),
                                   bcoo.data.dtype, x.stop_gradient)
        kd, kh, kw = self._conv._kernel_size
        dil = self._conv._dilation
        offs = [((dz - kd // 2) * dil[0], (dy - kh // 2) * dil[1],
                 (dx - kw // 2) * dil[2])
                for dz in range(kd) for dy in range(kh) for dx in range(kw)]

        in_mask = x._live_mask

        def fn(vals, w, b):
            n, z, y, xx = (idx[:, i] for i in range(4))
            # rep: duplicate-coordinate rows (a coalescing producer
            # upstream, e.g. a strided sparse conv's cap padding) — only
            # each site's FIRST live row carries the response, the rest
            # emit 0, so densifying the output sums to the exact value
            cflat, cvals, rep = _prep_join(idx, vals, D, H, W,
                                           N * D * H * W, in_mask)
            cols = []
            for dz, dy, dx in offs:
                zq, yq, xq = z + dz, y + dy, xx + dx
                valid = ((zq >= 0) & (zq < D) & (yq >= 0) & (yq < H) &
                         (xq >= 0) & (xq < W))
                qflat = ((n * D + jnp.clip(zq, 0, D - 1)) * H +
                         jnp.clip(yq, 0, H - 1)) * W + jnp.clip(xq, 0, W - 1)
                cols.append(_join_gather(cflat, cvals, qflat, valid))
            g = jnp.concatenate(cols, axis=-1)           # [nnz, K3*Cin]
            # weight [Cout, Cin, kd, kh, kw] -> [K3*Cin, Cout] matching
            # the offs-major, Cin-minor gather layout
            wmat = jnp.transpose(w, (2, 3, 4, 1, 0)).reshape(
                g.shape[-1], Cout)
            out = jax.lax.dot_general(
                g, wmat, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(vals.dtype)
            if b is not None:
                out = out + b.astype(out.dtype)
            return jnp.where(rep[:, None], out, 0)

        if self.bias is not None:
            out_vals = apply(fn, x.values(), self.weight, self.bias)
        else:
            out_vals = apply(lambda v, w: fn(v, w, None),
                             x.values(), self.weight)
        out = sparse.SparseCooTensor(jnp.swapaxes(idx, 0, 1),
                                     out_vals._value,
                                     (N, D, H, W, Cout),
                                     x.stop_gradient)
        # values() must stay ON the tape (the constructor wraps raw
        # arrays): grads flow sparse-layer-to-sparse-layer through the
        # stored values, exactly like the reference's sparse autograd
        out._values = out_vals
        out._live_mask = x._live_mask   # subm keeps the input's rows
        return out


class BatchNorm(Layer):
    """BatchNorm over the channel (last) dim of sparse NDHWC activations;
    statistics over stored (active) sites only."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        from paddle_tpu.nn.layer.norm import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon)

    def forward(self, x):
        from paddle_tpu import sparse
        vals = x.values()                       # [nnz, C]
        mask = getattr(x, "_live_mask", None)
        if mask is None:
            out_vals = self._bn(vals)
        else:
            out_vals = self._masked_bn(vals, mask)
        idx = jnp.swapaxes(x._bcoo.indices, 0, 1)
        out = sparse.SparseCooTensor(idx, out_vals._value, x._bcoo.shape,
                                     x.stop_gradient)
        out._values = out_vals
        out._live_mask = mask
        return out

    def _masked_bn(self, vals, mask):
        """BatchNorm over LIVE rows only (cap-padded rows from a strided
        sparse conv must neither dilute the statistics nor become
        nonzero beta values summed onto a real site)."""
        from paddle_tpu.core.dispatch import apply
        from paddle_tpu.core.engine import no_grad
        bn = self._bn
        eps, mom = bn._epsilon, bn._momentum
        training = self.training

        def fn(v, w, b, rm, rv):
            # fp32 statistics + unbiased running-var update, matching
            # the dense path (nn/functional/norm.py batch_norm) so the
            # SAME layer behaves identically masked and unmasked
            vf = v.astype(jnp.float32)
            m = mask.astype(jnp.float32)[:, None]
            alive = jnp.sum(m) > 0
            cnt = jnp.maximum(jnp.sum(m), 1.0)
            if training:
                mean = jnp.sum(vf * m, 0) / cnt
                var = jnp.sum(((vf - mean) ** 2) * m, 0) / cnt
                unbias = cnt / jnp.maximum(cnt - 1.0, 1.0)
                # an all-dead batch has NO data: fall back to the
                # running stats so the buffer blend below is a no-op
                # instead of decaying toward fabricated mean=0/var=0
                run_mean = jnp.where(alive, mean, rm)
                run_var = jnp.where(alive, var * unbias, rv)
                mean = jnp.where(alive, mean, rm)
                var = jnp.where(alive, var, rv)
            else:
                mean, var = rm, rv
                run_mean, run_var = rm, rv
            out = (vf - mean) / jnp.sqrt(var + eps)
            if w is not None:
                out = out * w
            if b is not None:
                out = out + b
            out = out.astype(v.dtype)
            return jnp.where(mask[:, None], out, 0), run_mean, run_var

        out, mean, var = apply(fn, vals, bn.weight, bn.bias,
                               bn._mean, bn._variance)
        if training:
            with no_grad():
                bn._mean._set_value(mom * bn._mean._value +
                                    (1 - mom) * mean._value)
                bn._variance._set_value(mom * bn._variance._value +
                                        (1 - mom) * var._value)
        return out


class LeakyReLU(Layer):
    """Zero-preserving leaky ReLU on stored values (reference
    sparse/nn/layer/activation.py LeakyReLU)."""

    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        from paddle_tpu import sparse
        slope = self.negative_slope
        return sparse._unary_on_values(
            lambda v: jnp.where(v >= 0, v, slope * v))(x)


class ReLU6(Layer):
    """min(max(0, v), 6) on stored values (reference ReLU6)."""

    def forward(self, x):
        from paddle_tpu import sparse
        return sparse._unary_on_values(
            lambda v: jnp.clip(v, 0.0, 6.0))(x)


class SyncBatchNorm(BatchNorm):
    """Cross-replica BatchNorm over sparse activations: on TPU the
    statistics sync falls out of jit over the mesh (the same design as
    dense nn.SyncBatchNorm), so this shares BatchNorm's implementation
    (reference sparse/nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class MaxPool3D(Layer):
    """Max pool over sparse NDHWC activations (reference
    sparse/nn/layer/pooling.py MaxPool3D): like the reference's rulebook
    kernel, the max runs over ACTIVE (stored) sites only — implicit
    zeros do not participate, so all-negative active windows keep their
    true (negative) max — and windows with no active site produce no
    output entry."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        if data_format != "NDHWC":
            raise ValueError(
                "sparse MaxPool3D only supports data_format='NDHWC' "
                "(the reference kernel has the same contract)")
        self.kernel_size = kernel_size
        self.stride = stride or kernel_size
        self.padding = padding

    def _triple(self, v):
        return (v, v, v) if isinstance(v, int) else tuple(v)

    def forward(self, x):
        from paddle_tpu import sparse
        if (isinstance(x, sparse.SparseCooTensor)
                and x._bcoo.indices.shape[-1] == 4
                and x._bcoo.data.ndim == 2):
            return self._forward_gather(x)
        return self._forward_dense(x)

    def _forward_gather(self, x):
        """r5 nnz path: same candidate-site/sorted-join machinery as the
        strided conv, combined by max over taps — O(nnz·K³), no dense
        volume. Windows with no active site produce dead (masked) rows."""
        from paddle_tpu import sparse
        from paddle_tpu.core.dispatch import apply

        bcoo = x._bcoo
        N, D, H, W, C = bcoo.shape
        kd, kh, kw = self._triple(self.kernel_size)
        sd, sh, sw = self._triple(self.stride)
        pd, ph, pw = self._triple(self.padding)
        Do = (D + 2 * pd - kd) // sd + 1
        Ho = (H + 2 * ph - kh) // sh + 1
        Wo = (W + 2 * pw - kw) // sw + 1
        if min(Do, Ho, Wo) <= 0:
            return _empty_site_coo(
                sparse, (N, max(Do, 0), max(Ho, 0), max(Wo, 0), C),
                bcoo.data.dtype, x.stop_gradient)
        if max(N * D * H * W, N * Do * Ho * Wo) >= 2 ** 31:
            raise ValueError("sparse MaxPool3D: volume exceeds int32 "
                             "site indexing; tile the volume")
        idx = jnp.asarray(bcoo.indices, jnp.int32)
        nnz = idx.shape[0]
        offs = [(dz, dy, dx) for dz in range(kd)
                for dy in range(kh) for dx in range(kw)]
        in_sent = N * D * H * W
        out_sent = N * Do * Ho * Wo
        cap = min(nnz * len(offs), out_sent)
        if nnz == 0 or cap <= 0:
            return _empty_site_coo(sparse, (N, Do, Ho, Wo, C),
                                   bcoo.data.dtype, x.stop_gradient)
        in_mask = x._live_mask
        dims, strides, pads = (D, H, W), (sd, sh, sw), (pd, ph, pw)

        def fn(vals):
            cflat, cvals, _ = _prep_join(idx, vals, D, H, W, in_sent,
                                         in_mask)
            site = _candidate_out_sites(idx, in_mask, offs, dims, strides,
                                        pads, (Do, Ho, Wo), cap, out_sent)
            live, *coords = site
            neg = jnp.asarray(-jnp.inf, jnp.float32)
            best = jnp.full((cap, C), neg)
            for off in offs:
                qflat, v = _tap_query(coords, off, dims, strides, pads,
                                      live)
                pos = jnp.clip(jnp.searchsorted(cflat, qflat),
                               0, cflat.shape[0] - 1)
                found = (cflat[pos] == qflat) & v
                tap = jnp.where(found[:, None],
                                cvals[pos].astype(jnp.float32), neg)
                best = jnp.maximum(best, tap)
            # every live out site has >=1 active tap by construction
            out = jnp.where(live[:, None], best, 0).astype(vals.dtype)
            return out, _pad_oidx(live, coords), live

        out_vals, oidx, live = apply(fn, x.values())
        out = sparse.SparseCooTensor(oidx._value, out_vals._value,
                                     (N, Do, Ho, Wo, C), x.stop_gradient)
        out._values = out_vals
        out._live_mask = live._value
        return out

    def _forward_dense(self, x):
        from paddle_tpu import sparse
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.nn.functional.pooling import max_pool3d

        dense = x._value                         # [N, D, H, W, C]
        idx = x._bcoo.indices                    # [nnz, 5]
        mask = jnp.zeros(dense.shape, jnp.float32).at[
            tuple(idx[:, i] for i in range(idx.shape[1]))].set(1.0)
        neg_inf = jnp.asarray(-jnp.inf, dense.dtype)
        masked = jnp.where(mask > 0, dense, neg_inf)
        pooled = max_pool3d(Tensor(masked), self.kernel_size,
                            stride=self.stride, padding=self.padding,
                            data_format="NDHWC")._value
        pooled_mask = max_pool3d(Tensor(mask), self.kernel_size,
                                 stride=self.stride, padding=self.padding,
                                 data_format="NDHWC")._value
        out = jnp.where(pooled_mask > 0, pooled, 0.0)
        return sparse.to_sparse_coo(Tensor(out), out.ndim)
