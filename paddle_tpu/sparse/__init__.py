"""Sparse tensors. Reference: python/paddle/sparse/ (COO/CSR tensor
creation in python/paddle/sparse/creation.py, unary/binary/matmul ops,
sparse nn layers).

TPU-native: backed by jax.experimental.sparse BCOO — XLA lowers
bcoo_dot_general to gather/scatter+MXU programs, so spmm genuinely skips
zero blocks. The SparseCooTensor also keeps a dense mirror (`_value`) so
every dense paddle_tpu op still accepts it; ops below prefer the BCOO path
and fall back to dense where BCOO lacks a kernel.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply, unwrap
from paddle_tpu.core.tensor import Tensor

try:
    from jax.experimental import sparse as jsparse
    _HAS_BCOO = True
except Exception:  # pragma: no cover
    _HAS_BCOO = False


class SparseCooTensor(Tensor):
    """COO tensor whose PRIMARY representation is the BCOO triplet —
    construction allocates O(nnz); the dense mirror `_value` (which lets
    every dense paddle_tpu op still accept a sparse tensor) materializes
    LAZILY on first touch and is cached. Sparse-aware ops below consult
    `_bcoo` only and never trigger it."""

    def __init__(self, indices, values, shape, stop_gradient=True):
        iv = unwrap(indices)
        vv = unwrap(values)
        self._bcoo = jsparse.BCOO((vv, jnp.swapaxes(iv, 0, 1)),
                                  shape=tuple(int(s) for s in shape))
        self._dense_cache = None
        # static-shape padding convention: producers whose true nnz is
        # data-dependent (e.g. strided sparse conv under jit) carry a
        # bool [nnz] row mask here; None = every stored row is live.
        # Padded rows hold value 0 at a duplicated live coordinate, so
        # coalescing consumers (to_dense, conv joins) need no mask —
        # row-wise consumers (BatchNorm, Softmax) must honor it.
        self._live_mask = None
        # Tensor.__init__ would require a dense value; init only the
        # non-storage fields so nothing materializes at construction
        self._init_meta(stop_gradient)
        self._indices = Tensor(iv)
        self._values = Tensor(vv)

    # ---- lazy dense mirror ----
    @property
    def _value(self):
        if self._dense_cache is None:
            self._dense_cache = self._bcoo.todense()
        return self._dense_cache

    @_value.setter
    def _value(self, v):
        # a direct rebind (in-place dense op, state restore) makes the
        # dense value authoritative; metadata below follows it
        self._dense_cache = v

    def _meta_src(self):
        """Once the dense mirror exists (lazily materialized or rebound
        by an in-place op) it is authoritative for metadata; before that,
        metadata comes from the BCOO triplet without densifying."""
        return self._bcoo if self._dense_cache is None else \
            self._dense_cache

    # ---- metadata (must not densify a pristine sparse tensor) ----
    @property
    def shape(self):
        return list(self._meta_src().shape)

    @property
    def ndim(self):
        return self._meta_src().ndim

    @property
    def dim(self):
        return self._meta_src().ndim

    @property
    def rank(self):
        return self._meta_src().ndim

    @property
    def size(self):
        s = self._meta_src().shape
        return int(np.prod(s)) if s else 1

    @property
    def dtype(self):
        return self._values.dtype if self._dense_cache is None else \
            Tensor.dtype.fget(self)

    def indices(self):
        return self._indices

    def values(self):
        return self._values

    def to_dense(self):
        return Tensor(self._value)

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def nnz(self):
        return int(self._bcoo.nse)

    def coalesce(self):
        b = self._bcoo.sum_duplicates()
        return SparseCooTensor(jnp.swapaxes(b.indices, 0, 1), b.data,
                               b.shape, self.stop_gradient)

    def t(self):
        return transpose(self, [1, 0])


class SparseCsrTensor(SparseCooTensor):
    """CSR surface over the same BCOO backing (crows kept for API parity)."""

    def __init__(self, crows, cols, values, shape, stop_gradient=True):
        crows_v = jnp.asarray(unwrap(crows))
        cols_v = jnp.asarray(unwrap(cols))
        shape = tuple(int(s) for s in shape)
        if len(shape) == 2:
            nnz = int(crows_v[-1])
            if nnz != cols_v.shape[0]:
                raise ValueError(
                    f"sparse_csr_tensor: crows[-1]={nnz} does not match "
                    f"len(cols)={cols_v.shape[0]}")
            # expand crows -> per-entry row ids ON DEVICE (total length is
            # the static nnz, so the repeat stays statically shaped)
            rows = jnp.repeat(jnp.arange(crows_v.shape[0] - 1),
                              jnp.diff(crows_v),
                              total_repeat_length=cols_v.shape[0])
            indices = jnp.stack([rows, cols_v])
        elif len(shape) == 3:
            # batched CSR (phi convention, e.g. the attention sparse_mask):
            # crows is [batch*(rows+1)] of per-batch row pointers, cols is
            # the per-batch column lists concatenated
            nbatch, nrows = shape[0], shape[1]
            cr = crows_v.reshape(nbatch, nrows + 1)
            per_batch = np.asarray(cr[:, -1])
            total = int(per_batch.sum())
            if total != cols_v.shape[0]:
                raise ValueError(
                    f"sparse_csr_tensor: sum of per-batch nnz {total} does "
                    f"not match len(cols)={cols_v.shape[0]}")
            rows = jnp.concatenate([
                jnp.repeat(jnp.arange(nrows), jnp.diff(cr[i]),
                           total_repeat_length=int(per_batch[i]))
                for i in range(nbatch)])
            batch_ids = jnp.repeat(jnp.arange(nbatch),
                                   jnp.asarray(per_batch),
                                   total_repeat_length=total)
            indices = jnp.stack([batch_ids, rows, cols_v])
        else:
            raise ValueError("sparse_csr_tensor supports 2-D or batched "
                             f"3-D shapes, got {shape}")
        super().__init__(indices, values, shape, stop_gradient)
        self._crows = Tensor(crows_v)
        self._cols = Tensor(cols_v)

    def crows(self):
        return self._crows

    def cols(self):
        return self._cols

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    if shape is None:
        iv = np.asarray(unwrap(indices))
        shape = tuple(int(m) + 1 for m in iv.max(axis=1))
    return SparseCooTensor(indices, values, shape, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape, stop_gradient)


def to_sparse_coo(x, sparse_dim=None):
    """Dense Tensor -> SparseCooTensor (reference: Tensor.to_sparse_coo).

    `sparse_dim` < ndim leaves the trailing dims DENSE: for NDHWC
    activations, sparse_dim=4 yields site indices [4, nnz] + values
    [nnz, C] — the layout the reference's sparse convs consume (and the
    r5 SubmConv3D gather path requires)."""
    v = unwrap(x)
    if sparse_dim is None or sparse_dim >= v.ndim:
        idx = jnp.stack(jnp.nonzero(v))
        vals = v[tuple(idx)]
        return SparseCooTensor(idx, vals, v.shape)
    mask = (v != 0).any(axis=tuple(range(sparse_dim, v.ndim)))
    idx = jnp.stack(jnp.nonzero(mask))
    vals = v[tuple(idx)]                 # [nnz, trailing…]
    return SparseCooTensor(idx, vals, v.shape)


def is_sparse(x):
    return isinstance(x, SparseCooTensor)


# ---------------------------------------------------------------------------
# ops — BCOO path where supported, dense fallback otherwise
# ---------------------------------------------------------------------------

def matmul(x, y, name=None):
    """spmm: BCOO @ dense via bcoo_dot_general (real sparse compute — XLA
    skips stored-zero blocks), dense@dense passthrough otherwise."""
    if isinstance(x, SparseCooTensor) and not isinstance(y, SparseCooTensor):
        def fn(yv):
            return jsparse.bcoo_dot_general(
                x._bcoo, yv,
                dimension_numbers=(((x._bcoo.ndim - 1,), (0,)), ((), ())))
        return apply(fn, y)
    from paddle_tpu.tensor.math import matmul as dense_matmul
    xv = x.to_dense() if isinstance(x, SparseCooTensor) else x
    yv = y.to_dense() if isinstance(y, SparseCooTensor) else y
    return dense_matmul(xv, yv)


def masked_matmul(x, y, mask, name=None):
    """Dense@dense, sampled at mask's sparsity pattern (SDDMM)."""
    out = jnp.matmul(unwrap(x), unwrap(y))
    idx = mask._bcoo.indices
    vals = out[tuple(idx[:, i] for i in range(idx.shape[1]))]
    return SparseCooTensor(jnp.swapaxes(idx, 0, 1), vals, out.shape)


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        idx = jnp.concatenate([x._bcoo.indices, y._bcoo.indices], axis=0)
        vals = jnp.concatenate([x._bcoo.data, y._bcoo.data], axis=0)
        return SparseCooTensor(jnp.swapaxes(idx, 0, 1), vals,
                               x._bcoo.shape).coalesce()
    from paddle_tpu.tensor.math import add as dense_add
    xv = x.to_dense() if isinstance(x, SparseCooTensor) else x
    yv = y.to_dense() if isinstance(y, SparseCooTensor) else y
    return dense_add(xv, yv)


def subtract(x, y, name=None):
    return add(x, multiply(y, -1.0) if isinstance(y, SparseCooTensor)
               else Tensor(-unwrap(y)))


def multiply(x, y, name=None):
    """Elementwise; sparse * scalar keeps sparsity."""
    if isinstance(x, SparseCooTensor) and np.isscalar(y):
        out = SparseCooTensor(jnp.swapaxes(x._bcoo.indices, 0, 1),
                              x._bcoo.data * y, x._bcoo.shape)
        out._live_mask = x._live_mask
        return out
    from paddle_tpu.tensor.math import multiply as dense_mul
    xv = x.to_dense() if isinstance(x, SparseCooTensor) else x
    yv = y.to_dense() if isinstance(y, SparseCooTensor) else y
    return dense_mul(xv, yv)


def _unary_on_values(fn_vals):
    """Zero-preserving unary ops act on stored values only (padded rows
    hold 0 and zero-preserving ops keep them 0; the live mask
    propagates). Values route through the tape so a sparse layer chain
    (conv -> relu -> conv) backprops end to end."""
    def op(x, name=None):
        if isinstance(x, SparseCooTensor):
            tv = apply(fn_vals, x.values())
            out = SparseCooTensor(jnp.swapaxes(x._bcoo.indices, 0, 1),
                                  tv._value, x._bcoo.shape,
                                  x.stop_gradient)
            out._values = tv
            out._live_mask = x._live_mask
            return out
        return apply(fn_vals, x)
    return op


relu = _unary_on_values(lambda v: jnp.maximum(v, 0.0))
sin = _unary_on_values(jnp.sin)
tanh = _unary_on_values(jnp.tanh)
sqrt = _unary_on_values(jnp.sqrt)
abs = _unary_on_values(jnp.abs)
neg = _unary_on_values(jnp.negative)
# zero-preserving unaries (reference python/paddle/sparse/unary.py)
asin = _unary_on_values(jnp.arcsin)
asinh = _unary_on_values(jnp.arcsinh)
atan = _unary_on_values(jnp.arctan)
atanh = _unary_on_values(jnp.arctanh)
sinh = _unary_on_values(jnp.sinh)
tan = _unary_on_values(jnp.tan)
square = _unary_on_values(jnp.square)
expm1 = _unary_on_values(jnp.expm1)
log1p = _unary_on_values(jnp.log1p)
deg2rad = _unary_on_values(jnp.deg2rad)
rad2deg = _unary_on_values(jnp.rad2deg)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    """Cast indices and/or values (reference sparse/unary.py cast)."""
    if isinstance(x, SparseCooTensor):
        idx = jnp.swapaxes(x._bcoo.indices, 0, 1)
        if index_dtype is not None:
            from paddle_tpu.core.dtype import convert_dtype
            idx = idx.astype(convert_dtype(index_dtype))
        vals = x._bcoo.data
        if value_dtype is not None:
            from paddle_tpu.core.dtype import convert_dtype
            vals = vals.astype(convert_dtype(value_dtype))
        out = SparseCooTensor(idx, vals, x._bcoo.shape)
        out._live_mask = x._live_mask
        return out
    return x.cast(value_dtype) if value_dtype is not None else x


def coalesce(x, name=None):
    """Merge duplicate coordinates (reference sparse/unary.py coalesce)."""
    if isinstance(x, SparseCooTensor):
        summed = x._bcoo.sum_duplicates(nse=x._bcoo.nse)
        return SparseCooTensor(jnp.swapaxes(summed.indices, 0, 1),
                               summed.data, summed.shape)
    return x


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def reshape(x, shape, name=None):
    """Reshape a sparse tensor by recomputing flat coordinates — O(nnz),
    never densifies (reference sparse/unary.py reshape)."""
    if not isinstance(x, SparseCooTensor):
        from paddle_tpu.tensor.manipulation import reshape as dense_r
        return dense_r(x, shape)
    old_shape = x._bcoo.shape
    n = int(np.prod(old_shape))
    known = int(np.prod([s for s in shape if s != -1])) or 1
    shape = tuple(n // known if s == -1 else int(s) for s in shape)
    idx = x._bcoo.indices  # [nnz, ndim]
    strides = np.cumprod((old_shape[1:] + (1,))[::-1])[::-1].copy()
    flat = (idx * jnp.asarray(strides, idx.dtype)).sum(axis=1)
    new_strides = np.cumprod((shape[1:] + (1,))[::-1])[::-1].copy()
    new_idx = jnp.stack(
        [(flat // int(st)) % int(dim)
         for st, dim in zip(new_strides, shape)], axis=0)
    out = SparseCooTensor(new_idx, x._bcoo.data, shape)
    out._live_mask = x._live_mask   # rows keep their order
    return out


def divide(x, y, name=None):
    """Elementwise divide; dense result (implicit zeros divide to 0/y)."""
    xv = x.to_dense() if isinstance(x, SparseCooTensor) else x
    yv = y.to_dense() if isinstance(y, SparseCooTensor) else y
    return Tensor(unwrap(xv) / unwrap(yv))


def mv(x, vec, name=None):
    """Sparse matrix x dense vector (reference sparse/matmul.py mv):
    O(nnz) gather-multiply-segment-sum on the BCOO triplet."""
    if isinstance(x, SparseCooTensor):
        idx = x._bcoo.indices
        contrib = x._bcoo.data * unwrap(vec)[idx[:, 1]]
        out = jnp.zeros((x._bcoo.shape[0],), x._bcoo.data.dtype
                        ).at[idx[:, 0]].add(contrib)
        return Tensor(out)
    from paddle_tpu.tensor.math import matmul as dense_mm
    return dense_mm(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta * input + alpha * (x @ y) (reference sparse/matmul.py addmm)."""
    prod = matmul(x, y)
    iv = input.to_dense() if isinstance(input, SparseCooTensor) else input
    return Tensor(beta * unwrap(iv) + alpha * unwrap(prod))
def pow(x, factor, name=None):
    """Zero-preserving only for factor > 0 (0**f == 0); otherwise implicit
    zeros would become 1 (f == 0) or inf (f < 0), so fall back to dense."""
    if np.isscalar(factor) and factor > 0:
        return _unary_on_values(lambda v: jnp.power(v, factor))(x)
    xv = x.to_dense() if isinstance(x, SparseCooTensor) else x
    return Tensor(jnp.power(unwrap(xv), factor))


def transpose(x, perm, name=None):
    if isinstance(x, SparseCooTensor):
        idx = x._bcoo.indices[:, jnp.asarray(perm)]
        shape = tuple(x._bcoo.shape[p] for p in perm)
        out = SparseCooTensor(jnp.swapaxes(idx, 0, 1), x._bcoo.data, shape)
        out._live_mask = x._live_mask   # rows keep their order
        return out
    from paddle_tpu.tensor.manipulation import transpose as dense_t
    return dense_t(x, perm)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    from paddle_tpu.tensor.math import sum as dense_sum
    return dense_sum(x.to_dense() if isinstance(x, SparseCooTensor) else x,
                     axis=axis, dtype=dtype, keepdim=keepdim)


from paddle_tpu.sparse import nn  # noqa: E402,F401
