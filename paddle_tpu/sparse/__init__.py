"""Sparse tensors. Reference: python/paddle/sparse/ (COO/CSR).

TPU-native: backed by jax.experimental.sparse BCOO (XLA-lowerable); dense
fallbacks keep API parity where BCOO lacks an op.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply, unwrap
from paddle_tpu.core.tensor import Tensor

try:
    from jax.experimental import sparse as jsparse
    _HAS_BCOO = True
except Exception:  # pragma: no cover
    _HAS_BCOO = False


class SparseCooTensor(Tensor):
    def __init__(self, indices, values, shape, stop_gradient=True):
        iv = unwrap(indices)
        vv = unwrap(values)
        self._bcoo = jsparse.BCOO((vv, jnp.swapaxes(iv, 0, 1)),
                                  shape=tuple(int(s) for s in shape))
        super().__init__(self._bcoo.todense(), stop_gradient=stop_gradient)
        self._indices = Tensor(iv)
        self._values = Tensor(vv)

    def indices(self):
        return self._indices

    def values(self):
        return self._values

    def to_dense(self):
        return Tensor(self._value)

    def is_sparse_coo(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    if shape is None:
        iv = np.asarray(unwrap(indices))
        shape = tuple(int(m) + 1 for m in iv.max(axis=1))
    return SparseCooTensor(indices, values, shape, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    crows_v = np.asarray(unwrap(crows))
    cols_v = np.asarray(unwrap(cols))
    rows = np.repeat(np.arange(len(crows_v) - 1), np.diff(crows_v))
    indices = np.stack([rows, cols_v])
    return SparseCooTensor(indices, values, shape, stop_gradient)


def matmul(x, y, name=None):
    xv = x.to_dense() if isinstance(x, SparseCooTensor) else x
    from paddle_tpu.tensor.math import matmul as dense_matmul
    return dense_matmul(xv, y)


def add(x, y, name=None):
    from paddle_tpu.tensor.math import add as dense_add
    xv = x.to_dense() if isinstance(x, SparseCooTensor) else x
    yv = y.to_dense() if isinstance(y, SparseCooTensor) else y
    return dense_add(xv, yv)


def relu(x, name=None):
    from paddle_tpu.nn.functional.activation import relu as dense_relu
    return dense_relu(x.to_dense() if isinstance(x, SparseCooTensor) else x)
