"""Quantized KV-cache pages — per-page-scaled int8/fp8 paged pools.

Plane 1 of the quantization subsystem (ROADMAP item 2): the serving
ceiling for "millions of users" is KV pages per chip, and KV values are
*storage*, not accumulators — they are written once and read through an
f32-accumulated attention contraction.  Quantizing the paged pools to
int8 (or fp8 where the dtype exists) with one scale per (page, head)
halves bytes/token vs bf16 and quarters them vs f32, which is exactly
that many more concurrent sequences inside the same HBM budget.

Storage format (the per-page-scale design implied by Ragged Paged
Attention's paged pools, arXiv:2604.15464 — see PAPERS.md):

- code pools:   ``[num_pages, n_head, page_size, head_dim]`` in the
  code dtype (int8 / float8_e4m3fn / float8_e5m2);
- scale pools:  ``[num_pages, n_head]`` float32 — one scale per
  (page, head), so the overhead is 4 bytes per ``page_size*head_dim``
  codes (~3% at the default 8x16 geometry) and a hot head cannot
  coarsen a cold head's grid;
- value ≈ code * scale, with ``scale = absmax / qmax`` over the page's
  real tokens.

Write paths:

- **prefill** quantizes each (row-page, head) block against the absmax
  of the real tokens landing in it (padding tokens are masked out of
  the scale), then scatters codes token-wise and scales page-wise —
  the same garbage-page-0 routing as the f32 pools.
- **decode** appends one token per row with *rescale-on-append*: the
  target page's scale grows monotonically (``new = max(old,
  tok_absmax/qmax)``), and only when it actually grows are the page's
  existing codes re-gridded (``round(code * old/new)``).  The common
  no-growth step multiplies by exactly 1.0 — bit-identical codes — so
  the quantization error per value stays bounded by a few grid steps
  instead of accumulating per append.  A page at offset 0 is FRESH for
  its row: its stale scale (from a previous owner) is ignored.

Read path: :func:`quantized_attend` dequantizes in-trace — gather int8
codes + per-page scales, one ``convert`` + one adjacent scale multiply
(the numlint NL301-clean shape), then f32 score/value contractions and
one rounding back to the query dtype (the NL101-clean pattern PR 12
established for narrow pools).  XLA fuses the dequant into the
contraction, so HBM sees code-width reads while the MXU sees floats.

Determinism contract (docs/quantization.md "Tolerance contracts"):
every function here is a pure per-row computation — row ``b``'s codes
depend only on row ``b``'s tokens — so continuous batching stays
token-identical to sequential serving under quantized pools.  An
EVICTION replay re-quantizes prompt+generated wholesale through
prefill (batch scales) where the original run quantized incrementally
(grown scales), so post-replay logits differ at quantization-error
order; the serving tolerance contract bounds that divergence.

Module-level imports are jax/numpy only so the analysis CLIs can
import the package light.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

__all__ = [
    "KVQuantSpec",
    "KV_CACHE_DTYPES",
    "dequantize_codes",
    "encode_int_codes",
    "kv_bytes_per_token",
    "quantize_block",
    "quantized_attend",
    "quantized_decode_step",
    "quantized_prefill_append",
    "resolve_kv_cache_dtype",
]


@dataclass(frozen=True)
class KVQuantSpec:
    """One supported code dtype for the quantized KV pools."""

    name: str           # canonical config string ("int8", "fp8_e4m3", ...)
    dtype_name: str     # jnp dtype attribute name
    qmax: float         # largest representable magnitude on the code grid
    is_int: bool        # int codes round+clip; fp8 codes cast

    @property
    def code_dtype(self):
        return getattr(jnp, self.dtype_name)

    @property
    def code_bytes(self):
        return jnp.dtype(self.code_dtype).itemsize


def _fp8_qmax(dtype_name):
    try:
        return float(jnp.finfo(getattr(jnp, dtype_name)).max)
    except (AttributeError, TypeError):  # dtype absent on this jax
        return 0.0


# int8 is always available; the fp8 entries exist only where this jax
# exposes the dtype (resolve_kv_cache_dtype gives the actionable error)
KV_CACHE_DTYPES = {
    "int8": KVQuantSpec("int8", "int8", 127.0, True),
}
for _name, _attr in (("fp8_e4m3", "float8_e4m3fn"),
                     ("fp8_e5m2", "float8_e5m2")):
    if hasattr(jnp, _attr):
        KV_CACHE_DTYPES[_name] = KVQuantSpec(
            _name, _attr, _fp8_qmax(_attr), False)


def resolve_kv_cache_dtype(name):
    """Config string -> :class:`KVQuantSpec` (None passes through).

    Accepts ``None`` (un-quantized pools at ``EngineConfig.dtype``) or
    one of :data:`KV_CACHE_DTYPES`.  Unknown names — including fp8 on a
    jax without the dtype — raise with the supported set spelled out.
    """
    if name is None or isinstance(name, KVQuantSpec):
        return name
    spec = KV_CACHE_DTYPES.get(str(name))
    if spec is None:
        raise ValueError(
            f"kv_cache_dtype {name!r} is not supported here; choose "
            f"None or one of {sorted(KV_CACHE_DTYPES)} (fp8 entries "
            f"exist only when this jax exposes the dtype)")
    return spec


def kv_bytes_per_token(num_heads, head_dim, page_size, spec=None,
                       dtype=jnp.float32):
    """Pool storage bytes per token of KV capacity for ONE layer
    (K + V): the honest per-token cost the perfgate/bench budgets
    gate — quantized pools pay ``code_bytes`` per element plus the
    per-(page, head) f32 scale amortized over the page's tokens."""
    if spec is None:
        return 2 * num_heads * head_dim * jnp.dtype(dtype).itemsize
    per_head = head_dim * spec.code_bytes + 4.0 / page_size
    return 2 * num_heads * per_head


# ----------------------------------------------------------- primitives
def encode_int_codes(scaled, qmax, key=None, dtype=jnp.int8):
    """THE int-code rounding core — round (deterministic, or stochastic
    floor+Bernoulli when a `key` rides along), clip to ±qmax, cast.
    Shared by the KV-page codec below, the EQuARX collective
    (quantization/collectives.py), and the legacy int32-wire collective
    (distributed/quantized_collective.py), so the rounding/clip
    contract has exactly one definition."""
    if key is not None:
        lo = jnp.floor(scaled)
        frac = scaled - lo
        scaled = lo + jax.random.bernoulli(key, frac).astype(jnp.float32)
    else:
        scaled = jnp.round(scaled)
    return jnp.clip(scaled, -qmax, qmax).astype(dtype)


def _encode(scaled, spec):
    """Scaled values (value/scale) -> codes on the spec's grid."""
    if spec.is_int:
        return encode_int_codes(scaled, spec.qmax,
                                dtype=spec.code_dtype)
    # fp8: the cast IS the rounding; clip keeps outliers finite
    return jnp.clip(scaled, -spec.qmax, spec.qmax).astype(spec.code_dtype)


def quantize_block(values, spec, axes):
    """Quantize `values` with one scale per block.

    `axes`: the axes REDUCED into each scale (the block extent).
    Returns ``(codes, scales)`` with ``scales = absmax/qmax`` keeping
    the reduced axes as size-1 (broadcast-ready).  All-zero blocks get
    scale 0 and all-zero codes (0 * 0 == 0 round-trips exactly).
    """
    v = values.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(v), axis=axes, keepdims=True)
    scales = absmax / spec.qmax
    safe = jnp.where(scales > 0, scales, 1.0)
    return _encode(v / safe, spec), scales


def dequantize_codes(codes, scales, spec=None):
    """codes * scales in f32 — `scales` must already be shaped to
    broadcast (size-1 reduced axes).  The scale multiply sits adjacent
    to the convert: the NL301-clean consumption shape."""
    del spec
    return codes.astype(jnp.float32) * scales


# ------------------------------------------------------------- prefill
def quantized_prefill_append(k_new, v_new, kq, vq, tables, lens,
                             page_size, spec):
    """Batched prompt write into quantized pools.

    k_new/v_new: ``[b, h, S, d]`` float; kq/vq: ``(codes, scales)``
    pool pairs; tables ``[b, P]``; lens ``[b]`` (0 = row not being
    prefilled — nothing scatters, the f32 contract).  Returns updated
    ``(kq, vq)``.

    Each (row-page, head) block's scale comes from the absmax of the
    REAL tokens landing in that page (positions >= lens[b] are masked
    to zero first); codes scatter token-wise exactly like the f32
    :func:`paged_prefill_append`, scales scatter page-wise.  Page ids
    for masked positions route to the garbage page 0.
    """
    b, h, S, d = k_new.shape
    lens = lens.astype(jnp.int32)
    t = jnp.arange(S, dtype=jnp.int32)
    page_idx = jnp.minimum(t // page_size, tables.shape[1] - 1)   # [S]
    offs = t % page_size
    page_ids = tables[:, page_idx]                                # [b, S]
    valid = t[None, :] < lens[:, None]
    page_ids = jnp.where(valid, page_ids, 0)
    flat_pages = page_ids.reshape(-1)
    flat_offs = jnp.tile(offs, b)

    n_slots = -(-S // page_size)          # row-page slots covering S
    pad = n_slots * page_size - S
    slot_ids = jnp.where(
        (jnp.arange(n_slots, dtype=jnp.int32) * page_size)[None, :]
        < lens[:, None],
        tables[:, :n_slots], 0)                                   # [b, n]

    def write(pool, vals):
        codes_pool, scales_pool = pool
        vv = jnp.where(valid[:, None, :, None], vals.astype(jnp.float32),
                       0.0)                                       # [b,h,S,d]
        blocks = jnp.pad(vv, ((0, 0), (0, 0), (0, pad), (0, 0)))
        blocks = blocks.reshape(b, h, n_slots, page_size, d)
        # one scale per (row-page slot, head) over its real tokens
        scales = jnp.max(jnp.abs(blocks), axis=(3, 4)) / spec.qmax
        safe = jnp.where(scales > 0, scales, 1.0)                 # [b,h,n]
        per_tok = jnp.repeat(safe, page_size, axis=2)[:, :, :S]   # [b,h,S]
        codes = _encode(vv / per_tok[..., None], spec)
        ct = jnp.swapaxes(codes, 1, 2).reshape(b * S, h, d)
        codes_pool = codes_pool.at[flat_pages, :, flat_offs].set(ct)
        page_scales = jnp.moveaxis(scales, 1, 2).reshape(b * n_slots, h)
        scales_pool = scales_pool.at[slot_ids.reshape(-1)].set(page_scales)
        return codes_pool, scales_pool

    return write(kq, k_new), write(vq, v_new)


# -------------------------------------------------------------- decode
def _append_token(pool, tok, page_ids, offs, spec):
    """Rescale-on-append of one token per row into its target page.

    pool: ``(codes [N,h,p,d], scales [N,h])``; tok ``[b, h, d]`` float;
    page_ids/offs ``[b]``.  The page scale grows monotonically; a
    no-growth append multiplies existing codes by exactly 1.0 (bit-
    identical), and an offset-0 append treats the page as fresh (the
    previous owner's scale is dead state, not a floor).
    """
    codes_pool, scales_pool = pool
    p = codes_pool.shape[2]
    page = codes_pool[page_ids]                            # [b, h, p, d]
    old_scale = jnp.where(offs[:, None] == 0, 0.0,
                          scales_pool[page_ids])           # [b, h]
    tok32 = tok.astype(jnp.float32)
    tok_scale = jnp.max(jnp.abs(tok32), axis=-1) / spec.qmax
    new_scale = jnp.maximum(old_scale, tok_scale)
    safe = jnp.where(new_scale > 0, new_scale, 1.0)
    ratio = old_scale / safe                               # [b, h]
    regrid = dequantize_codes(page, ratio[..., None, None])
    tok_codes = _encode(tok32 / safe[..., None], spec)     # [b, h, d]
    at = jnp.arange(p, dtype=jnp.int32)
    here = at[None, None, :, None] == offs[:, None, None, None]
    page = jnp.where(here, tok_codes[:, :, None, :].astype(page.dtype),
                     _encode(regrid, spec))
    return (codes_pool.at[page_ids].set(page),
            scales_pool.at[page_ids].set(new_scale))


def quantized_decode_step(q, k_new, v_new, kq, vq, tables, lens,
                          page_size, spec, scale=None):
    """Quantized analogue of :func:`paged_decode_step`: write each
    row's new token at position ``lens[b]`` (rescale-on-append), attend
    over ``lens[b]+1`` tokens with f32 accumulation.  Returns
    ``(out, kq, vq)``; the caller owns the lens update (the multi-layer
    engine contract)."""
    lens = lens.astype(jnp.int32)
    page_idx = lens // page_size
    offs = lens % page_size
    page_ids = jnp.take_along_axis(tables, page_idx[:, None],
                                   axis=1)[:, 0]           # [b]
    kt = jnp.swapaxes(k_new, 1, 2)[:, 0]                   # [b, h, d]
    vt = jnp.swapaxes(v_new, 1, 2)[:, 0]
    kq = _append_token(kq, kt, page_ids, offs, spec)
    vq = _append_token(vq, vt, page_ids, offs, spec)
    out = quantized_attend(q, kq, vq, tables, lens + 1, page_size, spec,
                           scale)
    return out, kq, vq


# -------------------------------------------------------------- attend
def quantized_attend(q, kq, vq, tables, lens, page_size, spec,
                     scale=None):
    """Attention of ``[b, h, 1, d]`` queries over quantized pages.

    Dequantization is in-trace and adjacent to its scale (NL301-clean),
    and BOTH contractions accumulate in f32 with one rounding back to
    the query dtype at the output (NL101-clean) — the score matmul and
    the value matmul reduce over the entire cached history, the deepest
    sums in the serving path.
    """
    del spec
    b, h, one, d = q.shape
    sc = scale if scale is not None else 1.0 / float(d) ** 0.5
    k_codes, k_scales = kq
    v_codes, v_scales = vq
    P = tables.shape[1]

    def seq(codes, scales):
        pages = codes[tables]                         # [b, P, h, p, d]
        psc = scales[tables]                          # [b, P, h]
        x = dequantize_codes(pages, psc[..., None, None])
        return jnp.moveaxis(x, 2, 1).reshape(b, h, P * page_size, d)

    k_seq = seq(k_codes, k_scales)
    v_seq = seq(v_codes, v_scales)
    pos = jnp.arange(P * page_size)
    mask = pos[None, None, None, :] < lens[:, None, None, None]
    s = jnp.matmul(q.astype(jnp.float32) * sc,
                   jnp.swapaxes(k_seq, -1, -2))       # [b, h, 1, Pp] f32
    s = jnp.where(mask, s, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.matmul(p, v_seq).astype(q.dtype)       # [b, h, 1, d]
