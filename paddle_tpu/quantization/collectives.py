"""EQuARX-style quantized AllReduce — Plane 2 of the quantization
subsystem.

Motivated by EQuARX (Efficient Quantized AllReduce in XLA,
arXiv:2506.17615, PAPERS.md): multichip training is gradient-sync-bound
over ICI, and the predecessor here
(`distributed/quantized_collective.py`) still put an int32 tensor on
the wire and leaned on the compiler to pack it.  This module moves the
ACTUAL payload to int8, in the EQuARX shape:

1. **block-scale + quantize** — the local f32 tensor is padded to an
   ``[axis_size, blocks, block]`` grid and every ``block``-element
   chunk gets its own scale (``absmax/qmax``, 4 bytes per block); codes
   are int8.  Per-block scales bound the error locally — one outlier
   coarsens 256 neighbours, not the whole gradient.
2. **all_to_all in narrow dtype** — shard ``r`` of every rank's codes
   (and scales) lands on rank ``r``: the reduce-scatter phase at int8
   wire width.
3. **dequant + local reduce** — each rank dequantizes its n shard
   copies adjacent to their scales and sums them in f32 (exact given
   the codes; numlint NL101/NL301-clean by construction).
4. **requantize + all_gather in narrow dtype + final dequant** — the
   reduced shard goes back on the wire as fresh int8 codes + scales;
   every rank reassembles and dequantizes the full tensor.

Two rounding stages, each bounded by half a grid step per value, so
``|err| <= (n_ranks + 1) * scale / (2 * qmax)``-ish per block — the
loss-trajectory contract in tests/test_quantized_kv.py pins what that
means for training.  Optional stochastic rounding (a step-varying
``key``) keeps the stage-1 error unbiased over a trajectory.

Selection is the policy's job (:mod:`quantization.policy`):
``distributed.collective.all_reduce`` routes mesh-axis float SUM/AVG
here when a :class:`~paddle_tpu.quantization.policy.CollectivePolicy`
is active, and keeps the plain psum otherwise or off-mesh.

The wire accounting (:func:`quantized_all_reduce_wire_bytes`,
:func:`collective_wire_bytes`) is what perfgate's ``allreduce_bytes``
budget and the bench ``--worker-quant`` lane gate: the analytic model
is device-count-independent (deterministic in CI), and the traced
walker proves the lowered program's collectives carry the bytes the
model claims.

Module-level imports are jax-only so the analysis CLIs stay light.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.quantization.kv_cache import encode_int_codes as _encode

__all__ = ["collective_wire_bytes", "quantized_all_reduce",
           "quantized_all_reduce_wire_bytes"]


def _axis_size(axis_name):
    """Static extent of a named mesh axis (jax_compat shims older jax)."""
    return int(lax.axis_size(axis_name))


def quantized_all_reduce(x, axis_name, bits=8, block=256, key=None,
                         mean=False):
    """All-reduce `x` over `axis_name` with int8 wire payloads.

    Call INSIDE shard_map over the reduce axis.  `x`: local float array
    (any shape); returns f32 (cast back to ``x.dtype`` by the policy
    hook).  ``key``: optional PRNG key enabling stochastic rounding of
    the stage-1 payload — pass a STEP-VARYING key; it is folded with
    the rank index here so ranks round independently.  ``mean=True``
    divides by the axis size (the dp gradient-sync op).
    """
    qmax = float(2 ** (int(bits) - 1) - 1)
    n = _axis_size(axis_name)
    orig_shape, size = x.shape, x.size
    flat = x.astype(jnp.float32).reshape(-1)
    grid = n * int(block)
    padded = -(-max(size, 1) // grid) * grid
    if padded != size:
        flat = jnp.pad(flat, (0, padded - size))
    g = flat.reshape(n, padded // (n * block), block)

    # stage 1: per-block scale, int8 codes
    s1 = jnp.max(jnp.abs(g), axis=-1) / qmax            # [n, nb]
    safe1 = jnp.where(s1 > 0, s1, 1.0)
    if key is not None:
        key = jax.random.fold_in(key, lax.axis_index(axis_name))
    q1 = _encode(g / safe1[..., None], qmax, key)       # [n, nb, block]

    # reduce-scatter phase at int8 width: shard r of every rank -> rank r
    qt = lax.all_to_all(q1, axis_name, split_axis=0, concat_axis=0,
                        tiled=True)                     # [n, nb, block]
    st = lax.all_to_all(s1, axis_name, split_axis=0, concat_axis=0,
                        tiled=True)                     # [n, nb]
    partial = jnp.sum(qt.astype(jnp.float32) * st[..., None],
                      axis=0)                           # [nb, block] f32

    # stage 2: requantize the reduced shard, gather at int8 width
    s2 = jnp.max(jnp.abs(partial), axis=-1) / qmax      # [nb]
    safe2 = jnp.where(s2 > 0, s2, 1.0)
    q2 = _encode(partial / safe2[..., None], qmax, None)
    allq = lax.all_gather(q2, axis_name)                # [n, nb, block]
    alls = lax.all_gather(s2, axis_name)                # [n, nb]
    out = (allq.astype(jnp.float32) * alls[..., None]).reshape(padded)
    out = out[:size].reshape(orig_shape)
    if mean:
        out = out / n
    return out


def quantized_all_reduce_wire_bytes(n_elems, axis_size, bits=8,
                                    block=256, wide_bytes=4):
    """Deterministic wire-byte model for one all-reduce of `n_elems`.

    Counts the payload bytes each rank PUTS ON THE WIRE, with the
    ``(n-1)/n`` locality factor applied to both sides so the ratio is
    fair: the plain path is the textbook ring all-reduce
    (reduce-scatter + all-gather = ``2 * (n-1)/n`` x payload at
    `wide_bytes`); the quantized path moves int8 codes + f32 per-block
    scales through the same two phases.  Returns the dict the perfgate
    ``quantization`` target and the bench lane report.
    """
    del bits                        # codes travel as int8 at any bits<=8
    n = int(axis_size)
    grid = n * int(block)
    padded = -(-max(int(n_elems), 1) // grid) * grid
    scale_bytes = (padded // int(block)) * 4
    locality = (n - 1) / n if n > 1 else 1.0
    quant = 2 * locality * (padded + scale_bytes)
    wide = 2 * locality * int(wide_bytes) * int(n_elems)
    return {
        "allreduce_bytes": int(round(quant)),
        "allreduce_bytes_wide": int(round(wide)),
        "allreduce_quant_vs_wide_ratio": round(quant / max(1.0, wide), 4),
    }


_COLLECTIVE_PRIMS = ("psum", "all_to_all", "all_gather", "ppermute",
                     "reduce_scatter", "all_reduce", "psum_scatter",
                     "collective_permute")


def _iter_jaxprs(v):
    if hasattr(v, "eqns"):
        yield v
    elif hasattr(v, "jaxpr"):
        yield v.jaxpr
    elif isinstance(v, (list, tuple)):
        for item in v:
            yield from _iter_jaxprs(item)


def collective_wire_bytes(jaxpr):
    """Sum the operand bytes entering collective eqns of a traced
    program (sub-jaxprs included — shard_map/pjit bodies are where the
    collectives live).  The honest cross-check for the analytic model:
    the lowered quantized program must put int8, not f32, on the wire.
    Returns ``{"total": bytes, "by_prim": {prim: bytes}}``."""
    by_prim = {}

    def walk(jx):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if any(name == p or name.startswith(p + "_")
                   for p in _COLLECTIVE_PRIMS):
                b = 0
                for v in eqn.invars:
                    aval = getattr(v, "aval", None)
                    if aval is None or not hasattr(aval, "dtype"):
                        continue
                    nelem = 1
                    for d in getattr(aval, "shape", ()) or ():
                        nelem *= int(d)
                    b += nelem * jnp.dtype(aval.dtype).itemsize
                by_prim[name] = by_prim.get(name, 0) + b
            for v in eqn.params.values():
                for sub in _iter_jaxprs(v):
                    walk(sub)

    walk(getattr(jaxpr, "jaxpr", jaxpr))
    return {"total": sum(by_prim.values()), "by_prim": by_prim}
