"""paddle_tpu.quantization — the two quantized memory planes + PTQ/QAT.

Three sub-surfaces (docs/quantization.md):

- :mod:`~paddle_tpu.quantization.kv_cache` — **Plane 1**: per-page-
  scaled int8/fp8 paged KV pools behind
  ``serving.EngineConfig(kv_cache_dtype=)`` — 2-4x concurrent
  sequences per chip at a documented decode-divergence tolerance.
- :mod:`~paddle_tpu.quantization.collectives` — **Plane 2**: the
  EQuARX-style quantized AllReduce (arXiv:2506.17615, PAPERS.md) —
  block-scaled int8 payloads through all_to_all/all_gather for dp
  gradient sync and tp decode all-reduce, selectable per trace via the
  :mod:`~paddle_tpu.quantization.policy` context (the ``amp/policy.py``
  trace-scoped shape), with a plain-XLA fallback off-mesh.
- the original PTQ observers + imperative PTQ/QAT below (reference
  parity: python/paddle/quantization — observers are tiny jnp
  reductions; converted linears run REAL int8 x int8 -> int32 on the
  MXU at double bf16 throughput).

Both planes are accounted and gated: cost_audit/SL301 and perfgate's
``kv_bytes_per_token`` / ``allreduce_bytes`` budgets see the narrow
storage, and numlint's NL301/NL302 run over every quantized serving
program (tools/numlint.py `serving_quant` target).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.quantization.collectives import (  # noqa: F401
    collective_wire_bytes, quantized_all_reduce,
    quantized_all_reduce_wire_bytes)
from paddle_tpu.quantization.kv_cache import (  # noqa: F401
    KV_CACHE_DTYPES, KVQuantSpec, dequantize_codes, kv_bytes_per_token,
    quantize_block, quantized_attend, quantized_decode_step,
    quantized_prefill_append, resolve_kv_cache_dtype)
from paddle_tpu.quantization.policy import (  # noqa: F401
    CollectivePolicy, current_collective_policy, quantized_collectives)

__all__ = ["PTQConfig", "default_ptq_config", "BaseQuantizer",
           "AbsmaxQuantizer", "PerChannelAbsmaxQuantizer", "HistQuantizer",
           "KLQuantizer", "ImperativePTQ", "ImperativeQuantAware",
           "fake_quant", "QuantizedLinear",
           # plane 1: quantized KV pages
           "KVQuantSpec", "KV_CACHE_DTYPES", "resolve_kv_cache_dtype",
           "quantize_block", "dequantize_codes", "kv_bytes_per_token",
           "quantized_attend", "quantized_decode_step",
           "quantized_prefill_append",
           # plane 2: quantized collectives + policy
           "quantized_all_reduce", "quantized_all_reduce_wire_bytes",
           "collective_wire_bytes", "CollectivePolicy",
           "quantized_collectives", "current_collective_policy"]


# ------------------------------------------------------------- quantizers

class BaseQuantizer:
    """Observer: watch tensors during calibration, then yield scales."""

    bits = 8

    def __init__(self, quant_bits=8):
        self.bits = quant_bits
        self._qmax = float(2 ** (quant_bits - 1) - 1)

    def sample(self, value):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError


class AbsmaxQuantizer(BaseQuantizer):
    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._absmax = jnp.zeros(())   # device-side: sampling never syncs

    def sample(self, value):
        self._absmax = jnp.maximum(self._absmax,
                                   jnp.max(jnp.abs(value)))

    def scales(self):
        return max(float(self._absmax), 1e-8) / self._qmax


class PerChannelAbsmaxQuantizer(BaseQuantizer):
    """Per-output-channel absmax (weights; channel = LAST dim of the
    paddle [in, out] linear weight / dim 0 of conv [O,I,H,W])."""

    def __init__(self, quant_bits=8, channel_axis=-1):
        super().__init__(quant_bits)
        self.channel_axis = channel_axis
        self._absmax = None

    def sample(self, value):
        ax = tuple(i for i in range(value.ndim)
                   if i != self.channel_axis % value.ndim)
        m = jnp.max(jnp.abs(value), axis=ax)
        self._absmax = m if self._absmax is None else \
            jnp.maximum(self._absmax, m)

    def scales(self):
        return np.asarray(jnp.maximum(self._absmax, 1e-8)) / self._qmax


class HistQuantizer(BaseQuantizer):
    """Histogram observer: scale from the `hist_percent` quantile of
    |x| (clips outliers, the reference's default 0.99999)."""

    def __init__(self, quant_bits=8, bins=2048, hist_percent=0.99999):
        super().__init__(quant_bits)
        self.bins = bins
        self.percent = hist_percent
        self._hist = np.zeros(bins)
        self._absmax = 1e-8

    def sample(self, value):
        v = np.abs(np.asarray(jax.device_get(value))).reshape(-1)
        new_max = max(self._absmax, float(v.max() if v.size else 0.0))
        if new_max > self._absmax and self._hist.any():
            # O(bins) proportional re-bin: spread each old bin's mass over
            # the new bins its interval overlaps (no per-element replay)
            old_edges = np.linspace(0, self._absmax, self.bins + 1)
            new_hist = np.zeros(self.bins)
            scale = self.bins / new_max
            lo = old_edges[:-1] * scale
            hi = old_edges[1:] * scale
            for b in range(self.bins):
                if self._hist[b] == 0:
                    continue
                i0, i1 = int(lo[b]), min(int(np.ceil(hi[b])), self.bins)
                width = hi[b] - lo[b]
                for j in range(i0, i1):
                    ov = min(hi[b], j + 1) - max(lo[b], j)
                    if ov > 0:
                        new_hist[j] += self._hist[b] * ov / width
            self._hist = new_hist
        self._absmax = max(new_max, 1e-8)
        h, _ = np.histogram(v, bins=self.bins, range=(0, self._absmax))
        self._hist = self._hist + h

    def scales(self):
        total = self._hist.sum()
        if total == 0:
            return 1e-8 / self._qmax
        cdf = np.cumsum(self._hist) / total
        idx = int(np.searchsorted(cdf, self.percent))
        edge = (idx + 1) / self.bins * self._absmax
        return max(edge, 1e-8) / self._qmax


class KLQuantizer(BaseQuantizer):
    """KL-divergence calibration (TensorRT-style): pick the clip
    threshold whose quantized distribution is closest in KL to the
    observed one."""

    def __init__(self, quant_bits=8, bins=2048):
        super().__init__(quant_bits)
        self.bins = bins
        self._hist = HistQuantizer(quant_bits, bins, 1.0)

    def sample(self, value):
        self._hist.sample(value)

    def scales(self):
        hist = self._hist._hist
        absmax = self._hist._absmax
        total = hist.sum()
        if total == 0:
            return 1e-8 / self._qmax
        levels = int(2 ** (self.bits - 1))
        best, best_kl = self.bins, np.inf
        p_full = hist / total
        # start at 2*levels: at t == levels every chunk is one bin, q == p
        # and KL degenerates to 0 — the quantization must actually coarsen
        for t in range(2 * levels, self.bins + 1,
                       max(1, self.bins // 128)):
            p = p_full[:t].copy()
            p[-1] += p_full[t:].sum()          # clip mass into last bin
            # quantize the first t bins down to `levels` buckets,
            # spreading each chunk's mass over its NONZERO support
            chunks = np.array_split(p, levels)
            q_parts = []
            for c in chunks:
                nz = c > 0
                qc = np.zeros_like(c)
                if nz.any():
                    qc[nz] = c.sum() / nz.sum()
                q_parts.append(qc)
            q = np.concatenate(q_parts)
            mask = p > 0
            if not mask.any():
                continue
            q = np.where(q > 0, q, 1e-12)
            kl = float(np.sum(p[mask] * np.log(p[mask] / q[mask])))
            if kl < best_kl:
                best_kl, best = kl, t
        edge = best / self.bins * absmax
        return max(edge, 1e-8) / self._qmax


SUPPORT_ACT_QUANTIZERS = [AbsmaxQuantizer, HistQuantizer, KLQuantizer]
SUPPORT_WT_QUANTIZERS = [AbsmaxQuantizer, PerChannelAbsmaxQuantizer]


class PTQConfig:
    def __init__(self, activation_quantizer=None, weight_quantizer=None):
        self.activation_quantizer = activation_quantizer or \
            AbsmaxQuantizer()
        self.weight_quantizer = weight_quantizer or \
            PerChannelAbsmaxQuantizer()
        if not any(isinstance(self.activation_quantizer, t)
                   for t in SUPPORT_ACT_QUANTIZERS):
            name = type(self.activation_quantizer).__name__
            raise ValueError(
                f"activation quantizer {name} not in "
                "SUPPORT_ACT_QUANTIZERS (per-tensor scales are required "
                "for the activation path)")
        if not any(isinstance(self.weight_quantizer, t)
                   for t in SUPPORT_WT_QUANTIZERS):
            name = type(self.weight_quantizer).__name__
            raise ValueError(
                f"weight quantizer {name} not in SUPPORT_WT_QUANTIZERS")


def default_ptq_config():
    return PTQConfig()


# ------------------------------------------------------------- fake quant

def _fq(v, scale, qmax):
    return jnp.clip(jnp.round(v / scale), -qmax, qmax) * scale


@jax.custom_vjp
def _fake_quant(v, scale, qmax):
    return _fq(v, scale, qmax)


def _fq_fwd(v, scale, qmax):
    return _fq(v, scale, qmax), None


def _fq_bwd(_, ct):
    # straight-through estimator: round() passes the cotangent unchanged
    return ct, None, None


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


def fake_quant(x, scale, bits=8):
    """Simulated quantization with STE gradients (QAT building block)."""
    qmax = float(2 ** (bits - 1) - 1)
    sc = jnp.asarray(scale)
    return apply(lambda v: _fake_quant(v, sc, qmax),
                 x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)))


# ------------------------------------------------------------ PTQ wrapper

def _swap_layers(container, want, make):
    """One recursive layer-replacement traversal shared by PTQ and QAT."""
    for attr, child in list(getattr(container, "_sub_layers", {}).items()):
        if want(child):
            container._sub_layers[attr] = make(child)
        else:
            _swap_layers(child, want, make)


class _ObservedLayer(Layer):
    """Calibration wrapper: records activation/weight stats, then
    converts to a quantized layer. Observers are deep copies of the
    configured prototypes so user settings (bits/bins/percentile) are
    honored per layer."""

    def __init__(self, inner, cfg):
        super().__init__()
        import copy
        self.inner = inner
        self.act_obs = copy.deepcopy(cfg.activation_quantizer)
        self.wt_obs = copy.deepcopy(cfg.weight_quantizer)
        # weights are frozen during PTQ calibration: one sample suffices
        self.wt_obs.sample(inner.weight._value)

    def forward(self, x):
        self.act_obs.sample(x._value)
        return self.inner(x)


class QuantizedLinear(Layer):
    """Converted int8 linear: weights stored int8 per-channel; the
    matmul runs int8 x int8 -> int32 ON THE MXU (double bf16 rate), with
    per-tensor dynamic activation quantization."""

    def __init__(self, linear, w_scales, act_scale, bits=8):
        super().__init__()
        self._qmax = float(2 ** (bits - 1) - 1)
        w = np.asarray(jax.device_get(linear.weight._value))
        ws = np.broadcast_to(np.asarray(w_scales), (w.shape[-1],)).copy()
        self.w_int8 = Tensor(jnp.asarray(
            np.clip(np.round(w / ws), -self._qmax, self._qmax)
            .astype(np.int8)))
        self.w_scales = Tensor(jnp.asarray(ws.astype(np.float32)))
        self.act_scale = float(act_scale)
        self.bias = linear.bias

    def forward(self, x):
        act_scale, qmax = self.act_scale, self._qmax

        def fn(v, w_i8, ws, b):
            q = jnp.clip(jnp.round(v / act_scale), -qmax,
                         qmax).astype(jnp.int8)
            acc = jax.lax.dot_general(
                q, w_i8, (((v.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (act_scale * ws)
            if b is not None:
                out = out + b
            return out.astype(v.dtype)

        if self.bias is not None:
            return apply(fn, x, self.w_int8, self.w_scales, self.bias)
        return apply(lambda v, w, s: fn(v, w, s, None), x, self.w_int8,
                     self.w_scales)


class ImperativePTQ:
    """Post-training quantization driver (reference ImperativePTQ):
    quantize() wraps Linear layers with observers; run calibration
    batches; convert() swaps in int8 QuantizedLinear layers."""

    def __init__(self, ptq_config=None):
        self.cfg = ptq_config or default_ptq_config()

    def quantize(self, model, inplace=True):
        from paddle_tpu import nn
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        _swap_layers(model, lambda c: isinstance(c, nn.Linear),
                     lambda c: _ObservedLayer(c, self.cfg))
        return model

    def convert(self, model, inplace=True):
        _swap_layers(
            model, lambda c: isinstance(c, _ObservedLayer),
            lambda c: QuantizedLinear(c.inner, c.wt_obs.scales(),
                                      c.act_obs.scales()))
        return model


class ImperativeQuantAware:
    """QAT driver (reference ImperativeQuantAware): wraps Linear layers
    so training sees fake-quantized weights/activations with STE grads;
    convert() reuses the PTQ int8 conversion from the learned ranges."""

    def __init__(self, weight_bits=8, activation_bits=8, **kw):
        self.wbits = weight_bits
        self.abits = activation_bits

    def quantize(self, model):
        from paddle_tpu import nn

        outer = self

        class _QATLinear(Layer):
            def __init__(self, inner):
                super().__init__()
                self.inner = inner
                self.act_obs = AbsmaxQuantizer(outer.abits)
                self.wt_obs = PerChannelAbsmaxQuantizer(outer.wbits)

            def forward(self, x):
                self.act_obs.sample(x._value)
                self.wt_obs.sample(self.inner.weight._value)
                from paddle_tpu.nn import functional as F
                # ranges stay device-side during training — no host syncs
                a_sc = jnp.maximum(self.act_obs._absmax,
                                   1e-8) / self.act_obs._qmax
                w_sc = jnp.maximum(self.wt_obs._absmax,
                                   1e-8) / self.wt_obs._qmax
                xq = fake_quant(x, a_sc, outer.abits)
                wq = fake_quant(self.inner.weight, w_sc, outer.wbits)
                return F.linear(xq, wq, self.inner.bias)

        _swap_layers(model, lambda c: isinstance(c, nn.Linear),
                     _QATLinear)
        return model

    def convert(self, model):
        _swap_layers(
            model,
            lambda c: hasattr(c, "inner") and hasattr(c, "wt_obs"),
            lambda c: QuantizedLinear(c.inner, c.wt_obs.scales(),
                                      c.act_obs.scales()))
        return model


class PTQRegistry:
    """Kept for API parity; the TPU PTQ driver discovers layers by
    isinstance rather than a registry of op names."""
    pass
