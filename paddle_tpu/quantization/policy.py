"""Trace-scoped quantized-collective policy (the amp/policy.py shape).

The EQuARX quantized AllReduce (:mod:`quantization.collectives`) is an
accuracy/bandwidth trade, so it must be SELECTED, never ambient: a
:class:`CollectivePolicy` pushed with :func:`quantized_collectives`
covers exactly the dynamic extent it wraps — one ``to_static`` trace,
one eager gradient sync, one shard_map body — and
``distributed.collective.all_reduce`` (mesh-axis SUM/AVG on floats) and
``DataParallel.apply_collective_grads`` consult it at their choke
points.  Everything else — integer payloads, MAX/MIN/PROD reductions,
tensors below ``min_elems``, and every collective OFF a mesh axis —
keeps the plain-XLA path, so correctness never depends on the policy
being installed (the "plain-XLA fallback off-mesh" contract).

Like the amp residency policy, the TLS is thread-local and re-entrant:
traces started inside the context (including re-traces of a
StaticFunction that entered it) see the policy; other threads and
outer code never do.
"""
from __future__ import annotations

import contextlib
import threading

__all__ = ["CollectivePolicy", "current_collective_policy",
           "quantized_collectives"]

_tls = threading.local()


class CollectivePolicy:
    """One trace's quantized-collective configuration.

    - ``bits``: code width (<= 8; codes travel as int8 either way).
    - ``block``: elements per scale block — smaller blocks track local
      magnitude tighter at 4/block bytes of scale overhead per element.
    - ``key``: optional PRNG key enabling stochastic rounding of the
      stage-1 payload (pass a STEP-VARYING key; see
      collectives.quantized_all_reduce).
    - ``min_elems``: tensors smaller than this keep the plain psum —
      tiny payloads are latency-bound, not bandwidth-bound, and padding
      to a block grid would only add error.
    """

    __slots__ = ("bits", "block", "key", "min_elems")

    def __init__(self, bits=8, block=256, key=None, min_elems=1024):
        bits = int(bits)
        if not 2 <= bits <= 8:
            raise ValueError(
                f"CollectivePolicy bits must be in [2, 8] (codes travel "
                f"as int8), got {bits}")
        block = int(block)
        if block < 8:
            raise ValueError(
                f"CollectivePolicy block must be >= 8, got {block}")
        self.bits = bits
        self.block = block
        self.key = key
        self.min_elems = int(min_elems)

    def __repr__(self):
        return (f"CollectivePolicy(bits={self.bits}, block={self.block}, "
                f"min_elems={self.min_elems}, "
                f"stochastic={self.key is not None})")


def current_collective_policy():
    """The CollectivePolicy active on this thread, or None."""
    return getattr(_tls, "policy", None)


@contextlib.contextmanager
def quantized_collectives(bits=8, block=256, key=None, min_elems=1024):
    """Push a :class:`CollectivePolicy` for the dynamic extent.

    ``with quantized_collectives(): train_step(...)`` quantizes the dp
    gradient all-reduce (and any tp decode all-reduce routed through
    ``distributed.collective.all_reduce``) inside the wrapped trace;
    an existing :class:`CollectivePolicy` instance may be passed as
    ``bits``.
    """
    pol = bits if isinstance(bits, CollectivePolicy) else \
        CollectivePolicy(bits, block=block, key=key, min_elems=min_elems)
    prev = getattr(_tls, "policy", None)
    _tls.policy = pol
    try:
        yield pol
    finally:
        _tls.policy = prev
