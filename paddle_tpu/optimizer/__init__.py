"""Optimizers. Reference: python/paddle/optimizer/__init__.py."""
from paddle_tpu.optimizer import lr  # noqa: F401
# the reference also surfaces the schedulers at paddle.optimizer level
from paddle_tpu.optimizer.lr import (  # noqa: F401
    CosineAnnealingDecay,
    CyclicLR,
    ExponentialDecay,
    InverseTimeDecay,
    LambdaDecay,
    LinearWarmup,
    LRScheduler,
    MultiplicativeDecay,
    MultiStepDecay,
    NaturalExpDecay,
    NoamDecay,
    OneCycleLR,
    PiecewiseDecay,
    PolynomialDecay,
    ReduceOnPlateau,
    StepDecay,
)
from paddle_tpu.optimizer.adam import Adam, Adamax, AdamW, Lamb  # noqa: F401
from paddle_tpu.optimizer.optimizer import Optimizer  # noqa: F401
from paddle_tpu.optimizer.rmsprop import Adadelta, Adagrad, RMSProp  # noqa: F401
from paddle_tpu.optimizer.gradient_merge import (  # noqa: F401
    GradientMergeOptimizer,
)
from paddle_tpu.optimizer.sgd import (  # noqa: F401
    SGD,
    LarsMomentum,
    LarsMomentumOptimizer,
    Momentum,
)
