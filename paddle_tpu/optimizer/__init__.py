"""Optimizers. Reference: python/paddle/optimizer/__init__.py."""
from paddle_tpu.optimizer import lr  # noqa: F401
from paddle_tpu.optimizer.adam import Adam, Adamax, AdamW, Lamb  # noqa: F401
from paddle_tpu.optimizer.optimizer import Optimizer  # noqa: F401
from paddle_tpu.optimizer.rmsprop import Adadelta, Adagrad, RMSProp  # noqa: F401
from paddle_tpu.optimizer.sgd import SGD, Momentum  # noqa: F401
