"""Adam / AdamW / Adamax / Lamb. Reference: python/paddle/optimizer/adam*.py, lamb.py."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.optimizer.optimizer import Optimizer


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None, moment_dtype=None, fused=False, guard=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, guard=guard)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        # moment_dtype="bfloat16" halves optimizer-state HBM (the fit
        # lever for billion-param models on one 16 GB chip — the
        # reference reaches the same end via sharding stage2/3 across
        # ranks); moment math still runs in fp32, only storage narrows
        self._moment_dtype = jnp.dtype(moment_dtype) if moment_dtype             else jnp.float32
        # fused=True: rank-2 params update through the single-pass
        # pallas kernel (ops/pallas/optim.py) — p/g/m/v read once,
        # p'/m'/v' written once, same f32 math to the last op.  Rank-1
        # params and hosts without pallas keep the loop below.
        self._fused = bool(fused)

    def _fused_decay(self, p):
        """(decoupled_coeff, gate) the fused kernel applies — plain
        Adam has none (coupled decay arrives in the gradient)."""
        return 0.0, True

    def _update_param(self, p, g, lr_mult):
        lr = self._lr_value() * lr_mult
        mdt = self._moment_dtype
        m = self._acc("moment1", p, dtype=mdt)
        v = self._acc("moment2", p, dtype=mdt)
        b1p = self._acc("beta1_pow", p, init=1.0, shape=(), dtype=jnp.float32)
        b2p = self._acc("beta2_pow", p, init=1.0, shape=(), dtype=jnp.float32)
        if self._will_fuse(p):
            from paddle_tpu.ops.pallas.optim import fused_adam_update
            coeff, decay_on = self._fused_decay(p)
            # bias-correction powers advance only on a COMMITTED step:
            # under guard their update is gated on this param's WHOLE-
            # param finite verdict, so the kernel consumes the
            # candidate corrections and the powers follow the commit.
            # Partial-commit caveat (kernel gates per row-block): when
            # only SOME blocks are bad, the good blocks commit with
            # this step's corrections while the powers hold — a
            # bounded one-decay correction offset on those blocks,
            # always finite, and erased by the policy machine's
            # rollback (the default remedy past skip_limit).  The
            # overwhelmingly common anomaly (NaN loss => every grad
            # NaN) gates every block and is an exact zero-update.
            b1_new = b1p._value * self._beta1
            b2_new = b2p._value * self._beta2
            if self._guard:
                new_p, new_m, new_v, parts = fused_adam_update(
                    p._value, g, m._value, v._value, lr,
                    1 - b1_new, 1 - b2_new,
                    beta1=self._beta1, beta2=self._beta2,
                    eps=self._epsilon, weight_decay=coeff,
                    decay_on=decay_on, guard=True)
                blocks = parts[:, 0]         # per-block grad sumsq
                psum = jnp.sum(blocks)
                good = jnp.isfinite(psum)
                self._guard_parts.append(psum)
                self._guard_bad.append(jnp.sum(
                    1.0 - jnp.isfinite(blocks).astype(jnp.float32)))
                self._guard_regions += int(blocks.shape[0])
                b1p._set_value(jnp.where(good, b1_new, b1p._value))
                b2p._set_value(jnp.where(good, b2_new, b2p._value))
            else:
                b1p._set_value(b1_new)
                b2p._set_value(b2_new)
                new_p, new_m, new_v = fused_adam_update(
                    p._value, g, m._value, v._value, lr,
                    1 - b1p._value, 1 - b2p._value,
                    beta1=self._beta1, beta2=self._beta2,
                    eps=self._epsilon, weight_decay=coeff,
                    decay_on=decay_on)
            p._set_value(new_p)
            m._set_value(new_m)
            v._set_value(new_v)
            return
        b1p._set_value(b1p._value * self._beta1)
        b2p._set_value(b2p._value * self._beta2)
        g = g.astype(jnp.float32)
        new_m = self._beta1 * m._value.astype(jnp.float32) + (1 - self._beta1) * g
        new_v = self._beta2 * v._value.astype(jnp.float32) + (1 - self._beta2) * g * g
        m._set_value(new_m.astype(mdt))
        v._set_value(new_v.astype(mdt))
        mhat = new_m / (1 - b1p._value)
        vhat = new_v / (1 - b2p._value)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        p._set_value((p._value.astype(jnp.float32) - upd).astype(p._value.dtype))


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None,
                 moment_dtype=None, fused=False, guard=False):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name,
                         moment_dtype, fused, guard)
        self._coeff = weight_decay if isinstance(weight_decay, float) else 0.01
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _fused_decay(self, p):
        on = self._apply_decay_param_fun is None or \
            self._apply_decay_param_fun(p.name)
        return self._coeff, on

    def _update_param(self, p, g, lr_mult):
        if self._lr_ratio is not None:
            lr_mult = lr_mult * self._lr_ratio(p)
        if not self._will_fuse(p):
            # fused updates fold the decoupled decay into the kernel
            # (same op order: decay BEFORE the adam update)
            lr = self._lr_value() * lr_mult
            if self._apply_decay_param_fun is None or \
                    self._apply_decay_param_fun(p.name):
                p._set_value((p._value.astype(jnp.float32) *
                              (1.0 - lr * self._coeff)).astype(p._value.dtype))
        Adam._update_param(self, p, g, lr_mult)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _update_param(self, p, g, lr_mult):
        lr = self._lr_value() * lr_mult
        g = g.astype(jnp.float32)
        m = self._acc("moment", p, dtype=jnp.float32)
        u = self._acc("inf_norm", p, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=1.0, shape=(), dtype=jnp.float32)
        b1p._set_value(b1p._value * self._beta1)
        new_m = self._beta1 * m._value + (1 - self._beta1) * g
        new_u = jnp.maximum(self._beta2 * u._value, jnp.abs(g))
        m._set_value(new_m)
        u._set_value(new_u)
        upd = lr * new_m / ((1 - b1p._value) * (new_u + self._epsilon))
        p._set_value((p._value.astype(jnp.float32) - upd).astype(p._value.dtype))


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update_param(self, p, g, lr_mult):
        lr = self._lr_value() * lr_mult
        g = g.astype(jnp.float32)
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=1.0, shape=(), dtype=jnp.float32)
        b2p = self._acc("beta2_pow", p, init=1.0, shape=(), dtype=jnp.float32)
        b1p._set_value(b1p._value * self._beta1)
        b2p._set_value(b2p._value * self._beta2)
        new_m = self._beta1 * m._value + (1 - self._beta1) * g
        new_v = self._beta2 * v._value + (1 - self._beta2) * g * g
        m._set_value(new_m)
        v._set_value(new_v)
        mhat = new_m / (1 - b1p._value)
        vhat = new_v / (1 - b2p._value)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon)
        wd = 0.0 if (self._exclude_fn is not None and self._exclude_fn(p)) \
            else self._lamb_wd
        pf = p._value.astype(jnp.float32)
        update = r + wd * pf
        w_norm = jnp.sqrt(jnp.sum(jnp.square(pf)))
        u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        p._set_value((pf - lr * trust * update).astype(p._value.dtype))
