"""k-step gradient accumulation (gradient merge).

Reference:
python/paddle/distributed/fleet/meta_optimizers/gradient_merge_optimizer.py
— the reference rewrites the program to accumulate grads into persistent
@GradientMerge vars and gates the inner optimizer's ops on `step % k == 0`.

TPU-native form: the wrapper is itself trace-free — every state update
is an unconditional jnp.where on `fire = (count % k == 0)`, so one
to_static trace covers accumulating AND applying steps (no shape or
branch divergence between them, no retrace at the firing step). The
inner optimizer's update runs every step on the would-be-merged grad;
its writes (param + its own accumulators, e.g. momentum) are then
where-committed only on firing steps.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.engine import no_grad
from paddle_tpu.optimizer.optimizer import Optimizer


class GradientMergeOptimizer:
    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        if not isinstance(inner_optimizer, Optimizer):
            raise TypeError("GradientMergeOptimizer wraps an Optimizer")
        self._inner = inner_optimizer
        self._k = int(k_steps)
        self._avg = bool(avg)

    # delegate everything the wrapper does not own (lr, state_dict, …)
    def __getattr__(self, name):
        return getattr(self.__dict__["_inner"], name)

    @no_grad()
    def step(self):
        from paddle_tpu.distributed import elastic
        # every microbatch step IS training progress: the accumulate
        # path below never reaches Optimizer.step (it calls the update
        # internals directly), so without this beat the elastic
        # watchdog sees k-1 of every k steps as a stall
        elastic.notify_progress()
        inner = self._inner
        if self._k <= 1:
            inner.step()            # delegate beats again — harmless
            return
        counter = inner._acc("gm_count", inner._lr_tensor,
                             shape=(), dtype=jnp.int32)
        new_count = counter._value + 1
        fire = (new_count % self._k) == 0

        # accumulate first; clip applies to the MERGED grad (the inner
        # optimizer would see the merged grad in the reference, so a
        # global-norm clip must measure it, not the microbatch grad)
        from paddle_tpu.core.tensor import Tensor
        accs = {}
        pg_eff = []
        for p, g in inner._params_grads():
            acc = inner._acc("gm_acc", p, dtype=jnp.float32)
            new_acc = acc._value + g._value.astype(jnp.float32)
            accs[id(p)] = (acc, new_acc)
            g_eff = new_acc / self._k if self._avg else new_acc
            pg_eff.append((p, Tensor(g_eff, stop_gradient=True)))
        if inner._grad_clip is not None:
            pg_eff = inner._grad_clip(pg_eff)
        for p, g in pg_eff:
            acc, new_acc = accs[id(p)]
            g_eff = g._value

            # snapshot, run the inner update unconditionally, then
            # where-commit — including accumulators the update CREATED
            # this step (their pre-state is their lazy init value)
            lr_mult = getattr(p, "optimize_attr", {}).get(
                "learning_rate", 1.0) if hasattr(p, "optimize_attr") else 1.0
            before = {k: t._value for k, t in inner._accumulators.items()}
            old_p = p._value
            gv = inner._apply_decay(p, g_eff)
            inner._update_param(p, gv, lr_mult)
            p._set_value(jnp.where(fire, p._value, old_p))
            for k, t in inner._accumulators.items():
                if k in before:
                    if t._value is not before[k]:
                        t._set_value(jnp.where(fire, t._value, before[k]))
                else:
                    init = t.__dict__.get("_reinit")
                    if init is not None:
                        t._set_value(jnp.where(fire, t._value, init()))
            acc._set_value(jnp.where(fire, jnp.zeros_like(new_acc), new_acc))
        counter._set_value(new_count)

    @no_grad()
    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, self._inner._params_grads()
