"""SGD / Momentum. Reference: python/paddle/optimizer/{sgd,momentum}.py."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.optimizer.optimizer import Optimizer


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False,
                 guard=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, guard=guard)

    def _update_param(self, p, g, lr_mult):
        lr = self._lr_value() * lr_mult
        p._set_value((p._value.astype(jnp.float32) -
                      lr * g.astype(jnp.float32)).astype(p._value.dtype))


class LarsMomentum(Optimizer):
    """LARS (Layer-wise Adaptive Rate Scaling) momentum.

    Reference: python/paddle/fluid/optimizer.py LarsMomentumOptimizer and
    distributed/fleet/meta_optimizers/lars_optimizer.py — per-layer
    trust ratio
        local_lr = lr * lars_coeff * ||p|| / (||g|| + wd * ||p|| + eps)
        v        = mu * v + local_lr * (g + wd * p)
        p       -= v
    All norms/updates are jnp reductions so the whole step fuses into
    the to_static XLA program (no per-layer host sync).
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0.0,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._exclude = list(exclude_from_weight_decay or [])
        self._epsilon = epsilon
        self._rescale_grad = rescale_grad

    def _update_param(self, p, g, lr_mult):
        lr = self._lr_value() * lr_mult
        pv = p._value.astype(jnp.float32)
        g = g.astype(jnp.float32) * self._rescale_grad
        wd = self._lars_weight_decay
        if any(tok in (p.name or "") for tok in self._exclude):
            wd = 0.0
        p_norm = jnp.sqrt(jnp.sum(pv * pv))
        g_norm = jnp.sqrt(jnp.sum(g * g))
        denom = g_norm + wd * p_norm + self._epsilon
        # reference kernel semantics: when ||p|| or the denominator is 0
        # (fresh bias, zero grad) the trust ratio degrades to plain lr
        trust = jnp.where((p_norm > 0.0) & (denom > 0.0),
                          self._lars_coeff * p_norm /
                          jnp.where(denom > 0.0, denom, 1.0), 1.0)
        local_lr = lr * trust
        vel = self._acc("velocity", p, dtype=jnp.float32)
        new_v = self._momentum * vel._value + local_lr * (g + wd * pv)
        vel._set_value(new_v)
        p._set_value((pv - new_v).astype(p._value.dtype))


# reference spelling
LarsMomentumOptimizer = LarsMomentum


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov
        self._rescale_grad = rescale_grad

    def _update_param(self, p, g, lr_mult):
        lr = self._lr_value() * lr_mult
        g = g.astype(jnp.float32) * self._rescale_grad
        vel = self._acc("velocity", p, dtype=jnp.float32)
        new_v = self._momentum * vel._value + g
        vel._set_value(new_v)
        if self._use_nesterov:
            update = g + self._momentum * new_v
        else:
            update = new_v
        p._set_value((p._value.astype(jnp.float32) - lr * update).astype(
            p._value.dtype))
