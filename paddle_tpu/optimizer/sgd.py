"""SGD / Momentum. Reference: python/paddle/optimizer/{sgd,momentum}.py."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.optimizer.optimizer import Optimizer


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update_param(self, p, g, lr_mult):
        lr = self._lr_value() * lr_mult
        p._set_value((p._value.astype(jnp.float32) -
                      lr * g.astype(jnp.float32)).astype(p._value.dtype))


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov
        self._rescale_grad = rescale_grad

    def _update_param(self, p, g, lr_mult):
        lr = self._lr_value() * lr_mult
        g = g.astype(jnp.float32) * self._rescale_grad
        vel = self._acc("velocity", p, dtype=jnp.float32)
        new_v = self._momentum * vel._value + g
        vel._set_value(new_v)
        if self._use_nesterov:
            update = g + self._momentum * new_v
        else:
            update = new_v
        p._set_value((p._value.astype(jnp.float32) - lr * update).astype(
            p._value.dtype))
