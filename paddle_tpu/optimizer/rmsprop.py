"""RMSProp / Adagrad / Adadelta. Reference: python/paddle/optimizer/*."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.optimizer.optimizer import Optimizer


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update_param(self, p, g, lr_mult):
        lr = self._lr_value() * lr_mult
        g = g.astype(jnp.float32)
        ms = self._acc("mean_square", p, dtype=jnp.float32)
        mom = self._acc("momentum", p, dtype=jnp.float32)
        new_ms = self._rho * ms._value + (1 - self._rho) * g * g
        ms._set_value(new_ms)
        if self._centered:
            mg = self._acc("mean_grad", p, dtype=jnp.float32)
            new_mg = self._rho * mg._value + (1 - self._rho) * g
            mg._set_value(new_mg)
            denom = jnp.sqrt(new_ms - new_mg * new_mg + self._epsilon)
        else:
            denom = jnp.sqrt(new_ms + self._epsilon)
        new_mom = self._momentum * mom._value + lr * g / denom
        mom._set_value(new_mom)
        p._set_value((p._value.astype(jnp.float32) - new_mom).astype(p._value.dtype))


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update_param(self, p, g, lr_mult):
        lr = self._lr_value() * lr_mult
        g = g.astype(jnp.float32)
        acc = self._acc("moment", p, init=self._init_acc, dtype=jnp.float32)
        new_acc = acc._value + g * g
        acc._set_value(new_acc)
        p._set_value((p._value.astype(jnp.float32) -
                      lr * g / (jnp.sqrt(new_acc) + self._epsilon)).astype(
            p._value.dtype))


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._rho = rho

    def _update_param(self, p, g, lr_mult):
        lr = self._lr_value() * lr_mult
        g = g.astype(jnp.float32)
        avg_sq_g = self._acc("avg_squared_grad", p, dtype=jnp.float32)
        avg_sq_u = self._acc("avg_squared_update", p, dtype=jnp.float32)
        new_asg = self._rho * avg_sq_g._value + (1 - self._rho) * g * g
        avg_sq_g._set_value(new_asg)
        update = -jnp.sqrt((avg_sq_u._value + self._epsilon) /
                           (new_asg + self._epsilon)) * g
        avg_sq_u._set_value(self._rho * avg_sq_u._value +
                            (1 - self._rho) * update * update)
        p._set_value((p._value.astype(jnp.float32) + lr * update).astype(
            p._value.dtype))
