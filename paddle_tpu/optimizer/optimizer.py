"""Optimizer base. Reference: python/paddle/optimizer/optimizer.py.

Design: paddle's imperative `opt.step()` API, functional underneath — every
accumulator (moments etc.) and the learning-rate live as registered state
Tensors, so a `to_static` train step traces forward+backward+update into ONE
XLA program (the lr is a lifted scalar input, not a baked constant, so LR
schedules don't retrigger compilation).
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.engine import no_grad
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework.state import register_state_tensor
from paddle_tpu.observability.profile import layer_scope


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, guard=False):
        from paddle_tpu.optimizer.lr import LRScheduler
        self._parameter_list = list(parameters) if parameters is not None else None
        self._lr_scheduler = None
        if isinstance(learning_rate, LRScheduler):
            self._lr_scheduler = learning_rate
            lr0 = learning_rate()
        else:
            lr0 = float(learning_rate)
        self._lr_tensor = Tensor(jnp.asarray(lr0, jnp.float32), name="learning_rate")
        self._lr_tensor.persistable = True
        register_state_tensor(self._lr_tensor)
        if self._lr_scheduler is not None:
            self._lr_scheduler._bind(self._lr_tensor)
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._accumulators = {}
        # subclasses with a fused single-pass update kernel set this
        # (Adam/AdamW `fused=True`); the base loop never fuses
        self._fused = False
        # guard=True arms the training-sentinel probe + skip gate
        # (resilience/sentinel.py): every step computes the global
        # gradient sum-of-squares IN-TRACE and commits a ZERO update
        # for any parameter region whose gradients are non-finite —
        # the GradScaler-shaped skip, but inside the one compiled
        # program (works under to_static, where GradScaler's host-side
        # found_inf bool cannot).  The per-step verdict lands in a
        # registered (4,) f32 state tensor read via guard_summary().
        self._guard = bool(guard)
        self._guard_summary_t = None
        self._guard_parts = []      # per-region traced sumsq scalars
        self._guard_bad = []        # per-region traced 0/1 bad flags

    def _will_fuse(self, p):
        """True when this param's update will run the fused single-pass
        kernel (ops/pallas/optim.py) instead of the per-op loop."""
        if not self._fused:
            return False
        try:
            from paddle_tpu.ops.pallas.optim import supports_fused
        except Exception:
            return False
        return supports_fused(jnp.shape(p._value))

    # ---- lr ----
    def get_lr(self):
        if self._lr_scheduler is not None:
            return self._lr_scheduler()
        return float(self._lr_tensor._value)

    def set_lr(self, value):
        if self._lr_scheduler is not None:
            raise RuntimeError("cannot set_lr when LRScheduler is used")
        self._lr_tensor._set_value(jnp.asarray(float(value), jnp.float32))

    @property
    def _learning_rate(self):
        return self._lr_scheduler if self._lr_scheduler is not None else \
            float(self._lr_tensor._value)

    def _lr_value(self):
        """Traced lr read used inside update rules."""
        return self._lr_tensor._value

    # ---- accumulators ----
    def _acc(self, name, p, init=0.0, shape=None, dtype=None):
        key = (name, id(p))
        if key not in self._accumulators:
            shp = tuple(shape) if shape is not None else tuple(
                jnp.shape(p._value))
            dt = dtype or p._value.dtype
            t = Tensor(jnp.full(shp, init, dt), name=f"{p.name}_{name}")
            t.persistable = True
            # lazy creation can happen inside a to_static trace; record how to
            # rebuild a concrete initial value (see jit.api._StateSnapshot)
            t.__dict__["_reinit"] = lambda: jnp.full(shp, init, dt)
            register_state_tensor(t)
            # a same-shaped accumulator of a sharded parameter inherits
            # the parameter's PartitionSpec: moments of a tp-sharded
            # weight living replicated on every chip is pure HBM waste
            # (shardlint SL102) — the update math is elementwise over
            # the param, so the param's layout is always legal for it
            from paddle_tpu.distributed.mesh import (get_dist_spec,
                                                     shard_tensor)
            spec = get_dist_spec(p)
            if spec is not None and shp == tuple(jnp.shape(p._value)):
                shard_tensor(t, *spec)
            self._accumulators[key] = t
        return self._accumulators[key]

    # ---- grads ----
    def _params(self):
        if self._parameter_list is None:
            raise ValueError("optimizer constructed without parameters")
        return [p for p in self._parameter_list if not p.stop_gradient]

    def _params_grads(self):
        pg = []
        for p in self._params():
            if p.grad is not None:
                pg.append((p, p.grad))
        return pg

    def _apply_decay(self, p, g):
        from paddle_tpu.regularizer import L1Decay, L2Decay
        # per-parameter regularizer (ParamAttr) takes precedence and applies
        # even when the optimizer-level weight_decay is None (paddle semantics)
        if getattr(p, "regularizer", None) is not None:
            reg = p.regularizer
            if isinstance(reg, L2Decay):
                return g + reg._coeff * p._value
            if isinstance(reg, L1Decay):
                return g + reg._coeff * jnp.sign(p._value)
            return g
        wd = self._weight_decay
        if wd is None:
            return g
        if isinstance(wd, float):
            return g + wd * p._value
        if isinstance(wd, L2Decay):
            return g + wd._coeff * p._value
        if isinstance(wd, L1Decay):
            return g + wd._coeff * jnp.sign(p._value)
        return g

    @no_grad()
    def step(self):
        from paddle_tpu.distributed import elastic
        from paddle_tpu.observability import span
        from paddle_tpu.resilience import faultinject
        elastic.notify_progress()   # launcher-installed watchdog heartbeat
        # chaos hook: `exception` faults here exercise retry/elastic
        # recovery, `preempt` faults the drain path.  Under to_static
        # this fires at TRACE time only — chaos loops run eager.
        faultinject.fire("optimizer.step")
        # chaos hook: `bitflip`/`nan_grad` faults corrupt one gradient
        # element BEFORE the update (the SDC the sentinel's finite
        # guard + digest vote must catch).  Eager-only like every
        # occurrence-counted fault.
        spec = faultinject.fire("optimizer.grads")
        if spec is not None and spec.kind in ("bitflip", "nan_grad"):
            self._inject_grad_fault(spec)
        # under to_static this span fires at TRACE time (the update math
        # is fused into the step program); in eager mode it times every
        # parameter update pass.  The named scope puts the update math's
        # eqns under "optimizer.step" in roofline attribution — without
        # it the moment/param updates (5-6x param bytes every step) land
        # in <unattributed>
        with span("optimizer.step", cls=type(self).__name__), \
                layer_scope("optimizer.step"):
            pg = self._params_grads()
            if self._grad_clip is not None:
                pg = self._grad_clip(pg)
            if self._guard:
                self._guard_parts = []
                self._guard_bad = []
                self._guard_regions = 0
            for p, g in pg:
                lr_mult = getattr(p, "optimize_attr", {}).get("learning_rate", 1.0) \
                    if hasattr(p, "optimize_attr") else 1.0
                gv = g._value
                if gv.dtype != p._value.dtype and not self._will_fuse(p):
                    # the fused kernel casts in-register; pre-casting
                    # here would pay a full extra grad read+write
                    gv = gv.astype(jnp.float32)
                gv = self._apply_decay(p, gv)
                if self._guard and not self._will_fuse(p):
                    # fused params gate inside the kernel (Adam); the
                    # generic wrapper covers every unfused update rule
                    self._guarded_update(p, gv, lr_mult)
                else:
                    self._update_param(p, gv, lr_mult)
            if self._guard:
                self._commit_guard_summary()

    # ---- sentinel guard (resilience/sentinel.py) ----
    def _inject_grad_fault(self, spec):
        """Apply a bitflip/nan_grad fault spec to the target gradient
        (payload "param" names it; default: the first param with a
        grad).  Deterministic via (plan seed, occurrence)."""
        from paddle_tpu.resilience import faultinject
        plan = faultinject.active_plan()
        seed = plan.seed if plan is not None else 0
        target = spec.payload.get("param")
        for p, g in self._params_grads():
            if target is not None and p.name != target:
                continue
            g._set_value(jnp.asarray(faultinject.corrupt_array(
                spec, g._value, seed=seed)).astype(g._value.dtype))
            return

    def _summary_tensor(self):
        if self._guard_summary_t is None:
            t = Tensor(jnp.zeros((4,), jnp.float32),
                       name="sentinel_summary")
            t.persistable = True
            t.stop_gradient = True
            # lazy creation can happen inside a to_static trace
            t.__dict__["_reinit"] = lambda: jnp.zeros((4,), jnp.float32)
            register_state_tensor(t)
            self._guard_summary_t = t
        return self._guard_summary_t

    def guard_summary(self):
        """The last guarded step's probe as a
        :class:`~paddle_tpu.resilience.sentinel.GuardSummary`
        (None before the first guarded step) — the value
        ``TrainingSentinel.observe(summary=...)`` consumes."""
        if self._guard_summary_t is None:
            return None
        from paddle_tpu.resilience.sentinel import GuardSummary
        import numpy as np
        return GuardSummary.from_array(
            np.asarray(self._guard_summary_t._value))

    def _param_state_tensors(self, p):
        """`p` plus its registered accumulators (the tensors one
        parameter's update may mutate).  The pid -> tensors index is
        cached and rebuilt only when an accumulator lands (first step,
        lazy creation) — a linear scan of `_accumulators` here would
        make every guarded eager step O(params x accumulators)."""
        cached = getattr(self, "_guard_acc_index", None)
        if cached is None or cached[0] != len(self._accumulators):
            index = {}
            for (_name, tid), t in self._accumulators.items():
                index.setdefault(tid, []).append(t)
            cached = (len(self._accumulators), index)
            self._guard_acc_index = cached
        return [p] + cached[1].get(id(p), [])

    def _guarded_update(self, p, g, lr_mult):
        """Generic zero-update gate around ANY subclass update rule:
        reduce the gradient (f32 sum-of-squares — one reduction serves
        both the finite verdict and the grad-norm probe, since any
        non-finite element makes the sum non-finite), run the update,
        then select every mutated state tensor back to its prior value
        when the verdict is bad.  ``jnp.where`` (not multiply) so NaNs
        in the discarded branch cannot leak."""
        gsq = jnp.sum(jnp.square(g.astype(jnp.float32)))
        good = jnp.isfinite(gsq)
        self._guard_parts.append(gsq)
        self._guard_bad.append(1.0 - good.astype(jnp.float32))
        self._guard_regions += 1
        before = {id(t): (t, t._value)
                  for t in self._param_state_tensors(p)}
        self._update_param(p, g, lr_mult)
        import jax
        concrete = not isinstance(good, jax.core.Tracer)
        if concrete and bool(good):
            # eager clean step (the ~100% case): the verdict is a
            # concrete scalar, so skip the select entirely — the
            # jnp.where below would materialize a full copy of every
            # mutated state tensor per param per step
            return
        for t in self._param_state_tensors(p):
            prior = before.get(id(t))
            if prior is not None:
                old = prior[1]
            else:
                # accumulator created lazily INSIDE this update: its
                # pre-step value is its recorded fresh init
                reinit = t.__dict__.get("_reinit")
                if reinit is None:
                    continue
                old = reinit().astype(t._value.dtype)
            if t._value is old:
                continue                     # untouched this step
            # traced: data-dependent select (jnp.where, not multiply,
            # so NaNs in the discarded branch cannot leak).  Eager-bad:
            # restore the priors outright.
            t._value = old if concrete else jnp.where(good, t._value,
                                                      old)

    def _commit_guard_summary(self):
        """Fold the per-region probe scalars into the (4,) summary
        state tensor: [good, grad_sumsq, bad_regions, regions].  All
        f32 scalar math — bytes-free at cost-model scale."""
        if self._guard_parts:
            total = self._guard_parts[0]
            for x in self._guard_parts[1:]:
                total = total + x
            bad = self._guard_bad[0]
            for x in self._guard_bad[1:]:
                bad = bad + x
        else:
            total = jnp.asarray(0.0, jnp.float32)
            bad = jnp.asarray(0.0, jnp.float32)
        good = jnp.isfinite(total).astype(jnp.float32)
        t = self._summary_tensor()
        t._value = jnp.stack([
            good, total.astype(jnp.float32),
            jnp.asarray(bad, jnp.float32),
            jnp.asarray(float(self._guard_regions), jnp.float32)])
        t._version += 1

    def _update_param(self, p, g, lr_mult):
        raise NotImplementedError

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, self._params_grads()

    def clear_grad(self, set_to_zero=False):
        for p in self._params():
            p.clear_grad()

    clear_gradients = clear_grad

    # ---- state ----
    def state_dict(self):
        sd = {}
        for (name, pid), t in self._accumulators.items():
            sd[f"{t.name}"] = t
        sd["LR_Scheduler"] = {"last_epoch": self._lr_scheduler.last_epoch,
                              "last_lr": self._lr_scheduler.last_lr} \
            if self._lr_scheduler is not None else {}
        return sd

    def set_state_dict(self, state_dict):
        import numpy as np
        for (name, pid), t in self._accumulators.items():
            if t.name in state_dict:
                v = state_dict[t.name]
                v = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                t._set_value(v.astype(t._value.dtype))
        sched = state_dict.get("LR_Scheduler")
        if sched and self._lr_scheduler is not None:
            self._lr_scheduler.last_epoch = sched.get("last_epoch", 0)
