"""Optimizer base. Reference: python/paddle/optimizer/optimizer.py.

Design: paddle's imperative `opt.step()` API, functional underneath — every
accumulator (moments etc.) and the learning-rate live as registered state
Tensors, so a `to_static` train step traces forward+backward+update into ONE
XLA program (the lr is a lifted scalar input, not a baked constant, so LR
schedules don't retrigger compilation).
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.engine import no_grad
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework.state import register_state_tensor
from paddle_tpu.observability.profile import layer_scope


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        from paddle_tpu.optimizer.lr import LRScheduler
        self._parameter_list = list(parameters) if parameters is not None else None
        self._lr_scheduler = None
        if isinstance(learning_rate, LRScheduler):
            self._lr_scheduler = learning_rate
            lr0 = learning_rate()
        else:
            lr0 = float(learning_rate)
        self._lr_tensor = Tensor(jnp.asarray(lr0, jnp.float32), name="learning_rate")
        self._lr_tensor.persistable = True
        register_state_tensor(self._lr_tensor)
        if self._lr_scheduler is not None:
            self._lr_scheduler._bind(self._lr_tensor)
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._accumulators = {}
        # subclasses with a fused single-pass update kernel set this
        # (Adam/AdamW `fused=True`); the base loop never fuses
        self._fused = False

    def _will_fuse(self, p):
        """True when this param's update will run the fused single-pass
        kernel (ops/pallas/optim.py) instead of the per-op loop."""
        if not self._fused:
            return False
        try:
            from paddle_tpu.ops.pallas.optim import supports_fused
        except Exception:
            return False
        return supports_fused(jnp.shape(p._value))

    # ---- lr ----
    def get_lr(self):
        if self._lr_scheduler is not None:
            return self._lr_scheduler()
        return float(self._lr_tensor._value)

    def set_lr(self, value):
        if self._lr_scheduler is not None:
            raise RuntimeError("cannot set_lr when LRScheduler is used")
        self._lr_tensor._set_value(jnp.asarray(float(value), jnp.float32))

    @property
    def _learning_rate(self):
        return self._lr_scheduler if self._lr_scheduler is not None else \
            float(self._lr_tensor._value)

    def _lr_value(self):
        """Traced lr read used inside update rules."""
        return self._lr_tensor._value

    # ---- accumulators ----
    def _acc(self, name, p, init=0.0, shape=None, dtype=None):
        key = (name, id(p))
        if key not in self._accumulators:
            shp = tuple(shape) if shape is not None else tuple(
                jnp.shape(p._value))
            dt = dtype or p._value.dtype
            t = Tensor(jnp.full(shp, init, dt), name=f"{p.name}_{name}")
            t.persistable = True
            # lazy creation can happen inside a to_static trace; record how to
            # rebuild a concrete initial value (see jit.api._StateSnapshot)
            t.__dict__["_reinit"] = lambda: jnp.full(shp, init, dt)
            register_state_tensor(t)
            # a same-shaped accumulator of a sharded parameter inherits
            # the parameter's PartitionSpec: moments of a tp-sharded
            # weight living replicated on every chip is pure HBM waste
            # (shardlint SL102) — the update math is elementwise over
            # the param, so the param's layout is always legal for it
            from paddle_tpu.distributed.mesh import (get_dist_spec,
                                                     shard_tensor)
            spec = get_dist_spec(p)
            if spec is not None and shp == tuple(jnp.shape(p._value)):
                shard_tensor(t, *spec)
            self._accumulators[key] = t
        return self._accumulators[key]

    # ---- grads ----
    def _params(self):
        if self._parameter_list is None:
            raise ValueError("optimizer constructed without parameters")
        return [p for p in self._parameter_list if not p.stop_gradient]

    def _params_grads(self):
        pg = []
        for p in self._params():
            if p.grad is not None:
                pg.append((p, p.grad))
        return pg

    def _apply_decay(self, p, g):
        from paddle_tpu.regularizer import L1Decay, L2Decay
        # per-parameter regularizer (ParamAttr) takes precedence and applies
        # even when the optimizer-level weight_decay is None (paddle semantics)
        if getattr(p, "regularizer", None) is not None:
            reg = p.regularizer
            if isinstance(reg, L2Decay):
                return g + reg._coeff * p._value
            if isinstance(reg, L1Decay):
                return g + reg._coeff * jnp.sign(p._value)
            return g
        wd = self._weight_decay
        if wd is None:
            return g
        if isinstance(wd, float):
            return g + wd * p._value
        if isinstance(wd, L2Decay):
            return g + wd._coeff * p._value
        if isinstance(wd, L1Decay):
            return g + wd._coeff * jnp.sign(p._value)
        return g

    @no_grad()
    def step(self):
        from paddle_tpu.distributed import elastic
        from paddle_tpu.observability import span
        from paddle_tpu.resilience import faultinject
        elastic.notify_progress()   # launcher-installed watchdog heartbeat
        # chaos hook: `exception` faults here exercise retry/elastic
        # recovery, `preempt` faults the drain path.  Under to_static
        # this fires at TRACE time only — chaos loops run eager.
        faultinject.fire("optimizer.step")
        # under to_static this span fires at TRACE time (the update math
        # is fused into the step program); in eager mode it times every
        # parameter update pass.  The named scope puts the update math's
        # eqns under "optimizer.step" in roofline attribution — without
        # it the moment/param updates (5-6x param bytes every step) land
        # in <unattributed>
        with span("optimizer.step", cls=type(self).__name__), \
                layer_scope("optimizer.step"):
            pg = self._params_grads()
            if self._grad_clip is not None:
                pg = self._grad_clip(pg)
            for p, g in pg:
                lr_mult = getattr(p, "optimize_attr", {}).get("learning_rate", 1.0) \
                    if hasattr(p, "optimize_attr") else 1.0
                gv = g._value
                if gv.dtype != p._value.dtype and not self._will_fuse(p):
                    # the fused kernel casts in-register; pre-casting
                    # here would pay a full extra grad read+write
                    gv = gv.astype(jnp.float32)
                gv = self._apply_decay(p, gv)
                self._update_param(p, gv, lr_mult)

    def _update_param(self, p, g, lr_mult):
        raise NotImplementedError

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, self._params_grads()

    def clear_grad(self, set_to_zero=False):
        for p in self._params():
            p.clear_grad()

    clear_gradients = clear_grad

    # ---- state ----
    def state_dict(self):
        sd = {}
        for (name, pid), t in self._accumulators.items():
            sd[f"{t.name}"] = t
        sd["LR_Scheduler"] = {"last_epoch": self._lr_scheduler.last_epoch,
                              "last_lr": self._lr_scheduler.last_lr} \
            if self._lr_scheduler is not None else {}
        return sd

    def set_state_dict(self, state_dict):
        import numpy as np
        for (name, pid), t in self._accumulators.items():
            if t.name in state_dict:
                v = state_dict[t.name]
                v = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                t._set_value(v.astype(t._value.dtype))
        sched = state_dict.get("LR_Scheduler")
        if sched and self._lr_scheduler is not None:
            self._lr_scheduler.last_epoch = sched.get("last_epoch", 0)
