"""Independent / TransformedDistribution / ExponentialFamily.

Reference parity: python/paddle/distribution/independent.py:18,
transformed_distribution.py:22, exponential_family.py:20.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["Independent", "TransformedDistribution", "ExponentialFamily",
           "register_kl"]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _sum_rightmost(value, n):
    return value.sum(axis=tuple(range(value.ndim - n, value.ndim))) \
        if n > 0 else value


def _base():
    from paddle_tpu.distribution import Distribution
    return Distribution


class Independent:
    """Reinterpret the rightmost `reinterpreted_batch_rank` batch dims of
    `base` as event dims: log_prob sums over them (reference
    independent.py:18)."""

    def __init__(self, base, reinterpreted_batch_rank):
        if reinterpreted_batch_rank <= 0:
            raise ValueError("reinterpreted_batch_rank must be positive")
        self._base = base
        self._rank = int(reinterpreted_batch_rank)

    @property
    def mean(self):
        return self._base.mean

    @property
    def variance(self):
        return self._base.variance

    def sample(self, shape=()):
        return self._base.sample(shape)

    def rsample(self, shape=()):
        return self._base.rsample(shape)

    def log_prob(self, value):
        lp = self._base.log_prob(value)
        return Tensor(_sum_rightmost(_v(lp), self._rank))

    def prob(self, value):
        return Tensor(jnp.exp(_v(self.log_prob(value))))

    def entropy(self):
        return Tensor(_sum_rightmost(_v(self._base.entropy()), self._rank))


class TransformedDistribution:
    """Distribution of T_k(...T_1(x)) for x ~ base (reference
    transformed_distribution.py:22): sample pushes forward through the
    chain; log_prob pulls back with the inverse log-det corrections."""

    def __init__(self, base, transforms):
        from paddle_tpu.distribution.transform import ChainTransform
        self._base = base
        self._transforms = list(transforms)
        self._chain = ChainTransform(self._transforms)

    def sample(self, shape=()):
        x = self._base.sample(shape)
        return Tensor(self._chain._forward(_v(x)))

    def rsample(self, shape=()):
        x = self._base.rsample(shape) if hasattr(self._base, "rsample") \
            else self._base.sample(shape)
        return Tensor(self._chain._forward(_v(x)))

    def log_prob(self, value):
        y = _v(value)
        lp = 0.0
        for t in reversed(self._transforms):
            x = t._inverse(y)
            lp = lp - t._forward_log_det_jacobian(x)
            y = x
        base_lp = _v(self._base.log_prob(Tensor(y)))
        return Tensor(base_lp + lp)

    def prob(self, value):
        return Tensor(jnp.exp(_v(self.log_prob(value))))


class ExponentialFamily:
    """Bregman-duality entropy for exponential-family members (reference
    exponential_family.py:20): H = log-normalizer at natural params minus
    <params, grad log-normalizer> minus mean carrier measure, with the
    gradient taken by jax (the reference differentiates the fluid
    graph)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        # H = A(theta) - <theta, grad A(theta)> - E[carrier measure]; the
        # grad of sum(A) gives the per-batch-element partials because A
        # is elementwise over the batch
        params = [_v(p) for p in self._natural_parameters]
        grads = jax.grad(
            lambda *ps: jnp.sum(self._log_normalizer(*ps)),
            argnums=tuple(range(len(params))))(*params)
        ent = self._log_normalizer(*params) - self._mean_carrier_measure
        for p, g in zip(params, grads):
            ent = ent - p * g
        return Tensor(ent)


_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a pairwise KL rule (reference kl.py
    register_kl); kl_divergence dispatches on the most specific
    registered pair."""

    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return deco


def dispatch_kl(p, q):
    matches = [(cp, cq) for (cp, cq) in _KL_REGISTRY
               if isinstance(p, cp) and isinstance(q, cq)]
    if not matches:
        return None
    best = min(matches, key=lambda pair: (
        len(type(p).__mro__) - len(pair[0].__mro__),
        len(type(q).__mro__) - len(pair[1].__mro__)))
    return _KL_REGISTRY[best]
