"""Probability distributions. Reference: python/paddle/distribution/*."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply, unwrap, wrap
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework.state import next_key


def _v(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from paddle_tpu.tensor.math import exp
        return exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(self._batch_shape)
        z = jax.random.normal(next_key(), shape)
        return Tensor(self.loc + self.scale * z)

    def log_prob(self, value):
        def fn(v):
            var = self.scale ** 2
            return -((v - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) \
                - 0.5 * math.log(2 * math.pi)
        return apply(fn, value)

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) +
                      jnp.log(self.scale) * jnp.ones(self._batch_shape))

    def kl_divergence(self, other):
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _v(low)
        self.high = _v(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(self._batch_shape)
        u = jax.random.uniform(next_key(), shape)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        def fn(v):
            inside = (v >= self.low) & (v < self.high)
            return jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)
        return apply(fn, value)

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _v(logits)
        super().__init__(self.logits.shape[:-1])

    def sample(self, shape=()):
        shape = tuple(shape)
        return Tensor(jax.random.categorical(
            next_key(), self.logits, shape=shape + tuple(self._batch_shape)))

    @property
    def probs(self):
        return Tensor(jax.nn.softmax(self.logits, axis=-1))

    def log_prob(self, value):
        def fn(v):
            logp = jax.nn.log_softmax(self.logits, axis=-1)
            return jnp.take_along_axis(logp, v[..., None].astype(jnp.int32),
                                       axis=-1)[..., 0]
        return apply(fn, value)

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        p = jnp.exp(logp)
        return Tensor(-jnp.sum(p * logp, axis=-1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _v(probs)
        super().__init__(self.probs_.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        return Tensor((jax.random.uniform(next_key(), shape) <
                       self.probs_).astype(jnp.float32))

    def log_prob(self, value):
        def fn(v):
            p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply(fn, value)

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _v(alpha)
        self.beta = _v(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        return Tensor(jax.random.beta(next_key(), self.alpha, self.beta, shape))

    def log_prob(self, value):
        from jax.scipy.special import betaln
        def fn(v):
            return (self.alpha - 1) * jnp.log(v) + (self.beta - 1) * \
                jnp.log1p(-v) - betaln(self.alpha, self.beta)
        return apply(fn, value)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        a, b = self.alpha, self.beta
        return Tensor(a * b / ((a + b) ** 2 * (a + b + 1)))

    def entropy(self):
        from jax.scipy.special import betaln, digamma
        a, b = self.alpha, self.beta
        return Tensor(betaln(a, b) - (a - 1) * digamma(a) - (b - 1) * digamma(b)
                      + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _v(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        return Tensor(jax.random.dirichlet(next_key(), self.concentration,
                                           tuple(shape) + tuple(self._batch_shape)))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        def fn(v):
            a = self.concentration
            return jnp.sum((a - 1) * jnp.log(v), axis=-1) + \
                gammaln(jnp.sum(a, -1)) - jnp.sum(gammaln(a), -1)
        return apply(fn, value)


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = total_count
        self.probs_ = _v(probs)
        super().__init__(self.probs_.shape[:-1], self.probs_.shape[-1:])

    def sample(self, shape=()):
        n = self.total_count
        cat = jax.random.categorical(
            next_key(), jnp.log(jnp.maximum(self.probs_, 1e-30)),
            shape=tuple(shape) + (n,) + tuple(self._batch_shape))
        k = self.probs_.shape[-1]
        onehot = jax.nn.one_hot(cat, k)
        return Tensor(jnp.sum(onehot, axis=len(shape)))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        def fn(v):
            logp = jnp.log(jnp.maximum(self.probs_, 1e-30))
            return gammaln(jnp.sum(v, -1) + 1) - jnp.sum(gammaln(v + 1), -1) + \
                jnp.sum(v * logp, -1)
        return apply(fn, value)


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _v(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        return Tensor(jax.random.exponential(next_key(), shape) / self.rate)

    def log_prob(self, value):
        return apply(lambda v: jnp.log(self.rate) - self.rate * v, value)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        return Tensor(self.loc + self.scale * jax.random.gumbel(next_key(), shape))

    def log_prob(self, value):
        def fn(v):
            z = (v - self.loc) / self.scale
            return -(z + jnp.exp(-z)) - jnp.log(self.scale)
        return apply(fn, value)


class Geometric(Distribution):
    def __init__(self, probs):
        self.probs_ = _v(probs)
        super().__init__(self.probs_.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        u = jax.random.uniform(next_key(), shape)
        return Tensor(jnp.floor(jnp.log1p(-u) / jnp.log1p(-self.probs_)))

    def log_prob(self, value):
        return apply(lambda v: v * jnp.log1p(-self.probs_) +
                     jnp.log(self.probs_), value)


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        return Tensor(self.loc + self.scale * jax.random.laplace(next_key(), shape))

    def log_prob(self, value):
        return apply(lambda v: -jnp.abs(v - self.loc) / self.scale -
                     jnp.log(2 * self.scale), value)


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        return Tensor(jnp.exp(self.loc + self.scale *
                              jax.random.normal(next_key(), shape)))

    def log_prob(self, value):
        def fn(v):
            logv = jnp.log(v)
            return -((logv - self.loc) ** 2) / (2 * self.scale ** 2) - logv - \
                jnp.log(self.scale) - 0.5 * math.log(2 * math.pi)
        return apply(fn, value)


class Poisson(Distribution):
    def __init__(self, rate):
        self.rate = _v(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        return Tensor(jax.random.poisson(next_key(), self.rate, shape).astype(
            jnp.float32))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        return apply(lambda v: v * jnp.log(self.rate) - self.rate -
                     gammaln(v + 1), value)


def kl_divergence(p, q):
    rule = dispatch_kl(p, q)
    if rule is not None:
        return rule(p, q)
    if isinstance(p, Normal) and isinstance(q, Normal):
        return p.kl_divergence(q)
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        lp = jax.nn.log_softmax(p.logits, -1)
        lq = jax.nn.log_softmax(q.logits, -1)
        return Tensor(jnp.sum(jnp.exp(lp) * (lp - lq), -1))
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        pp = jnp.clip(p.probs_, 1e-7, 1 - 1e-7)
        qq = jnp.clip(q.probs_, 1e-7, 1 - 1e-7)
        return Tensor(pp * (jnp.log(pp) - jnp.log(qq)) +
                      (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))
    raise NotImplementedError(f"kl({type(p).__name__}, {type(q).__name__})")


from paddle_tpu.distribution.transform import (  # noqa: E402,F401
    AbsTransform,
    AffineTransform,
    ChainTransform,
    Constraint,
    ExpTransform,
    IndependentTransform,
    Positive,
    PowerTransform,
    Range,
    Real,
    ReshapeTransform,
    SigmoidTransform,
    Simplex,
    SoftmaxTransform,
    StackTransform,
    StickBreakingTransform,
    TanhTransform,
    Transform,
    Variable,
)
from paddle_tpu.distribution.transformed_distribution import (  # noqa: E402,F401
    ExponentialFamily,
    Independent,
    TransformedDistribution,
    dispatch_kl,
    register_kl,
)


class Stack:
    """Distribution over stacked independent components (reference
    variable.py Stack is the VARIABLE form; the distribution form stacks
    per-slice distributions along `axis`)."""

    def __init__(self, distributions, axis=0):
        self._dists = list(distributions)
        self._axis = axis

    def sample(self, shape=()):
        from paddle_tpu.tensor.manipulation import stack as tstack
        return tstack([d.sample(shape) for d in self._dists],
                      axis=self._axis)

    def log_prob(self, value):
        vv = _v(value)
        slices = jnp.moveaxis(vv, self._axis, 0)
        lps = [
            _v(d.log_prob(Tensor(slices[i])))
            for i, d in enumerate(self._dists)
        ]
        return Tensor(jnp.moveaxis(jnp.stack(lps, 0), 0, self._axis))
