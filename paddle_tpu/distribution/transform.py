"""Bijective transforms of random variables.

Reference parity: python/paddle/distribution/transform.py (Transform :59,
AbsTransform :342, AffineTransform :414, ChainTransform :496,
ExpTransform :621, IndependentTransform :670, PowerTransform :765,
ReshapeTransform :829, SigmoidTransform :953, SoftmaxTransform :996,
StackTransform :1052, StickBreakingTransform :1172, TanhTransform :1238),
constraint.py and variable.py.

All math is jnp through the VJP-tape `apply`, so transforms compose with
autograd and jit the same as any framework op.
"""
from __future__ import annotations

import enum
import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor

__all__ = [
    "Type", "Transform", "AbsTransform", "AffineTransform",
    "ChainTransform", "ExpTransform", "IndependentTransform",
    "PowerTransform", "ReshapeTransform", "SigmoidTransform",
    "SoftmaxTransform", "StackTransform", "StickBreakingTransform",
    "TanhTransform", "Constraint", "Real", "Range", "Positive", "Simplex",
    "Variable",
]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# --------------------------------------------------------- constraints
class Constraint:
    """Value-validity predicate (reference constraint.py:17)."""

    def __call__(self, value):
        raise NotImplementedError


class Real(Constraint):
    def __call__(self, value):
        return apply(lambda v: v == v, value)


class Range(Constraint):
    def __init__(self, lower, upper):
        self._lower, self._upper = lower, upper

    def __call__(self, value):
        return apply(lambda v: (self._lower <= v) & (v <= self._upper),
                     value)


class Positive(Constraint):
    def __call__(self, value):
        return apply(lambda v: v >= 0.0, value)


class Simplex(Constraint):
    def __call__(self, value):
        return apply(lambda v: jnp.all(v >= 0, -1)
                     & (jnp.abs(v.sum(-1) - 1) < 1e-6), value)


real = Real()
positive = Positive()
simplex = Simplex()


# ----------------------------------------------------------- variables
class Variable:
    """Random-variable domain metadata (reference variable.py:18)."""

    def __init__(self, is_discrete=False, event_rank=0, constraint=None):
        self._is_discrete = is_discrete
        self._event_rank = event_rank
        self._constraint = constraint or Real()

    @property
    def is_discrete(self):
        return self._is_discrete

    @property
    def event_rank(self):
        return self._event_rank

    def constraint(self, value):
        return self._constraint(value)


class _RealVariable(Variable):
    def __init__(self, is_discrete=False, event_rank=0):
        super().__init__(is_discrete, event_rank, Real())


class _PositiveVariable(Variable):
    def __init__(self, is_discrete=False, event_rank=0):
        super().__init__(is_discrete, event_rank, Positive())


class _IndependentVariable(Variable):
    def __init__(self, base, reinterpreted_batch_rank):
        super().__init__(base.is_discrete,
                         base.event_rank + reinterpreted_batch_rank,
                         base._constraint)
        self._base = base


class _StackVariable(Variable):
    def __init__(self, vars, axis=0):
        super().__init__(any(v.is_discrete for v in vars),
                         max(v.event_rank for v in vars))
        self._vars = vars
        self._axis = axis


# ----------------------------------------------------------- transforms
class Type(enum.Enum):
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"

    @classmethod
    def is_injective(cls, t):
        return t in (cls.BIJECTION, cls.INJECTION)


class Transform:
    _type = Type.BIJECTION

    @classmethod
    def _is_injective(cls):
        return Type.is_injective(cls._type)

    def __call__(self, input):
        if isinstance(input, Transform):
            return ChainTransform([self, input])
        from paddle_tpu.distribution import Distribution
        if isinstance(input, Distribution):
            from paddle_tpu.distribution.transformed_distribution import (
                TransformedDistribution)
            return TransformedDistribution(input, [self])
        return self.forward(input)

    # public API
    def forward(self, x):
        return Tensor(self._forward(_v(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_v(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._forward_log_det_jacobian(_v(x)))

    def inverse_log_det_jacobian(self, y):
        yv = _v(y)
        return Tensor(-self._forward_log_det_jacobian(self._inverse(yv)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    @property
    def _domain(self):
        return _RealVariable()

    @property
    def _codomain(self):
        return _RealVariable()

    # subclass hooks
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    """y = |x| — surjective, not injective (reference :342)."""

    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # principal branch (the positive preimage)


class AffineTransform(Transform):
    """y = loc + scale * x (reference :414)."""

    def __init__(self, loc, scale):
        self._loc = _v(loc)
        self._scale = _v(scale)

    @property
    def loc(self):
        return Tensor(self._loc)

    @property
    def scale(self):
        return Tensor(self._scale)

    def _forward(self, x):
        return self._loc + self._scale * x

    def _inverse(self, y):
        return (y - self._loc) / self._scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self._scale)), x.shape)


class ExpTransform(Transform):
    """y = exp(x) (reference :621)."""

    @property
    def _codomain(self):
        return _PositiveVariable()

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    """y = x ** power on the positive half-line (reference :765)."""

    def __init__(self, power):
        self._power = _v(power)

    @property
    def power(self):
        return Tensor(self._power)

    @property
    def _domain(self):
        return _PositiveVariable()

    @property
    def _codomain(self):
        return _PositiveVariable()

    def _forward(self, x):
        return jnp.power(x, self._power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self._power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self._power * jnp.power(x, self._power - 1)))


class SigmoidTransform(Transform):
    """y = sigmoid(x) (reference :953)."""

    @property
    def _codomain(self):
        return Variable(False, 0, Range(0.0, 1.0))

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    """y = tanh(x) (reference :1238)."""

    @property
    def _codomain(self):
        return Variable(False, 0, Range(-1.0, 1.0))

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log|dy/dx| = log(1 - tanh^2 x) = 2(log2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """y = softmax(x): surjection onto the simplex (reference :996)."""

    _type = Type.OTHER

    @property
    def _codomain(self):
        return Variable(False, 1, Simplex())

    def _forward(self, x):
        return jax.nn.softmax(x, -1)

    def _inverse(self, y):
        return jnp.log(y)


class StickBreakingTransform(Transform):
    """R^{K} -> interior of the (K+1)-simplex via stick-breaking
    (reference :1172)."""

    @property
    def _domain(self):
        return Variable(False, 1, Real())

    @property
    def _codomain(self):
        return Variable(False, 1, Simplex())

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        zc = jnp.cumprod(1 - z, -1)
        lead = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), zc], -1)
        tail = jnp.concatenate(
            [z, jnp.ones(x.shape[:-1] + (1,), x.dtype)], -1)
        return lead * tail

    def _inverse(self, y):
        k = y.shape[-1] - 1
        cum = jnp.cumsum(y[..., :-1], -1)
        rest = 1 - jnp.concatenate(
            [jnp.zeros(y.shape[:-1] + (1,), y.dtype), cum[..., :-1]], -1)
        z = y[..., :-1] / rest
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=y.dtype))
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _forward_log_det_jacobian(self, x):
        # dy_k/dx_k = z_k (1 - z_k) rest_k (triangular Jacobian):
        # log z = -softplus(-xs), log(1-z) = -softplus(xs)
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        xs = x - offset
        z = jax.nn.sigmoid(xs)
        rest = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype),
             jnp.cumprod(1 - z, -1)[..., :-1]], -1)
        return (-jax.nn.softplus(-xs) - jax.nn.softplus(xs)
                + jnp.log(rest)).sum(-1)


class ReshapeTransform(Transform):
    """Reshape the event block (reference :829)."""

    def __init__(self, in_event_shape, out_event_shape):
        if int(np.prod(in_event_shape)) != int(np.prod(out_event_shape)):
            raise ValueError("in/out event shapes must have equal size")
        self._in = tuple(in_event_shape)
        self._out = tuple(out_event_shape)

    @property
    def in_event_shape(self):
        return self._in

    @property
    def out_event_shape(self):
        return self._out

    def forward_shape(self, shape):
        n = len(self._in)
        if tuple(shape[len(shape) - n:]) != self._in:
            raise ValueError(f"shape {shape} does not end with {self._in}")
        return tuple(shape[:len(shape) - n]) + self._out

    def inverse_shape(self, shape):
        n = len(self._out)
        return tuple(shape[:len(shape) - n]) + self._in

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self._in)]
        return x.reshape(batch + self._out)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self._out)]
        return y.reshape(batch + self._in)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self._in)]
        return jnp.zeros(batch, x.dtype)


class IndependentTransform(Transform):
    """Promote rightmost batch dims of `base` into the event: log-dets
    sum over the reinterpreted dims (reference :670)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self._base = base
        self._rank = int(reinterpreted_batch_rank)

    @property
    def _domain(self):
        return _IndependentVariable(self._base._domain, self._rank)

    @property
    def _codomain(self):
        return _IndependentVariable(self._base._codomain, self._rank)

    def _forward(self, x):
        return self._base._forward(x)

    def _inverse(self, y):
        return self._base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ld = self._base._forward_log_det_jacobian(x)
        return ld.sum(axis=tuple(range(ld.ndim - self._rank, ld.ndim)))


class ChainTransform(Transform):
    """Function composition: last-listed applies first to forward? No —
    reference semantics: transforms apply in LIST ORDER on forward
    (reference :496)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    @classmethod
    def _chain_injective(cls, transforms):
        return all(t._is_injective() for t in transforms)

    def _is_injective(self):
        return self._chain_injective(self.transforms)

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._forward_log_det_jacobian(x)
            x = t._forward(x)
        return total


class StackTransform(Transform):
    """Apply transforms[i] to slice i along `axis` (reference :1052)."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self._axis = axis

    @property
    def axis(self):
        return self._axis

    def _map(self, fn_name, v):
        slices = jnp.moveaxis(v, self._axis, 0)
        outs = [getattr(t, fn_name)(slices[i])
                for i, t in enumerate(self.transforms)]
        return jnp.moveaxis(jnp.stack(outs, 0), 0, self._axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _forward_log_det_jacobian(self, x):
        return self._map("_forward_log_det_jacobian", x)
