"""paddle_tpu.static — static-graph compatibility surface.

Reference: python/paddle/static/__init__.py. In the TPU-native design there
is no separate static interpreter: InputSpec feeds to_static/AOT shapes, and
save/load_inference_model persist state for the inference Predictor.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.dtype import convert_dtype


class InputSpec:
    """Reference: python/paddle/static/input.py."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(list(ndarray.shape), ndarray.dtype, name)

    def batch(self, batch_size):
        self.shape = [batch_size] + self.shape
        return self

    def unbatch(self):
        self.shape = self.shape[1:]
        return self

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    def example(self):
        import jax.numpy as jnp
        from paddle_tpu.core.tensor import Tensor
        shape = [1 if (s is None or s == -1) else s for s in self.shape]
        return Tensor(jnp.zeros(shape, self.dtype))


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """feed_vars (InputSpecs/Tensors) become the exported program's input
    signature; `program` kwarg carries the Layer (TPU design: the compiled
    StableHLO export IS the inference model)."""
    from paddle_tpu.jit import save as jit_save
    program = kwargs.get("program")
    jit_save(program if program is not None else _DummyLayer(), path_prefix,
             input_spec=list(feed_vars) if feed_vars else None)


class _DummyLayer:
    pass


def load_inference_model(path_prefix, executor=None, **kwargs):
    from paddle_tpu.jit import load as jit_load
    return jit_load(path_prefix)


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


class Program:
    """Placeholder for paddle.static.Program (not used in the TPU design)."""

    def __init__(self):
        pass


def default_main_program():
    return Program()


def default_startup_program():
    return Program()
