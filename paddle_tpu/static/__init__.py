"""paddle_tpu.static — static-graph compatibility surface.

Reference: python/paddle/static/__init__.py. In the TPU-native design there
is no separate static interpreter: InputSpec feeds to_static/AOT shapes, and
save/load_inference_model persist state for the inference Predictor.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.dtype import convert_dtype


class InputSpec:
    """Reference: python/paddle/static/input.py."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(list(ndarray.shape), ndarray.dtype, name)

    def batch(self, batch_size):
        self.shape = [batch_size] + self.shape
        return self

    def unbatch(self):
        self.shape = self.shape[1:]
        return self

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    def example(self):
        import jax.numpy as jnp
        from paddle_tpu.core.tensor import Tensor
        shape = [1 if (s is None or s == -1) else s for s in self.shape]
        return Tensor(jnp.zeros(shape, self.dtype))


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """feed_vars (InputSpecs/Tensors) become the exported program's input
    signature; `program` kwarg carries the Layer (TPU design: the compiled
    StableHLO export IS the inference model)."""
    from paddle_tpu.jit import save as jit_save
    program = kwargs.get("program")
    jit_save(program if program is not None else _DummyLayer(), path_prefix,
             input_spec=list(feed_vars) if feed_vars else None)


class _DummyLayer:
    pass


def load_inference_model(path_prefix, executor=None, **kwargs):
    from paddle_tpu.jit import load as jit_load
    return jit_load(path_prefix)


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


class Program:
    """Light paddle.static.Program analogue.  There is no separate
    ProgramDesc interpreter in the TPU design — a "program" is the pair
    (traced callables, state tensors) — but the Program object carries
    the reference's bookkeeping surface: random seed, a global block
    holding created vars/params, and state_dict-style access so
    save/load/program_guard-based user code runs.
    """

    def __init__(self):
        self.random_seed = 0
        self._vars = {}

    def global_block(self):
        return self

    # block-ish surface
    def var(self, name):
        return self._vars[name]

    def all_parameters(self):
        from paddle_tpu.core.tensor import Parameter
        return [v for v in self._vars.values()
                if isinstance(v, Parameter)]

    def list_vars(self):
        return list(self._vars.values())

    def state_dict(self, mode="all"):
        return dict(self._vars)

    def set_state_dict(self, state_dict):
        for k, v in state_dict.items():
            if k in self._vars:
                self._vars[k]._set_value(
                    v._value if hasattr(v, "_value") else v)

    def clone(self, for_test=False):
        p = Program()
        p.random_seed = self.random_seed
        p._vars = dict(self._vars)
        return p


_main_program = [Program()]
_startup_program = [Program()]


def default_main_program():
    return _main_program[0]


def default_startup_program():
    return _startup_program[0]


class program_guard:
    """Scope new vars into the given program (reference static/__init__
    program_guard)."""

    def __init__(self, main_program, startup_program=None):
        self._main = main_program
        self._startup = startup_program

    def __enter__(self):
        self._prev = (_main_program[0], _startup_program[0])
        _main_program[0] = self._main
        if self._startup is not None:
            _startup_program[0] = self._startup
        return self

    def __exit__(self, *exc):
        _main_program[0], _startup_program[0] = self._prev
        return False


class Executor:
    """paddle.static.Executor facade: `run(feed=..., fetch_list=...)`
    calls the traced callables the TPU design compiles — fetch entries
    may be Tensors (returned as numpy) or callables of the feed."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        feed = feed or {}
        outs = []
        for f in (fetch_list or []):
            if callable(f):
                out = f(**feed)
            else:
                out = f
            if return_numpy and hasattr(out, "numpy"):
                out = out.numpy()
            outs.append(out)
        return outs

    def close(self):
        return None


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self.scope

    def __exit__(self, *exc):
        return False


class _Scope:
    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, None)

    def find_var(self, name):
        return self._vars.get(name)


_global_scope = _Scope()


def global_scope():
    return _global_scope


def name_scope(prefix=None):
    import contextlib

    @contextlib.contextmanager
    def ctx():
        yield

    return ctx()


def device_guard(device=None):
    import contextlib

    @contextlib.contextmanager
    def ctx():
        yield

    return ctx()


def cpu_places(device_count=None):
    import jax

    from paddle_tpu.core.device import CPUPlace
    n = device_count or max(1, len(jax.devices("cpu")))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    return []  # no CUDA devices on the TPU backend


def npu_places(device_ids=None):
    return []


def xpu_places(device_ids=None):
    return []


def mlu_places(device_ids=None):
    return []


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    import jax.numpy as jnp

    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.framework.state import register_state_tensor
    t = Tensor(jnp.full(tuple(shape), value, convert_dtype(dtype)),
               name=name)
    t.persistable = persistable
    if persistable:
        register_state_tensor(t)
    default_main_program()._vars[t.name] = t
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    import jax.numpy as jnp

    from paddle_tpu.core.tensor import Parameter
    # Parameter registers itself as a state tensor on construction
    p = Parameter(jnp.zeros(tuple(shape), convert_dtype(dtype)), name=name)
    init = default_initializer or (
        attr.initializer if attr is not None and getattr(
            attr, "initializer", None) else None)
    if init is not None:
        init(p)
    default_main_program()._vars[p.name] = p
    return p


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Symbolic-gradient analogue: tape/jax gradients of targets w.r.t.
    inputs (reference static append_backward/gradients pair).
    target_gradients weight each target BEFORE the scalar reduction —
    the reference's cotangent contract."""
    from paddle_tpu.autograd import grad
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is not None:
        tg = target_gradients if isinstance(
            target_gradients, (list, tuple)) else [target_gradients]
        targets = [t if w is None else t * w
                   for t, w in zip(targets, tg)]
    total = targets[0].sum()
    for t in targets[1:]:
        total = total + t.sum()
    return grad(total, inputs, allow_unused=True)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Eager/tape analogue of append_backward: computes grads and returns
    (param, grad) pairs like the reference."""
    loss.backward()
    params = parameter_list or []
    return [(p, p.grad) for p in params if getattr(p, "grad", None)
            is not None]


def accuracy(input, label, k=1, correct=None, total=None):
    from paddle_tpu.metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    from paddle_tpu.metric import Auc
    m = Auc(num_thresholds=min(num_thresholds, 4095))
    m.update(input, label)
    import numpy as _np

    import paddle_tpu as P
    return P.to_tensor(_np.asarray(m.accumulate(), _np.float32))


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op (reference static/nn/common.py py_func): runs func
    on host values; the tape records it via pure_callback semantics —
    eager path calls directly."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    result = func(*xs)
    if out is not None and hasattr(out, "_set_value") and hasattr(
            result, "_value"):
        out._set_value(result._value)
        return out
    return result


class WeightNormParamAttr:
    """reference static/nn/common.py WeightNormParamAttr: ParamAttr that
    applies weight normalization (dim) on the created parameter."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        from paddle_tpu.nn.initializer import ParamAttr
        self.dim = dim
        self.attr = ParamAttr(name=name, initializer=initializer,
                              learning_rate=learning_rate,
                              regularizer=regularizer, trainable=trainable)


class ExponentialMovingAverage:
    """EMA of parameters (reference static/__init__.py
    ExponentialMovingAverage): update() folds current params into the
    shadow values; apply()/restore() swap them in and out."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = None
        self._params = None

    def _ensure(self, params):
        import jax.numpy as jnp
        if self._params is None:
            self._params = list(params)
            for p in self._params:
                self._shadow[id(p)] = p._value.astype(jnp.float32) + 0

    def update(self, parameters=None):
        import jax.numpy as jnp
        if parameters is None and self._params is None:
            raise ValueError(
                "first update() needs the parameter list (the reference "
                "discovers it from the static program; there is none here)")
        self._ensure(parameters or self._params)
        d = self._decay
        for p in self._params:
            sh = self._shadow[id(p)]
            self._shadow[id(p)] = d * sh + (1 - d) * p._value.astype(
                jnp.float32)

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._backup = [(p, p._value) for p in self._params or []]
            for p in (self._params or []):
                p._set_value(self._shadow[id(p)].astype(p._value.dtype))
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return ctx()

    def restore(self, executor=None):
        if self._backup:
            for p, v in self._backup:
                p._set_value(v)
            self._backup = None


class BuildStrategy:
    """Compile-strategy bag; XLA owns fusion/layout decisions, knobs are
    accepted for compatibility."""

    def __init__(self):
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.enable_auto_fusion = False
        self.memory_optimize = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """The reference compiles a ProgramDesc; here to_static already
    produces the compiled XLA executable, so this wraps and forwards."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def with_data_parallel(self, *a, **kw):
        return self

    def __getattr__(self, item):
        return getattr(self._program, item)


ParallelExecutor = CompiledProgram


def serialize_program(feed_vars, fetch_vars, **kwargs):
    # JSON, not pickle: loading a serialized program must never execute
    # code (same policy as jit.serialization's PTPU container)
    import json
    return json.dumps({"feed": [getattr(v, "name", None)
                                for v in feed_vars],
                       "fetch": [getattr(v, "name", None)
                                 for v in fetch_vars]}).encode()


def deserialize_program(data):
    import json
    return json.loads(data.decode() if isinstance(data, bytes) else data)


def serialize_persistables(feed_vars, fetch_vars, executor=None, **kwargs):
    import io

    import numpy as _np
    buf = io.BytesIO()
    prog = default_main_program()
    _np.savez(buf, **{k: _np.asarray(v._value)
                      for k, v in prog._vars.items()})
    return buf.getvalue()


def deserialize_persistables(program, data, executor=None):
    import io

    import numpy as _np
    loaded = _np.load(io.BytesIO(data))
    for k in loaded.files:
        if k in program._vars:
            program._vars[k]._set_value(loaded[k])
    return program


def save(program, model_prefix):
    import numpy as _np
    # write through a file object so the on-disk name is EXACTLY
    # `prefix.pdparams` (np.savez appends .npz to bare string names)
    with open(model_prefix + ".pdparams", "wb") as f:
        _np.savez(f, **{k: _np.asarray(v._value)
                        for k, v in program._vars.items()})


def _params_path(model_prefix):
    import os
    path = model_prefix + ".pdparams"
    if not os.path.exists(path) and os.path.exists(path + ".npz"):
        return path + ".npz"  # back-compat: earlier saves via bare savez
    return path


def load(program, model_prefix, executor=None, var_list=None):
    import numpy as _np
    loaded = _np.load(_params_path(model_prefix))
    for k in loaded.files:
        if k in program._vars:
            program._vars[k]._set_value(loaded[k])


def load_program_state(model_prefix, var_list=None):
    import numpy as _np
    loaded = _np.load(_params_path(model_prefix))
    return {k: loaded[k] for k in loaded.files}


def set_program_state(program, state):
    for k, v in state.items():
        if k in program._vars:
            program._vars[k]._set_value(v)


def normalize_program(program, feed_vars, fetch_vars):
    return program


# reference static Variable — in the TPU design every variable IS a
# Tensor; a direct alias keeps `isinstance(x, static.Variable)` true for
# Tensors in ported code
from paddle_tpu.core.tensor import Tensor as Variable  # noqa: E402


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print (reference static/nn/control_flow.py Print): eager
    prints immediately; under jit it becomes jax.debug.print."""
    import jax

    from paddle_tpu.core.dispatch import apply

    def fn(v):
        jax.debug.print((message or "") + " {}", v)
        return v

    return apply(fn, input)


from paddle_tpu.static import nn  # noqa: E402,F401
from paddle_tpu.static import sparsity  # noqa: E402,F401


def save_to_file(path, content):
    """Raw-bytes file write (reference static/io.py:423)."""
    if not isinstance(content, bytes):
        raise ValueError("save_to_file expects bytes content")
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    """Raw-bytes file read (reference static/io.py:704)."""
    with open(path, "rb") as f:
        return f.read()


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """fluid-era lr helper (reference fluid/layers/
    learning_rate_scheduler.py:119): continuous form decays every step
    (gamma chosen so lr(decay_steps) == learning_rate * decay_rate);
    staircase holds lr constant within each decay_steps window."""
    if staircase:
        from paddle_tpu.optimizer.lr import LambdaDecay
        return LambdaDecay(
            learning_rate,
            lr_lambda=lambda ep: decay_rate ** (ep // decay_steps))
    from paddle_tpu.optimizer.lr import ExponentialDecay
    return ExponentialDecay(learning_rate,
                            gamma=decay_rate ** (1.0 / decay_steps))


def ctr_metric_bundle(input, label, ins_tag_weight=None, name="default"):
    """CTR metric accumulators (reference fluid/contrib/layers/
    metric_op.py:28): returns six running-stat tensors
    (local_sqrerr, local_abserr, local_prob, local_q, local_pos_num,
    local_ins_num) that ACCUMULATE across calls — one persistent bundle
    per `name`, like the reference's per-graph global variables.
    Finalize as MAE = local_abserr/local_ins_num, RMSE =
    sqrt(local_sqrerr/local_ins_num), predicted_ctr =
    local_prob/local_ins_num, q = local_q/local_ins_num. In a
    distributed job all-reduce the six accumulators first (plain state
    tensors — distributed.all_reduce applies directly)."""
    import jax.numpy as jnp

    import paddle_tpu
    from paddle_tpu.core.engine import no_grad
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.framework.state import register_state_tensor

    pred = input if isinstance(input, Tensor) else paddle_tpu.to_tensor(input)
    lab = label if isinstance(label, Tensor) else paddle_tpu.to_tensor(label)

    bundle = _ctr_bundles.get(name)
    if bundle is None:
        bundle = []
        for stat in ("local_sqrerr", "local_abserr", "local_prob",
                     "local_q", "local_pos_num", "local_ins_num"):
            t = Tensor(jnp.zeros((1,), jnp.float32),
                       name=f"ctr_{name}_{stat}")
            t.persistable = True
            # created lazily, possibly inside a to_static trace: the
            # snapshot machinery re-inits mid-trace-created state from
            # this spec, then the retrace lifts it properly
            t._reinit = lambda: jnp.zeros((1,), jnp.float32)
            register_state_tensor(t)
            bundle.append(t)
        _ctr_bundles[name] = bundle
    sqrerr, abserr, prob, q, pos_num, ins_num = bundle

    pv = pred._value.astype(jnp.float32).reshape(-1)
    lv = lab._value.astype(jnp.float32).reshape(-1)
    if ins_tag_weight is None:
        wv = jnp.float32(1.0)
    else:
        w = ins_tag_weight if isinstance(ins_tag_weight, Tensor) \
            else paddle_tpu.to_tensor(ins_tag_weight)
        wv = w._value.astype(jnp.float32).reshape(-1)[0]
    err = pv - lv
    with no_grad():
        sqrerr._set_value(sqrerr._value + jnp.sum(err * err)[None] * wv)
        abserr._set_value(abserr._value + jnp.sum(jnp.abs(err))[None] * wv)
        prob._set_value(prob._value + jnp.sum(pv)[None] * wv)
        # q-value: sum of pred/(1-pred) odds, the reference's calibration
        q._set_value(q._value + jnp.sum(
            pv / jnp.clip(1.0 - pv, 1e-6, None))[None] * wv)
        pos_num._set_value(pos_num._value + jnp.sum(lv)[None] * wv)
        ins_num._set_value(ins_num._value + jnp.float32(
            lv.shape[0])[None] * wv)
    return sqrerr, abserr, prob, q, pos_num, ins_num


_ctr_bundles = {}
