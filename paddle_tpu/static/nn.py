"""paddle.static.nn parity (reference: python/paddle/static/nn/
__init__.py:62 and static/nn/common.py / control_flow.py).

The reference's static layer functions append ops + parameters to the
active fluid Program.  Here each call builds the matching eager layer
(parameters register as state tensors and land in the active Program's
var table) and applies it immediately — the TPU design's "program" is
the traced computation itself.  Layers are cached per call site name so
repeated invocations inside a training loop reuse their parameters.

Control flow (cond/case/switch_case/while_loop) runs through
`lax.cond`/`lax.while_loop` under a trace and plain Python eagerly.
Sequence ops operate on dense [batch, time, ...] tensors with an
explicit length tensor — the dense analogue of fluid's LoD tensors
(LoD does not exist in this framework).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor

__all__ = [
    "fc", "batch_norm", "embedding", "bilinear_tensor_product", "case",
    "cond", "conv2d", "conv2d_transpose", "conv3d", "conv3d_transpose",
    "crf_decoding", "data_norm", "deform_conv2d", "group_norm",
    "instance_norm", "layer_norm", "prelu", "py_func", "spectral_norm",
    "switch_case", "while_loop", "sparse_embedding", "sequence_softmax",
    "sequence_pool", "sequence_concat", "sequence_first_step",
    "sequence_last_step", "sequence_reverse", "StaticRNN",
    "sequence_pad", "sequence_unpad", "sequence_reshape",
    "sequence_slice", "sequence_expand", "sequence_expand_as",
    "sequence_enumerate", "sequence_scatter", "sequence_conv",
    "row_conv", "nce", "multi_box_head",
]

_layer_cache = {}


def _call_site():
    """(filename, lineno) of the user call two frames up — the identity
    of an UNNAMED static.nn layer, so a layer invoked in a training loop
    reuses its parameters while two different unnamed calls of the same
    shape stay distinct."""
    import sys
    f = sys._getframe(3)
    return (f.f_code.co_filename, f.f_lineno)


def _cached(key, make):
    key = key if key[1] is not None else (key[0], _call_site(), *key[2:])
    if key not in _layer_cache:
        _layer_cache[key] = make()
    return _layer_cache[key]


# ------------------------------------------------------------- layers
def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from paddle_tpu import nn
    in_dim = 1
    for s in x.shape[num_flatten_dims:]:
        in_dim *= s
    layer = _cached(("fc", name, in_dim, size), lambda: nn.Linear(
        in_dim, size, weight_attr=weight_attr, bias_attr=bias_attr))
    flat = x.reshape(list(x.shape[:num_flatten_dims]) + [in_dim])
    out = layer(flat)
    if activation is not None:
        from paddle_tpu.nn import functional as F
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    from paddle_tpu import nn
    layer = _cached(("emb", getattr(param_attr, "name", None), *size),
                    lambda: nn.Embedding(size[0], size[1],
                                         padding_idx=padding_idx,
                                         sparse=is_sparse,
                                         weight_attr=param_attr))
    return layer(input)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False, is_test=False):
    from paddle_tpu import nn
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _cached(("bn", name, c), lambda: nn.BatchNorm2D(
        c, momentum=momentum, epsilon=epsilon, weight_attr=param_attr,
        bias_attr=bias_attr,
        data_format=data_layout) if len(input.shape) == 4
        else nn.BatchNorm1D(c, momentum=momentum, epsilon=epsilon))
    # set mode EVERY call: a one-off is_test pass must not freeze the
    # cached layer in eval for the rest of training
    layer.eval() if is_test else layer.train()
    out = layer(input)
    if act:
        from paddle_tpu.nn import functional as F
        out = getattr(F, act)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCHW"):
    from paddle_tpu import nn
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = _cached(
        ("conv2d", name, cin, num_filters, str(filter_size)),
        lambda: nn.Conv2D(cin, num_filters, filter_size, stride=stride,
                          padding=padding, dilation=dilation,
                          groups=groups, weight_attr=param_attr,
                          bias_attr=bias_attr, data_format=data_format))
    out = layer(input)
    if act:
        from paddle_tpu.nn import functional as F
        out = getattr(F, act)(out)
    return out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    from paddle_tpu import nn
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    if filter_size is None:
        # reference derives the kernel from the requested output size:
        # out = (in - 1) * stride + k - 2 * pad  =>  k = ...
        if output_size is None:
            raise ValueError("conv2d_transpose needs filter_size or "
                             "output_size")
        osz = output_size if isinstance(output_size, (list, tuple)) \
            else (output_size, output_size)
        st = stride if isinstance(stride, (list, tuple)) \
            else (stride, stride)
        pd = padding if isinstance(padding, (list, tuple)) \
            else (padding, padding)
        in_sp = input.shape[2:4] if data_format == "NCHW" \
            else input.shape[1:3]
        filter_size = tuple(
            osz[i] - (in_sp[i] - 1) * st[i] + 2 * pd[i] for i in range(2))
    layer = _cached(
        ("convT2d", name, cin, num_filters, str(filter_size)),
        lambda: nn.Conv2DTranspose(cin, num_filters, filter_size,
                                   stride=stride, padding=padding,
                                   dilation=dilation, groups=groups,
                                   weight_attr=param_attr,
                                   bias_attr=bias_attr,
                                   data_format=data_format))
    out = layer(input)
    if act:
        from paddle_tpu.nn import functional as F
        out = getattr(F, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    from paddle_tpu import nn
    cin = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    layer = _cached(
        ("conv3d", name, cin, num_filters, str(filter_size)),
        lambda: nn.Conv3D(cin, num_filters, filter_size, stride=stride,
                          padding=padding, dilation=dilation,
                          groups=groups, weight_attr=param_attr,
                          bias_attr=bias_attr, data_format=data_format))
    out = layer(input)
    if act:
        from paddle_tpu.nn import functional as F
        out = getattr(F, act)(out)
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    from paddle_tpu import nn
    cin = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    if filter_size is None:
        if output_size is None:
            raise ValueError("conv3d_transpose needs filter_size or "
                             "output_size")
        osz = output_size if isinstance(output_size, (list, tuple)) \
            else (output_size,) * 3
        st = stride if isinstance(stride, (list, tuple)) \
            else (stride,) * 3
        pd = padding if isinstance(padding, (list, tuple)) \
            else (padding,) * 3
        in_sp = input.shape[2:5] if data_format == "NCDHW" \
            else input.shape[1:4]
        filter_size = tuple(
            osz[i] - (in_sp[i] - 1) * st[i] + 2 * pd[i] for i in range(3))
    layer = _cached(
        ("convT3d", name, cin, num_filters, str(filter_size)),
        lambda: nn.Conv3DTranspose(cin, num_filters, filter_size,
                                   stride=stride, padding=padding,
                                   dilation=dilation, groups=groups,
                                   weight_attr=param_attr,
                                   bias_attr=bias_attr,
                                   data_format=data_format))
    out = layer(input)
    if act:
        from paddle_tpu.nn import functional as F
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from paddle_tpu import nn
    norm_shape = list(input.shape[begin_norm_axis:])
    layer = _cached(("ln", name, tuple(norm_shape)),
                    lambda: nn.LayerNorm(norm_shape, epsilon=epsilon,
                                         weight_attr=param_attr,
                                         bias_attr=bias_attr))
    out = layer(input)
    if act:
        from paddle_tpu.nn import functional as F
        out = getattr(F, act)(out)
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from paddle_tpu import nn
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _cached(("gn", name, groups, c),
                    lambda: nn.GroupNorm(groups, c, epsilon=epsilon,
                                         weight_attr=param_attr,
                                         bias_attr=bias_attr,
                                         data_format=data_layout))
    out = layer(input)
    if act:
        from paddle_tpu.nn import functional as F
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from paddle_tpu import nn
    c = input.shape[1]
    layer = _cached(("in", name, c),
                    lambda: nn.InstanceNorm2D(c, epsilon=epsilon))
    return layer(input)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              enable_scale_and_shift=False, name=None, **kwargs):
    """Batch-statistics normalization without learnable affine by
    default (reference static/nn/common.py data_norm)."""
    def fn(v):
        mean = v.mean(axis=0, keepdims=True)
        var = v.var(axis=0, keepdims=True)
        return (v - mean) * jax.lax.rsqrt(var + epsilon)
    out = apply(fn, input)
    if act:
        from paddle_tpu.nn import functional as F
        out = getattr(F, act)(out)
    return out


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from paddle_tpu import nn
    if mode == "all":
        num = 1
    elif mode == "channel":
        num = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    else:
        num = 1
        for s in x.shape[1:]:
            num *= s
    layer = _cached(("prelu", name, mode, num),
                    lambda: nn.PReLU(num_parameters=num,
                                     weight_attr=param_attr,
                                     data_format=data_format))
    return layer(x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from paddle_tpu import nn
    layer = _cached(("sn", name, tuple(weight.shape)),
                    lambda: nn.SpectralNorm(weight.shape, dim=dim,
                                            power_iters=power_iters,
                                            epsilon=eps))
    return layer(weight)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    from paddle_tpu.vision.ops import DeformConv2D
    cin = x.shape[1]
    layer = _cached(("dcn", name, cin, num_filters, str(filter_size)),
                    lambda: DeformConv2D(cin, num_filters, filter_size,
                                         stride=stride, padding=padding,
                                         dilation=dilation, groups=groups,
                                         deformable_groups=deformable_groups))
    return layer(x, offset, mask)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from paddle_tpu import nn
    layer = _cached(("bilinear", name, x.shape[-1], y.shape[-1], size),
                    lambda: nn.Bilinear(x.shape[-1], y.shape[-1], size,
                                        weight_attr=param_attr,
                                        bias_attr=bias_attr))
    out = layer(x, y)
    if act:
        from paddle_tpu.nn import functional as F
        out = getattr(F, act)(out)
    return out


def crf_decoding(potentials, transitions=None, lengths=None, label=None,
                 param_attr=None):
    """Viterbi decode (reference crf_decoding routes to the CRF kernel;
    the text namespace holds the TPU implementation).  With no explicit
    `transitions`, a learnable [n, n] transition table is created (per
    call site / param_attr name) like the reference's CRF weight; the
    paddle convention keeps bos/eos as the last two of the n tags."""
    import jax.numpy as jnp

    from paddle_tpu.text import viterbi_decode
    num_tags = potentials.shape[-1]
    if transitions is None:
        from paddle_tpu import nn

        class _Trans(nn.Layer):
            def __init__(self):
                super().__init__()
                self.weight = self.create_parameter(
                    [num_tags, num_tags], attr=param_attr)

        holder = _cached(("crf_trans",
                          getattr(param_attr, "name", None), num_tags),
                         _Trans)
        transitions = holder.weight
    if lengths is None:
        batch, time = potentials.shape[0], potentials.shape[1]
        lengths = Tensor(jnp.full((batch,), time, jnp.int32))
    scores, path = viterbi_decode(potentials, transitions, lengths)
    return path


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    from paddle_tpu.static import py_func as _py
    return _py(func, x, out, backward_func, skip_vars_in_backward_input)


# ------------------------------------------------------- control flow
def _is_tracing(*tensors):
    return any(isinstance(getattr(t, "_value", None), jax.core.Tracer)
               for t in tensors if isinstance(t, Tensor))


def cond(pred, true_fn=None, false_fn=None, name=None,
         return_names=None):
    """Two-branch conditional (reference control_flow.py cond): under a
    trace this lowers to lax.cond (both branches traced); eagerly it is
    a Python if."""
    if isinstance(pred, Tensor) and _is_tracing(pred):
        if true_fn is None or false_fn is None:
            raise ValueError(
                "under a trace, cond needs BOTH branches (lax.cond "
                "requires matching outputs; a None branch is only legal "
                "eagerly, where it means 'return None')")

        def wrap(fn):
            def inner(_):
                out = fn()
                # Tensors are not jax pytree leaves: strip them in any
                # (possibly nested) branch output structure
                return jax.tree_util.tree_map(
                    lambda o: o._value if isinstance(o, Tensor) else o,
                    out, is_leaf=lambda o: isinstance(o, Tensor))
            return inner

        out = jax.lax.cond(pred._value.reshape(()),
                           wrap(true_fn), wrap(false_fn), 0)
        return jax.tree_util.tree_map(Tensor, out)
    taken = bool(pred.numpy()) if isinstance(pred, Tensor) else bool(pred)
    branch = true_fn if taken else false_fn
    return branch() if branch is not None else None


def case(pred_fn_pairs, default=None, name=None):
    """First-match-wins multi-branch (reference control_flow.py case).
    Eager-only: nest `cond` for a traced multi-branch."""
    if _is_tracing(*[p for p, _ in pred_fn_pairs
                     if isinstance(p, Tensor)]):
        raise NotImplementedError(
            "static.nn.case needs concrete predicates; under to_static "
            "compose nested static.nn.cond calls (lax.cond) instead")
    for pred, fn in pred_fn_pairs:
        taken = bool(pred.numpy()) if isinstance(pred, Tensor) else \
            bool(pred)
        if taken:
            return fn()
    if default is not None:
        return default()
    return pred_fn_pairs[-1][1]()


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Index-dispatched branch (reference control_flow.py switch_case).
    Eager-only: use lax.switch-style nesting of `cond` under a trace."""
    if isinstance(branch_index, Tensor) and _is_tracing(branch_index):
        raise NotImplementedError(
            "static.nn.switch_case needs a concrete index; under "
            "to_static compose nested static.nn.cond calls instead")
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) \
        else branch_fns
    idx = int(branch_index.numpy()) if isinstance(branch_index, Tensor) \
        else int(branch_index)
    if idx in fns:
        return fns[idx]()
    if default is not None:
        return default()
    return fns[max(fns)]()


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """While loop (reference control_flow.py while_loop): lax.while_loop
    under a trace (body must keep shapes/dtypes stable), Python loop
    eagerly."""
    if _is_tracing(*loop_vars):
        def c(vals):
            out = cond(*[Tensor(v) for v in vals])
            return out._value.reshape(()) if isinstance(out, Tensor) \
                else out

        def b(vals):
            outs = body(*[Tensor(v) for v in vals])
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in outs)

        final = jax.lax.while_loop(
            c, b, tuple(v._value if isinstance(v, Tensor) else v
                        for v in loop_vars))
        return [Tensor(v) for v in final]
    vals = list(loop_vars)
    while True:
        c = cond(*vals)  # evaluate ONCE per iteration
        if not bool(c.numpy() if isinstance(c, Tensor) else c):
            break
        out = body(*vals)
        vals = list(out) if isinstance(out, (list, tuple)) else [out]
    return vals


# ------------------------------------------------------- sequence ops
# Dense [batch, time, ...] + explicit lengths replace fluid LoD tensors.
def _length_mask(lengths, time, dtype=jnp.float32):
    t = jnp.arange(time)
    return (t[None, :] < lengths[:, None]).astype(dtype)


def sequence_softmax(input, lengths=None, name=None):
    def fn(v, *rest):
        if rest:
            mask = _length_mask(rest[0], v.shape[1], v.dtype)
            v = jnp.where(mask[..., None] > 0 if v.ndim == 3
                          else mask > 0, v, -1e30)
        return jax.nn.softmax(v, axis=1)
    if lengths is None:
        return apply(fn, input)
    return apply(fn, input, lengths)


def sequence_pool(input, pool_type, lengths=None, pad_value=0.0):
    def fn(v, *rest):
        mask = None
        if rest:
            mask = _length_mask(rest[0], v.shape[1], v.dtype)
            while mask.ndim < v.ndim:
                mask = mask[..., None]
        if pool_type.lower() == "sum":
            return (v * mask).sum(1) if mask is not None else v.sum(1)
        if pool_type.lower() in ("average", "mean"):
            if mask is not None:
                return (v * mask).sum(1) / jnp.maximum(mask.sum(1), 1)
            return v.mean(1)
        if pool_type.lower() == "max":
            if mask is not None:
                v = jnp.where(mask > 0, v, -jnp.inf)
                out = v.max(1)
                # zero-length rows have nothing to pool: reference fills
                # them with pad_value instead of -inf
                empty = mask.reshape(mask.shape[0], mask.shape[1], -1
                                     ).sum(axis=(1, 2)) == 0
                shape = (out.shape[0],) + (1,) * (out.ndim - 1)
                return jnp.where(empty.reshape(shape), pad_value, out)
            return v.max(1)
        if pool_type.lower() == "sqrt":
            if mask is not None:
                return (v * mask).sum(1) / jnp.sqrt(
                    jnp.maximum(mask.sum(1), 1))
            return v.sum(1) / jnp.sqrt(v.shape[1])
        if pool_type.lower() in ("first", "last"):
            if pool_type.lower() == "first":
                return v[:, 0]
            if rest:
                idx = jnp.maximum(rest[0].astype(jnp.int32) - 1, 0)
                return jnp.take_along_axis(
                    v, idx[:, None, None] if v.ndim == 3
                    else idx[:, None], axis=1).squeeze(1)
            return v[:, -1]
        raise ValueError(f"unknown pool_type {pool_type}")
    if lengths is None:
        return apply(fn, input)
    return apply(fn, input, lengths)


def sequence_first_step(input, lengths=None):
    return sequence_pool(input, "first", lengths)


def sequence_last_step(input, lengths=None):
    return sequence_pool(input, "last", lengths)


def sequence_concat(input, name=None):
    from paddle_tpu.tensor.manipulation import concat
    return concat(list(input), axis=1)


def sequence_reverse(x, lengths=None, name=None):
    def fn(v, *rest):
        if not rest:
            return jnp.flip(v, axis=1)
        t = v.shape[1]
        lens = rest[0].astype(jnp.int32)
        idx = jnp.arange(t)[None, :]
        rev = jnp.where(idx < lens[:, None], lens[:, None] - 1 - idx, idx)
        return jnp.take_along_axis(
            v, rev[..., None] if v.ndim == 3 else rev, axis=1)
    if lengths is None:
        return apply(fn, x)
    return apply(fn, x, lengths)



def _param(key, shape, attr=None, is_bias=False):
    """Cached parameter holder for the functional static.nn ops (same
    call-site identity rules as _cached)."""
    import sys

    from paddle_tpu import nn

    if key[1] is None:
        # resolve the USER call site here — _cached's own frame walk
        # would land inside the static.nn op function (one extra frame
        # through _param) and silently share weights across call sites
        f = sys._getframe(2)
        key = (key[0], ("site", f.f_code.co_filename, f.f_lineno),
               *key[2:])

    def make():
        class _Holder(nn.Layer):
            def __init__(self):
                super().__init__()
                self.weight = self.create_parameter(
                    list(shape), attr=attr, is_bias=is_bias)

        return _Holder()

    return _cached(key, make).weight


def sequence_pad(x, pad_value, maxlen=None, lengths=None, name=None):
    """Pad variable-length rows to a common length (reference
    sequence_lod.py sequence_pad). Dense form: positions >= lengths[b]
    fill with pad_value; returns (padded, lengths)."""
    def fn(v, pv, *rest):
        t = v.shape[1] if maxlen is None else maxlen
        orig_t = v.shape[1]
        out = v[:, :t] if orig_t >= t else jnp.pad(
            v, [(0, 0), (0, t - orig_t)] + [(0, 0)] * (v.ndim - 2))
        if rest:
            mask = _length_mask(rest[0], t, jnp.bool_)
        else:
            # no lengths: only the maxlen extension is padding
            mask = (jnp.arange(t) < orig_t)[None, :]
        while mask.ndim < out.ndim:
            mask = mask[..., None]
        return jnp.where(mask, out, jnp.asarray(pv, out.dtype))

    lens = lengths
    if lens is None:
        from paddle_tpu.tensor.creation import full
        lens = full([x.shape[0]], x.shape[1], dtype="int64")
    padded = apply(fn, x, pad_value, lens) if lengths is not None \
        else apply(fn, x, pad_value)
    return padded, lens


def sequence_unpad(x, length, name=None):
    """Trim padding to the max real length and zero the tail (reference
    sequence_unpad; true ragged rows don't exist on TPU — static shapes —
    so the result keeps the batch layout with an exact-length mask)."""
    def fn(v, lens):
        t = v.shape[1]
        mask = _length_mask(lens, t, v.dtype)
        while mask.ndim < v.ndim:
            mask = mask[..., None]
        return v * mask

    return apply(fn, x, length)


def sequence_reshape(input, new_dim, name=None):
    """Refold the feature dim (reference sequence_reshape: total elements
    per batch row preserved, time adjusts to match new_dim)."""
    def fn(v):
        b = v.shape[0]
        return v.reshape(b, -1, new_dim)

    return apply(fn, input)


def sequence_slice(input, offset, length, name=None):
    """Per-row [offset, offset+length) window (reference sequence_slice).
    `length` must be a python int / equal per row (static shapes)."""
    def fn(v, off):
        off = off.reshape(-1).astype(jnp.int32)
        ln = int(np.asarray(jax.device_get(length._value)).reshape(-1)[0]) \
            if hasattr(length, "_value") else int(np.asarray(length).reshape(-1)[0])
        idx = off[:, None] + jnp.arange(ln)[None, :]
        idx = jnp.clip(idx, 0, v.shape[1] - 1)
        return jnp.take_along_axis(
            v, idx[..., None] if v.ndim == 3 else idx, axis=1)

    return apply(fn, input, offset)


def sequence_expand(x, y, ref_level=-1, name=None):
    """Repeat x's rows per y's row-lengths (reference sequence_expand's
    LoD broadcast). Dense form: x [B, ...] tiled to match y's batch."""
    def fn(xv, yv):
        if xv.shape[0] == yv.shape[0]:
            return xv
        if yv.shape[0] % xv.shape[0]:
            raise ValueError(
                f"sequence_expand: target batch {yv.shape[0]} is not a "
                f"multiple of source batch {xv.shape[0]}")
        rep = yv.shape[0] // xv.shape[0]
        return jnp.repeat(xv, rep, axis=0)

    return apply(fn, x, y)


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """Sliding windows of ids: [B, T] -> [B, T, win_size], positions past
    the end fill with pad_value (reference sequence_enumerate)."""
    def fn(v):
        t = v.shape[1]
        idx = jnp.arange(t)[:, None] + jnp.arange(win_size)[None, :]
        valid = idx < t
        gathered = v[:, jnp.clip(idx, 0, t - 1)]
        return jnp.where(valid[None], gathered,
                         jnp.asarray(pad_value, v.dtype))

    return apply(fn, input)


def sequence_scatter(input, index, updates, name=None):
    """Scatter updates into flat positions (reference sequence_scatter's
    dense rendering: index addresses dim-0 rows of a 2-D input)."""
    def fn(v, idx, upd):
        return v.at[idx.reshape(-1).astype(jnp.int32)].add(
            upd.reshape((-1,) + v.shape[1:]))

    return apply(fn, input, index, updates)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, param_attr=None,
                  bias_attr=None, act=None, name=None):
    """Context-window conv over time (reference sequence_conv): each
    step sees `filter_size` consecutive steps; implemented as one MXU
    matmul over the unfolded windows."""
    d = input.shape[-1]
    weight = _param(("seqconv_w", getattr(param_attr, "name", None),
                     filter_size * d, num_filters),
                    (filter_size * d, num_filters), param_attr)
    bias = _param(("seqconv_b", getattr(bias_attr, "name", None),
                   num_filters), (num_filters,), bias_attr,
                  is_bias=True) if bias_attr is not False else None

    start = -((filter_size - 1) // 2) if padding_start is None \
        else padding_start

    def fn(v, w, *rest):
        b, t, dd = v.shape
        offs = start + jnp.arange(filter_size)
        idx = jnp.arange(t)[:, None] + offs[None, :]
        valid = (idx >= 0) & (idx < t)
        g = v[:, jnp.clip(idx, 0, t - 1)]               # [b, t, fs, d]
        g = jnp.where(valid[None, :, :, None], g, 0.0)
        out = g.reshape(b, t, filter_size * dd) @ w
        if rest:
            out = out + rest[0]
        if act == "relu":
            out = jax.nn.relu(out)
        elif act == "tanh":
            out = jnp.tanh(out)
        return out

    if bias is not None:
        return apply(fn, input, weight, bias)
    return apply(fn, input, weight)


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """Lookahead (row) convolution (reference common.py row_conv — the
    DeepSpeech2 streaming op): out[t] = sum_{i=0..k} w[i] * x[t+i],
    a depthwise causal-in-reverse window over time."""
    d = input.shape[-1]
    k = future_context_size + 1
    weight = _param(("row_conv_w", getattr(param_attr, "name", None),
                     k, d), (k, d), param_attr)

    def fn(v, w):
        b, t, dd = v.shape
        idx = jnp.arange(t)[:, None] + jnp.arange(k)[None, :]
        valid = idx < t
        g = v[:, jnp.clip(idx, 0, t - 1)]               # [b, t, k, d]
        g = jnp.where(valid[None, :, :, None], g, 0.0)
        out = jnp.einsum("btkd,kd->btd", g, w)
        if act == "relu":
            out = jax.nn.relu(out)
        return out

    return apply(fn, input, weight)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference common.py nce):
    logistic discrimination of the true class against sampled noise
    classes. Negatives are drawn host-side per call (uniform or
    custom_dist); the compute is two gathers + a BCE — static shapes."""
    d = input.shape[-1]
    weight = _param(("nce_w", getattr(param_attr, "name", None),
                     num_total_classes, d), (num_total_classes, d),
                    param_attr)
    bias = _param(("nce_b", getattr(bias_attr, "name", None),
                   num_total_classes), (num_total_classes,), bias_attr,
                  is_bias=True) if bias_attr is not False else None

    rng = np.random.default_rng(seed or None)
    if custom_dist is not None:
        pdist = np.asarray(custom_dist, np.float64)
        pdist = pdist / pdist.sum()
        negs = rng.choice(num_total_classes, size=num_neg_samples,
                          p=pdist)
    else:
        negs = rng.integers(0, num_total_classes, size=num_neg_samples)
    negs = jnp.asarray(negs.astype(np.int64))

    def fn(v, y, w, *rest):
        b_ = rest[0] if rest else None
        yi = y.reshape(-1).astype(jnp.int32)
        w_pos = w[yi]                                    # [B, d]
        s_pos = jnp.sum(v * w_pos, -1)
        w_neg = w[negs]                                  # [K, d]
        s_neg = v @ w_neg.T                              # [B, K]
        if b_ is not None:
            s_pos = s_pos + b_[yi]
            s_neg = s_neg + b_[negs][None, :]
        # BCE-with-logits: positives label 1, sampled noise label 0
        def bce(s, t):
            return jnp.maximum(s, 0) - s * t + jnp.log1p(
                jnp.exp(-jnp.abs(s)))
        loss = bce(s_pos, 1.0) + bce(s_neg, 0.0).sum(-1)
        return loss[:, None]

    if bias is not None:
        return apply(fn, input, label, weight, bias)
    return apply(fn, input, label, weight)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32"):
    """PS-backed large-vocab embedding (reference common.py
    sparse_embedding): rows live beyond HBM in the host-RAM SparseTable
    (distributed/ps.py) and stream through jit-safe callbacks."""
    from paddle_tpu.distributed.ps import SparseTable, ps_embedding

    table = _cached(("sparse_emb", None, size[0], size[1]),
                    lambda: SparseTable(size[0], size[1]))
    return ps_embedding(input, table)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, offset=0.5, variance=None,
                   flip=True, clip=False, name=None,
                   min_max_aspect_ratios_order=False, **kw):
    """SSD detection head (reference vision/ops multi_box_head): per
    feature map, a 3x3 conv predicts box offsets + class scores for the
    prior boxes of vision.ops.prior_box; outputs concatenate across maps.
    Returns (mbox_locs, mbox_confs, boxes, variances)."""
    from paddle_tpu.nn.functional.conv import conv2d
    from paddle_tpu.vision.ops import prior_box as _prior_box

    variance = variance or [0.1, 0.1, 0.2, 0.2]
    n_in = len(inputs)
    if min_sizes is None:
        # the reference derives per-level sizes from min/max ratio
        min_ratio = 20 if min_ratio is None else min_ratio
        max_ratio = 90 if max_ratio is None else max_ratio
        img = base_size
        step = int((max_ratio - min_ratio) / max(n_in - 2, 1))
        min_sizes, max_sizes = [], []
        for r in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(img * r / 100.0)
            max_sizes.append(img * (r + step) / 100.0)
        min_sizes = [img * 0.10] + min_sizes[:n_in - 1]
        max_sizes = [img * 0.20] + max_sizes[:n_in - 1]

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) \
            else [min_sizes[i]]
        mx = None
        if max_sizes is not None and i < len(max_sizes):
            mx = max_sizes[i] if isinstance(max_sizes[i], (list, tuple)) \
                else [max_sizes[i]]
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                            (list, tuple)) \
            else [aspect_ratios[i]]
        step_i = steps[i] if steps else 0.0
        step_wh = (step_i, step_i) if not isinstance(step_i, (list, tuple)) \
            else tuple(step_i)
        boxes, variances = _prior_box(
            feat, image, min_sizes=ms, max_sizes=mx, aspect_ratios=ar,
            variance=variance, flip=flip, clip=clip, steps=step_wh,
            offset=offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        # priors per spatial location
        h, w = feat.shape[2], feat.shape[3]
        k = int(np.prod(boxes.shape[:-1]) // (h * w))
        c_in = feat.shape[1]
        wl = _param((f"mbox_loc_w_{i}", name and f"{name}_loc_{i}",
                     k * 4, c_in), (k * 4, c_in, 3, 3), None)
        wc = _param((f"mbox_conf_w_{i}", name and f"{name}_conf_{i}",
                     k * num_classes, c_in),
                    (k * num_classes, c_in, 3, 3), None)
        loc = conv2d(feat, wl, padding=1)      # [b, k*4, h, w]
        conf = conv2d(feat, wc, padding=1)     # [b, k*C, h, w]
        b = feat.shape[0]
        from paddle_tpu.tensor.manipulation import reshape, transpose
        loc = reshape(transpose(loc, [0, 2, 3, 1]), [b, -1, 4])
        conf = reshape(transpose(conf, [0, 2, 3, 1]),
                       [b, -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_all.append(reshape(boxes, [-1, 4]))
        vars_all.append(reshape(variances, [-1, 4]))
    from paddle_tpu.tensor.manipulation import concat
    return (concat(locs, axis=1), concat(confs, axis=1),
            concat(boxes_all, axis=0), concat(vars_all, axis=0))


class StaticRNN:
    """Step-wise RNN builder (reference control_flow.py StaticRNN):
    collect the step function through the with-block API, then run it
    as one lax.scan over time."""

    def __init__(self, name=None):
        self._inputs = []       # [batch, time, ...] tensors, time-major in scan
        self._memories = []     # (init Tensor)
        self._mem_next = {}
        self._outputs = []
        self._in_block = False

    def step(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._in_block = True
            yield self
            self._in_block = False

        return ctx()

    def step_input(self, x):
        self._inputs.append(x)
        marker = ("in", len(self._inputs) - 1)
        return _RNNRef(self, marker)

    def memory(self, init=None, shape=None, value=0.0, batch_ref=None):
        if init is None:
            batch = batch_ref.shape[0] if batch_ref is not None else 1
            init = Tensor(jnp.full((batch, *shape), value, jnp.float32))
        self._memories.append(init)
        return _RNNRef(self, ("mem", len(self._memories) - 1))

    def update_memory(self, mem_ref, new_ref):
        self._mem_next[mem_ref._marker[1]] = new_ref

    def step_output(self, out_ref):
        self._outputs.append(out_ref)

    def output(self, *out_refs):
        for r in out_refs:
            self.step_output(r)

    def __call__(self):
        ins = [jnp.swapaxes(t._value, 0, 1) for t in self._inputs]
        mems = tuple(m._value for m in self._memories)

        def scan_fn(carry, xs):
            env = {("in", i): xs[i] for i in range(len(ins))}
            env.update({("mem", i): carry[i]
                        for i in range(len(carry))})
            outs = [r._eval(env) for r in self._outputs]
            new_carry = tuple(
                self._mem_next[i]._eval(env) if i in self._mem_next
                else carry[i] for i in range(len(carry)))
            return new_carry, tuple(outs)

        _, ys = jax.lax.scan(scan_fn, mems, tuple(ins))
        outs = [Tensor(jnp.swapaxes(y, 0, 1)) for y in ys]
        return outs if len(outs) != 1 else outs[0]


class _RNNRef:
    """Deferred expression node inside a StaticRNN step block: records
    the op graph symbolically; evaluated per scan step."""

    def __init__(self, rnn, marker, fn=None, args=()):
        self._rnn = rnn
        self._marker = marker
        self._fn = fn
        self._args = args

    def _eval(self, env):
        if self._fn is None:
            return env[self._marker]
        return self._fn(*[a._eval(env) if isinstance(a, _RNNRef)
                          else (a._value if isinstance(a, Tensor) else a)
                          for a in self._args])

    def _lift(self, fn, *args):
        return _RNNRef(self._rnn, ("expr", id(self)), fn,
                       (self, *args))

    def __add__(self, other):
        return self._lift(lambda a, b: a + b, other)

    def __mul__(self, other):
        return self._lift(lambda a, b: a * b, other)

    def matmul(self, w):
        return self._lift(lambda a, b: a @ b, w)

    def tanh(self):
        return self._lift(jnp.tanh)
