"""paddle.static.sparsity parity namespace (reference:
python/paddle/static/sparsity/__init__.py) — static-graph surface over
the ASP n:m sparsity tooling in paddle_tpu.incubate.asp."""
from paddle_tpu.incubate.asp import (  # noqa: F401
    calculate_density,
    decorate,
    prune_model,
    reset_excluded_layers,
    set_excluded_layers,
)


def add_supported_layer(layer, pruning_func=None):
    from paddle_tpu.incubate import asp
    return asp.add_supported_layer(layer, pruning_func)
