"""paddle.linalg namespace. Reference: python/paddle/linalg.py."""
from paddle_tpu.tensor.linalg import (  # noqa: F401
    cholesky,
    cond,
    cholesky_solve,
    corrcoef,
    cov,
    det,
    eig,
    eigh,
    eigvals,
    eigvalsh,
    householder_product,
    inv,
    lstsq,
    lu,
    lu_unpack,
    matmul,
    matrix_exp,
    matrix_norm,
    matrix_power,
    matrix_rank,
    multi_dot,
    norm,
    pca_lowrank,
    pinv,
    qr,
    slogdet,
    solve,
    svd,
    svdvals,
    triangular_solve,
    vector_norm,
)

# tensor-namespace linear algebra also exposed here (reference parity:
# python/paddle/linalg.py re-exports these from paddle.tensor.linalg)
from paddle_tpu.tensor.linalg import (  # noqa: F401
    bmm,
    cross,
    dist,
    dot,
    mv,
    t,
    transpose,
)
from paddle_tpu.tensor.stat import histogram  # noqa: F401

