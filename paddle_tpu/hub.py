"""paddle.hub parity (reference: python/paddle/hub.py re-exporting
hapi/hub.py)."""
from paddle_tpu.hapi.hub import help, list, load  # noqa: F401

__all__ = ["list", "help", "load"]
