"""Shape/layout manipulation ops. Reference: python/paddle/tensor/manipulation.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply, unwrap
from paddle_tpu.core.tensor import Tensor


def _ints(seq):
    if isinstance(seq, Tensor):
        return tuple(int(v) for v in np.asarray(seq._value).reshape(-1))
    if isinstance(seq, (int, np.integer)):
        return (int(seq),)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in seq)


def reshape(x, shape, name=None):
    shape = _ints(shape)
    return apply(lambda v: jnp.reshape(v, shape), x)


def reshape_(x, shape, name=None):
    return x._inplace_assign(reshape(x, shape))


def transpose(x, perm, name=None):
    perm = _ints(perm)
    return apply(lambda v: jnp.transpose(v, perm), x)


def moveaxis(x, source, destination, name=None):
    return apply(lambda v: jnp.moveaxis(v, _ints(source), _ints(destination)), x)


def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda v: jnp.swapaxes(v, axis0, axis1), x)


transpose_last_2 = None


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis._value)
    return apply(lambda *vs: jnp.concatenate(vs, axis=axis), *x)


def stack(x, axis=0, name=None):
    return apply(lambda *vs: jnp.stack(vs, axis=axis), *x)


def unstack(x, axis=0, num=None, name=None):
    n = num or x.shape[axis]
    out = apply(lambda v: tuple(jnp.moveaxis(v, axis, 0)[i] for i in range(n)), x)
    return list(out)


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis._value)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: dimension {dim} along axis {axis} is not divisible "
                f"by num {num_or_sections}")
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = list(_ints(num_or_sections))
        if -1 in sections:
            rest = dim - sum(s for s in sections if s != -1)
            sections = [rest if s == -1 else s for s in sections]
    offsets = np.cumsum([0] + sections)

    def fn(v):
        return tuple(
            jax.lax.slice_in_dim(v, int(offsets[i]), int(offsets[i + 1]), axis=axis)
            for i in range(len(sections))
        )
    return list(apply(fn, x))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tensor_split(x, num_or_indices, axis=0, name=None):
    if isinstance(num_or_indices, int):
        out = apply(lambda v: tuple(jnp.array_split(v, num_or_indices, axis=axis)), x)
    else:
        out = apply(lambda v: tuple(jnp.split(v, list(_ints(num_or_indices)), axis=axis)), x)
    return list(out)


def squeeze(x, axis=None, name=None):
    def fn(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = _ints(axis) if not isinstance(axis, int) else (axis,)
        axes = tuple(a % v.ndim for a in axes)
        axes = tuple(a for a in axes if v.shape[a] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v
    return apply(fn, x)


def squeeze_(x, axis=None, name=None):
    return x._inplace_assign(squeeze(x, axis))


def unsqueeze(x, axis, name=None):
    axes = _ints(axis) if not isinstance(axis, int) else (axis,)
    return apply(lambda v: jnp.expand_dims(v, axes), x)


def unsqueeze_(x, axis, name=None):
    return x._inplace_assign(unsqueeze(x, axis))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def fn(v):
        nd = v.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = v.shape[:s] + (-1,) + v.shape[e + 1:]
        return jnp.reshape(v, new_shape)
    return apply(fn, x)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return x._inplace_assign(flatten(x, start_axis, stop_axis))


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis._value)
    return apply(lambda v, i: jnp.take(v, i.reshape(-1) if i.ndim > 1 else i, axis=axis), x, index)


def gather_nd(x, index, name=None):
    def fn(v, idx):
        return v[tuple(jnp.moveaxis(idx, -1, 0))]
    return apply(fn, x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            return v.at[i].set(u)
        z = v.at[i].set(jnp.zeros_like(u) if u.ndim else 0)
        return z.at[i].add(u)
    return apply(fn, x, index, updates)


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._inplace_assign(scatter(x, index, updates, overwrite))


def scatter_nd(index, updates, shape, name=None):
    shape = _ints(shape)
    def fn(i, u):
        z = jnp.zeros(shape, u.dtype)
        return z.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return apply(fn, index, updates)


def scatter_nd_add(x, index, updates, name=None):
    def fn(v, i, u):
        return v.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return apply(fn, x, index, updates)


def slice(input, axes, starts, ends, name=None):
    axes = _ints(axes)
    starts = _ints(starts)
    ends = _ints(ends)
    def fn(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e in zip(axes, starts, ends):
            idx[a] = builtins_slice(s, min(e, v.shape[a]) if e > 0 else e)
        return v[tuple(idx)]
    return apply(fn, input)


builtins_slice = __import__("builtins").slice


def strided_slice(x, axes, starts, ends, strides, name=None):
    axes = _ints(axes)
    starts = _ints(starts)
    ends = _ints(ends)
    strides = _ints(strides)
    def fn(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = builtins_slice(s, e, st)
        return v[tuple(idx)]
    return apply(fn, x)


def crop(x, shape=None, offsets=None, name=None):
    shape = _ints(shape)
    offsets = _ints(offsets) if offsets is not None else (0,) * len(shape)
    def fn(v):
        idx = tuple(
            builtins_slice(o, o + (s if s != -1 else v.shape[i] - o))
            for i, (o, s) in enumerate(zip(offsets, shape))
        )
        return v[idx]
    return apply(fn, x)


def tile(x, repeat_times, name=None):
    return apply(lambda v: jnp.tile(v, _ints(repeat_times)), x)


def expand(x, shape, name=None):
    shape = _ints(shape)
    def fn(v):
        tgt = tuple(v.shape[i - (len(shape) - v.ndim)] if s == -1 else s
                    for i, s in enumerate(shape))
        return jnp.broadcast_to(v, tgt)
    return apply(fn, x)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def expand_as(x, y, name=None):
    return apply(lambda v, w: jnp.broadcast_to(v, w.shape), x, y)


def broadcast_tensors(inputs, name=None):
    out = apply(lambda *vs: tuple(jnp.broadcast_arrays(*vs)), *inputs)
    return list(out)


def flip(x, axis, name=None):
    axes = _ints(axis) if not isinstance(axis, int) else (axis,)
    return apply(lambda v: jnp.flip(v, axis=axes), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), x)


def roll(x, shifts, axis=None, name=None):
    def fn(v):
        sh = _ints(shifts) if not isinstance(shifts, int) else shifts
        ax = None if axis is None else (_ints(axis) if not isinstance(axis, int) else axis)
        return jnp.roll(v, sh, axis=ax)
    return apply(fn, x)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    v = np.asarray(unwrap(x))
    res = np.unique(v, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    v = np.asarray(unwrap(x))
    if axis is None:
        v = v.reshape(-1)
        ax = 0
    else:
        ax = axis
    if v.shape[ax] == 0:
        outs = [Tensor(jnp.asarray(v))]
    else:
        sl = [np.s_[:]] * v.ndim
        sl[ax] = np.s_[1:]
        sl_prev = [np.s_[:]] * v.ndim
        sl_prev[ax] = np.s_[:-1]
        other = tuple(i for i in range(v.ndim) if i != ax)
        change = np.any(v[tuple(sl)] != v[tuple(sl_prev)], axis=other) if other else (v[tuple(sl)] != v[tuple(sl_prev)])
        keep = np.concatenate([[True], change])
        outs = [Tensor(jnp.asarray(np.compress(keep, v, axis=ax)))]
        if return_inverse:
            outs.append(Tensor(jnp.asarray(np.cumsum(keep) - 1)))
        if return_counts:
            idx = np.flatnonzero(keep)
            counts = np.diff(np.append(idx, v.shape[ax]))
            outs.append(Tensor(jnp.asarray(counts)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def masked_select(x, mask, name=None):
    return apply(lambda v, m: v[m], x, mask)


def masked_fill(x, mask, value, name=None):
    return apply(lambda v, m, val: jnp.where(m, jnp.asarray(val, v.dtype), v), x, mask, value)


def index_select(x, index, axis=0, name=None):
    return apply(lambda v, i: jnp.take(v, i.reshape(-1), axis=axis), x, index)


def index_sample(x, index, name=None):
    return apply(lambda v, i: jnp.take_along_axis(v, i, axis=1), x, index)


def index_add(x, index, axis, value, name=None):
    def fn(v, i, val):
        vm = jnp.moveaxis(v, axis, 0)
        vm = vm.at[i.reshape(-1)].add(jnp.moveaxis(val, axis, 0))
        return jnp.moveaxis(vm, 0, axis)
    return apply(fn, x, index, value)


def index_put(x, indices, value, accumulate=False, name=None):
    def fn(v, val, *idx):
        if accumulate:
            return v.at[idx].add(val)
        return v.at[idx].set(val)
    return apply(fn, x, value, *indices)


def take_along_axis(arr, indices, axis, name=None):
    def fn(v, i):
        i = jnp.broadcast_to(i, i.shape) if i.shape == v.shape else i
        return jnp.take_along_axis(v, i, axis=axis)
    return apply(fn, arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def fn(v, i, val):
        val = jnp.broadcast_to(jnp.asarray(val, v.dtype), i.shape)
        dims = [jnp.arange(s).reshape([-1 if k == d else 1 for k in range(i.ndim)])
                for d, s in enumerate(i.shape)]
        idx = tuple(i if d == axis else jnp.broadcast_to(dims[d], i.shape)
                    for d in range(i.ndim))
        if reduce == "add":
            return v.at[idx].add(val)
        if reduce in ("mul", "multiply"):
            return v.at[idx].multiply(val)
        return v.at[idx].set(val)
    return apply(fn, arr, indices, values)


def repeat_interleave(x, repeats, axis=None, name=None):
    def fn(v, r):
        if axis is None:
            v = v.reshape(-1)
            ax = 0
        else:
            ax = axis
        if isinstance(r, (int, np.integer)):
            return jnp.repeat(v, int(r), axis=ax)
        total = int(np.asarray(unwrap(repeats)).sum())
        return jnp.repeat(v, r, axis=ax, total_repeat_length=total)
    return apply(fn, x, repeats)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def fn(v):
        shard_size = (index_num + nshards - 1) // nshards
        lo = shard_id * shard_size
        in_shard = (v >= lo) & (v < lo + shard_size)
        return jnp.where(in_shard, v - lo, ignore_value)
    return apply(fn, input)


def as_complex(x, name=None):
    return apply(lambda v: jax.lax.complex(v[..., 0], v[..., 1]), x)


def as_real(x, name=None):
    return apply(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), x)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def atleast_1d(*inputs, name=None):
    outs = [apply(jnp.atleast_1d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply(jnp.atleast_2d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply(jnp.atleast_3d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def hstack(x, name=None):
    return apply(lambda *vs: jnp.hstack(vs), *x)


def vstack(x, name=None):
    return apply(lambda *vs: jnp.vstack(vs), *x)


def dstack(x, name=None):
    return apply(lambda *vs: jnp.dstack(vs), *x)


def row_stack(x, name=None):
    return vstack(x)


def column_stack(x, name=None):
    return apply(lambda *vs: jnp.column_stack(vs), *x)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def unbind(input, axis=0, name=None):
    """Split into a list of tensors along axis, removing it (reference:
    python/paddle/tensor/manipulation.py unbind)."""
    n = input.shape[axis]
    from paddle_tpu.core.dispatch import apply
    return [apply(lambda v, i=i: jnp.take(v, i, axis=axis), input)
            for i in range(n)]


def tensordot(x, y, axes=2, name=None):
    """Reference: python/paddle/tensor/manipulation.py tensordot."""
    from paddle_tpu.core.dispatch import apply
    from paddle_tpu.core.tensor import Tensor
    ax = axes
    if isinstance(ax, Tensor):
        ax = np.asarray(ax._value).tolist()
    if isinstance(ax, (list, tuple)):
        if all(isinstance(a, (int, np.integer)) for a in ax):
            # paddle semantics: a FLAT int sequence names the contracted
            # axes of BOTH operands
            flat = tuple(int(a) for a in ax)
            ax = (flat, flat)
        else:
            ax = tuple(tuple(np.asarray(
                a._value if isinstance(a, Tensor) else a).ravel().tolist())
                for a in ax)
            if len(ax) == 1:
                ax = (ax[0], ax[0])
    return apply(lambda a, b: jnp.tensordot(a, b, axes=ax), x, y)
