"""Einstein summation. Reference: python/paddle/tensor/einsum.py.

On TPU, einsum lowers straight to MXU dot_generals via XLA — far better than
the reference's plan-based CUDA implementation; we delegate to jnp.einsum.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply


def einsum(equation, *operands):
    def fn(*vs):
        from paddle_tpu.amp.auto_cast import downcast_inputs
        vs = downcast_inputs(*vs, opname="einsum")
        return jnp.einsum(equation, *vs)
    return apply(fn, *operands)
