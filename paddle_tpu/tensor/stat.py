"""Statistics ops. Reference: python/paddle/tensor/stat.py."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply, unwrap
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.tensor.math import _axis, mean  # noqa: F401 (mean re-export)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda v: jnp.std(v, axis=_axis(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda v: jnp.var(v, axis=_axis(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x)


def numel(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(unwrap(x).shape)), dtype=jnp.int64))


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def fn(v):
        if mode == "avg":
            return jnp.median(v, axis=_axis(axis), keepdims=keepdim)
        # 'min' mode: lower of the two middles + its index
        ax = -1 if axis is None else axis
        vv = v.reshape(-1) if axis is None else v
        n = vv.shape[ax]
        k = (n - 1) // 2
        sv = jnp.sort(vv, axis=ax)
        vals = jnp.take(sv, k, axis=ax)
        if keepdim and axis is not None:
            vals = jnp.expand_dims(vals, ax)
        return vals
    return apply(fn, x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    def fn(v):
        return jnp.nanmedian(v, axis=_axis(axis), keepdims=keepdim)
    return apply(fn, x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = unwrap(q) if isinstance(q, Tensor) else jnp.asarray(q)
    def fn(v):
        ax = _axis(axis)
        if isinstance(ax, tuple):
            ax = ax[0] if len(ax) == 1 else None
        return jnp.quantile(v.astype(jnp.float32), qv, axis=ax, keepdims=keepdim,
                            method=interpolation)
    return apply(fn, x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = unwrap(q) if isinstance(q, Tensor) else jnp.asarray(q)
    def fn(v):
        ax = _axis(axis)
        if isinstance(ax, tuple):
            ax = ax[0] if len(ax) == 1 else None
        return jnp.nanquantile(v.astype(jnp.float32), qv, axis=ax, keepdims=keepdim,
                               method=interpolation)
    return apply(fn, x)


def histogram(input, bins=100, min=0, max=0, name=None):
    v = np.asarray(unwrap(input))
    lo, hi = (min, max) if (min != 0 or max != 0) else (v.min(), v.max())
    hist, _ = np.histogram(v, bins=bins, range=(lo, hi))
    return Tensor(jnp.asarray(hist.astype(np.int64)))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    v = np.asarray(unwrap(x))
    w = np.asarray(unwrap(weights)) if weights is not None else None
    hist, edges = np.histogramdd(v, bins=bins, range=ranges, density=density, weights=w)
    return Tensor(jnp.asarray(hist)), [Tensor(jnp.asarray(e)) for e in edges]


def bincount(x, weights=None, minlength=0, name=None):
    def fn(v, w):
        length = builtins_max(minlength, int(np.asarray(unwrap(x)).max()) + 1 if np.asarray(unwrap(x)).size else minlength)
        return jnp.bincount(v, weights=w, length=length or 1)
    return apply(fn, x, weights)


builtins_max = __import__("builtins").max
