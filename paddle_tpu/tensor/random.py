"""Random ops. Reference: python/paddle/tensor/random.py.

paddle's global-seed RNG maps to a splitting JAX PRNG key held in
framework.state; each call consumes a fresh subkey, so eager semantics match
the reference while staying functional underneath.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply, unwrap
from paddle_tpu.core.dtype import convert_dtype, get_default_dtype
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework.state import next_key
from paddle_tpu.tensor.creation import _shape


def seed(s):
    from paddle_tpu.framework import state
    state.seed(s)


def rand(shape, dtype=None, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.uniform(next_key(), _shape(shape), dtype))


def randn(shape, dtype=None, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.normal(next_key(), _shape(shape), dtype))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        def fn(m, s):
            shp = jnp.broadcast_shapes(
                jnp.shape(m), jnp.shape(s)) if shape is None else _shape(shape)
            return m + s * jax.random.normal(next_key(), shp, get_default_dtype())
        return apply(fn, mean, std)
    shp = _shape(shape) if shape is not None else ()
    return Tensor(mean + std * jax.random.normal(next_key(), shp, get_default_dtype()))


def normal_(x, mean=0.0, std=1.0, name=None):
    x._set_value(mean + std * jax.random.normal(next_key(), tuple(unwrap(x).shape),
                                                unwrap(x).dtype))
    return x


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.uniform(next_key(), _shape(shape), dtype, min, max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    v = unwrap(x)
    x._set_value(jax.random.uniform(next_key(), tuple(v.shape), v.dtype, min, max))
    return x


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    dtype = convert_dtype(dtype)
    return Tensor(jax.random.randint(next_key(), _shape(shape), low, high, dtype))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    v = unwrap(x)
    if high is None:
        low, high = 0, low
    dtype = convert_dtype(dtype) or v.dtype
    return Tensor(jax.random.randint(next_key(), tuple(v.shape), low, high, dtype))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_key(), n).astype(convert_dtype(dtype)))


def multinomial(x, num_samples=1, replacement=False, name=None):
    def fn(v):
        logits = jnp.log(jnp.maximum(v, 1e-30))
        if replacement:
            return jax.random.categorical(
                next_key(), logits, axis=-1, shape=v.shape[:-1] + (num_samples,)
            ).astype(jnp.int64)
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(next_key(), v.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx.astype(jnp.int64)
    return apply(fn, x)


def bernoulli(x, name=None):
    def fn(v):
        return (jax.random.uniform(next_key(), v.shape) < v).astype(v.dtype)
    return apply(fn, x)


def bernoulli_(x, p=0.5, name=None):
    v = unwrap(x)
    x._set_value((jax.random.uniform(next_key(), tuple(v.shape)) < p).astype(v.dtype))
    return x


def poisson(x, name=None):
    def fn(v):
        return jax.random.poisson(next_key(), v).astype(v.dtype)
    return apply(fn, x)


def binomial(count, prob, name=None):
    def fn(n, p):
        return jax.random.binomial(next_key(), n.astype(jnp.float32), p).astype(jnp.int64)
    return apply(fn, count, prob)


def exponential_(x, lam=1.0, name=None):
    v = unwrap(x)
    x._set_value(jax.random.exponential(next_key(), tuple(v.shape), v.dtype) / lam)
    return x


def rand_like(x, dtype=None, name=None):
    v = unwrap(x)
    dtype = convert_dtype(dtype) or v.dtype
    return Tensor(jax.random.uniform(next_key(), tuple(v.shape), dtype))


def randn_like(x, dtype=None, name=None):
    v = unwrap(x)
    dtype = convert_dtype(dtype) or v.dtype
    return Tensor(jax.random.normal(next_key(), tuple(v.shape), dtype))


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(mean + std * jax.random.normal(next_key(), _shape(shape), dtype))
