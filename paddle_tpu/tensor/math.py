"""Math ops. Reference parity: python/paddle/tensor/math.py (~93 public fns).

All ops are thin pure-JAX functions routed through ``apply`` so they are
eager-differentiable and jit-traceable unchanged.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply, unwrap, wrap
from paddle_tpu.core.dtype import convert_dtype, get_default_dtype
from paddle_tpu.core.tensor import Tensor


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = np.asarray(axis._value)
        return tuple(int(v) for v in a.reshape(-1))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# ---- binary elementwise ----
def add(x, y, name=None):
    return apply(jnp.add, x, y)


def subtract(x, y, name=None):
    return apply(jnp.subtract, x, y)


def multiply(x, y, name=None):
    return apply(jnp.multiply, x, y)


def divide(x, y, name=None):
    return apply(jnp.true_divide, x, y)


def floor_divide(x, y, name=None):
    return apply(jnp.floor_divide, x, y)


def remainder(x, y, name=None):
    return apply(jnp.remainder, x, y)


mod = remainder
floor_mod = remainder


def pow(x, y, name=None):
    return apply(jnp.power, x, y)


def maximum(x, y, name=None):
    return apply(jnp.maximum, x, y)


def minimum(x, y, name=None):
    return apply(jnp.minimum, x, y)


def fmax(x, y, name=None):
    return apply(jnp.fmax, x, y)


def fmin(x, y, name=None):
    return apply(jnp.fmin, x, y)


def logaddexp(x, y, name=None):
    return apply(jnp.logaddexp, x, y)


def atan2(x, y, name=None):
    return apply(jnp.arctan2, x, y)


def heaviside(x, y, name=None):
    return apply(jnp.heaviside, x, y)


def gcd(x, y, name=None):
    return apply(jnp.gcd, x, y)


def lcm(x, y, name=None):
    return apply(jnp.lcm, x, y)


def inner(x, y, name=None):
    return apply(lambda a, b: jnp.tensordot(a, b, axes=(-1, -1)) if a.ndim and b.ndim else a * b, x, y)


def outer(x, y, name=None):
    return apply(lambda a, b: jnp.outer(a, b), x, y)


def kron(x, y, name=None):
    return apply(jnp.kron, x, y)


def lerp(x, y, weight, name=None):
    return apply(lambda a, b, w: a + w * (b - a), x, y, weight)


def nextafter(x, y, name=None):
    return apply(jnp.nextafter, x, y)


def copysign(x, y, name=None):
    return apply(jnp.copysign, x, y)


def hypot(x, y, name=None):
    return apply(lambda a, b: jnp.sqrt(a * a + b * b), x, y)


# ---- unary elementwise ----
def _unary(jfn):
    def op(x, name=None):
        return apply(jfn, x)
    op.__name__ = jfn.__name__
    return op


exp = _unary(jnp.exp)
expm1 = _unary(jnp.expm1)
sqrt = _unary(jnp.sqrt)
rsqrt = _unary(jax.lax.rsqrt)
abs = _unary(jnp.abs)
ceil = _unary(jnp.ceil)
floor = _unary(jnp.floor)
round = _unary(jnp.round)
trunc = _unary(jnp.trunc)
sign = _unary(jnp.sign)
sin = _unary(jnp.sin)
cos = _unary(jnp.cos)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
acos = _unary(jnp.arccos)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
cosh = _unary(jnp.cosh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
acosh = _unary(jnp.arccosh)
atanh = _unary(jnp.arctanh)
square = _unary(jnp.square)
reciprocal = _unary(lambda v: 1.0 / v)
erf = _unary(jax.scipy.special.erf)
erfinv = _unary(jax.scipy.special.erfinv)
digamma = _unary(jax.scipy.special.digamma)
lgamma = _unary(jax.scipy.special.gammaln)
i0 = _unary(jnp.i0)
rad2deg = _unary(jnp.rad2deg)
deg2rad = _unary(jnp.deg2rad)
angle = _unary(jnp.angle)
conj = _unary(jnp.conj)
frac = _unary(lambda v: v - jnp.trunc(v))
sgn = _unary(jnp.sign)
neg = _unary(jnp.negative)


def log(x, name=None):
    return apply(jnp.log, x)


def log2(x, name=None):
    return apply(jnp.log2, x)


def log10(x, name=None):
    return apply(jnp.log10, x)


def log1p(x, name=None):
    return apply(jnp.log1p, x)


def logit(x, eps=None, name=None):
    def fn(v):
        if eps is not None:
            v = jnp.clip(v, eps, 1.0 - eps)
        return jnp.log(v / (1.0 - v))
    return apply(fn, x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda v: scale_b * jnp.tanh(scale_a * v), x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def fn(v, s):
        return v * s + bias if bias_after_scale else (v + bias) * s
    return apply(fn, x, scale)


def clip(x, min=None, max=None, name=None):
    return apply(lambda v, lo, hi: jnp.clip(v, lo, hi), x, min, max)


def increment(x, value=1.0, name=None):
    x._set_value(x._value + value)
    return x


# ---- reductions ----
def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    dt = convert_dtype(dtype)
    return apply(lambda v: jnp.sum(v, axis=_axis(axis), dtype=dt, keepdims=keepdim), x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    dt = convert_dtype(dtype)
    return apply(lambda v: jnp.nansum(v, axis=_axis(axis), dtype=dt, keepdims=keepdim), x)


def mean(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.mean(v, axis=_axis(axis), keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.nanmean(v, axis=_axis(axis), keepdims=keepdim), x)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    dt = convert_dtype(dtype)
    return apply(lambda v: jnp.prod(v, axis=_axis(axis), dtype=dt, keepdims=keepdim), x)


def max(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.max(v, axis=_axis(axis), keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.min(v, axis=_axis(axis), keepdims=keepdim), x)


amax = max
amin = min


def logsumexp(x, axis=None, keepdim=False, name=None):
    def fn(v):
        from paddle_tpu.amp.auto_cast import downcast_inputs
        (v,) = downcast_inputs(v, opname="logsumexp")
        return jax.scipy.special.logsumexp(v, axis=_axis(axis),
                                           keepdims=keepdim)
    return apply(fn, x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.count_nonzero(v, axis=_axis(axis), keepdims=keepdim).astype(jnp.int64), x)


def all(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.all(v, axis=_axis(axis), keepdims=keepdim), x)


def any(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.any(v, axis=_axis(axis), keepdims=keepdim), x)


# ---- scans ----
def cumsum(x, axis=None, dtype=None, name=None):
    dt = convert_dtype(dtype)
    def fn(v):
        if axis is None:
            return jnp.cumsum(v.reshape(-1), dtype=dt)
        return jnp.cumsum(v, axis=int(axis), dtype=dt)
    return apply(fn, x)


def cumprod(x, dim=None, dtype=None, name=None):
    dt = convert_dtype(dtype)
    def fn(v):
        if dim is None:
            return jnp.cumprod(v.reshape(-1), dtype=dt)
        return jnp.cumprod(v, axis=int(dim), dtype=dt)
    return apply(fn, x)


def cummax(x, axis=None, dtype="int64", name=None):
    def fn(v):
        a = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        vals = jax.lax.associative_scan(jnp.maximum, vv, axis=a)
        n = vv.shape[a]
        ar = jnp.arange(n).reshape([-1 if i == a else 1 for i in range(vv.ndim)])
        first = jnp.where(vv == vals, ar, -1)
        inds = jax.lax.associative_scan(jnp.maximum, first, axis=a)
        return vals, inds.astype(convert_dtype(dtype))
    return apply(fn, x)


def cummin(x, axis=None, dtype="int64", name=None):
    def fn(v):
        a = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        vals = jax.lax.associative_scan(jnp.minimum, vv, axis=a)
        n = vv.shape[a]
        ar = jnp.arange(n).reshape([-1 if i == a else 1 for i in range(vv.ndim)])
        first = jnp.where(vv == vals, ar, -1)
        inds = jax.lax.associative_scan(jnp.maximum, first, axis=a)
        return vals, inds.astype(convert_dtype(dtype))
    return apply(fn, x)


def logcumsumexp(x, axis=None, name=None):
    def fn(v):
        a = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        return jax.lax.associative_scan(jnp.logaddexp, vv, axis=a)
    return apply(fn, x)


# ---- composite ----
def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    return apply(lambda *vs: jnp.sum(jnp.stack(vs), axis=0) if len(vs) > 1 else vs[0], *inputs)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    def fn(i, a, b):
        from paddle_tpu.amp.auto_cast import downcast_inputs
        a, b = downcast_inputs(a, b, opname="addmm")
        # normal promotion semantics: a bf16 product + fp32 input -> fp32
        return beta * i + alpha * (a @ b)
    return apply(fn, input, x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        from paddle_tpu.amp.auto_cast import downcast_inputs
        from paddle_tpu.nn.functional.common import (_is_master_downcast,
                                                     _mm_master)
        a2, b2 = downcast_inputs(a, b, opname="matmul")
        if _is_master_downcast(a2, b2, b) and not transpose_x:
            # master-weight case (e.g. the tied lm head): the weight
            # grad accumulates WIDE and lands f32 directly — numlint
            # NL101 (see F.linear's custom_vjp block)
            return _mm_master(bool(transpose_y), a2, b)
        if transpose_x:
            a2 = jnp.swapaxes(a2, -1, -2) if a2.ndim > 1 else a2
        if transpose_y:
            b2 = jnp.swapaxes(b2, -1, -2) if b2.ndim > 1 else b2
        return jnp.matmul(a2, b2)
    return apply(fn, x, y)


def mm(input, mat2, name=None):
    def fn(a, b):
        from paddle_tpu.amp.auto_cast import downcast_inputs
        from paddle_tpu.nn.functional.common import (_is_master_downcast,
                                                     _mm_master)
        a2, b2 = downcast_inputs(a, b, opname="mm")
        if _is_master_downcast(a2, b2, b):
            return _mm_master(False, a2, b)
        return jnp.matmul(a2, b2)
    return apply(fn, input, mat2)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    def fn(v, pre, app):
        return jnp.diff(v, n=n, axis=axis, prepend=pre, append=app)
    return apply(fn, x, prepend, append)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def fn(yv, xv):
        if xv is None:
            return jax.scipy.integrate.trapezoid(yv, dx=(1.0 if dx is None else dx), axis=axis)
        return jax.scipy.integrate.trapezoid(yv, x=xv, axis=axis)
    return apply(fn, y, x)


cumulative_trapezoid = None  # set below


def _cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def fn(yv, xv):
        d = 1.0 if dx is None else dx
        sl1 = [slice(None)] * yv.ndim
        sl2 = [slice(None)] * yv.ndim
        sl1[axis] = slice(1, None)
        sl2[axis] = slice(None, -1)
        if xv is not None:
            d = jnp.diff(xv, axis=axis) if xv.ndim > 1 else jnp.diff(xv)
            if xv.ndim == 1:
                shape = [1] * yv.ndim
                shape[axis] = -1
                d = d.reshape(shape)
        avg = (yv[tuple(sl1)] + yv[tuple(sl2)]) / 2.0
        return jnp.cumsum(avg * d, axis=axis)
    return apply(fn, y, x)


cumulative_trapezoid = _cumulative_trapezoid


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def isfinite(x, name=None):
    return apply(jnp.isfinite, x)


def isinf(x, name=None):
    return apply(jnp.isinf, x)


def isnan(x, name=None):
    return apply(jnp.isnan, x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf), x)


def take(x, index, mode="raise", name=None):
    def fn(v, i):
        i = i.reshape(-1)
        flat = v.reshape(-1)
        if mode == "wrap":
            i = i % flat.shape[0]
        elif mode == "clip":
            i = jnp.clip(i, 0, flat.shape[0] - 1)
        else:
            i = jnp.where(i < 0, i + flat.shape[0], i)
        out = flat[i]
        iv = index._value if isinstance(index, Tensor) else jnp.asarray(index)
        return out.reshape(iv.shape)
    return apply(fn, x, index)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2), x)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2), x)


def multiplex(inputs, index, name=None):
    def fn(idx, *vs):
        stacked = jnp.stack(vs)  # [n, batch, ...]
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0
        )[0]
    return apply(fn, index, *inputs)


def renorm(x, p, axis, max_norm, name=None):
    def fn(v):
        dims = tuple(i for i in range(v.ndim) if i != axis)
        norms = jnp.sum(jnp.abs(v) ** p, axis=dims, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return v * factor
    return apply(fn, x)


# in-place variants (paddle `op_` convention): rebind value on the same Tensor
def _make_inplace(op):
    def inplace(x, *a, **kw):
        out = op(x, *a, **kw)
        return x._inplace_assign(out)
    inplace.__name__ = op.__name__ + "_"
    return inplace


def fill_(x, value, name=None):
    """In-place fill with a scalar (reference varbase patch fill_)."""
    from paddle_tpu.tensor.creation import full_like
    return x._inplace_assign(full_like(x, value))


def zero_(x, name=None):
    """In-place zero fill (reference varbase patch zero_)."""
    return fill_(x, 0.0)


add_ = _make_inplace(add)
subtract_ = _make_inplace(subtract)
multiply_ = _make_inplace(multiply)
clip_ = _make_inplace(clip)
scale_ = _make_inplace(scale)
ceil_ = _make_inplace(ceil)
floor_ = _make_inplace(floor)
exp_ = _make_inplace(exp)
sqrt_ = _make_inplace(sqrt)
rsqrt_ = _make_inplace(rsqrt)
reciprocal_ = _make_inplace(reciprocal)
round_ = _make_inplace(round)
tanh_ = _make_inplace(tanh)
