"""Linear algebra ops. Reference: python/paddle/tensor/linalg.py.

Matmul-family ops hit the MXU via XLA dot_general; decompositions use
jnp.linalg (QR/SVD/eigh lower to XLA custom calls or CPU fallback).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply, unwrap
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.tensor.math import matmul, mm  # noqa: F401 re-export


def dot(x, y, name=None):
    # NOT autocast-white-listed: this lowers to an elementwise sum, which
    # would accumulate in bf16 (unlike matmul's fp32 MXU accumulator)
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def bmm(x, y, name=None):
    def fn(a, b):
        from paddle_tpu.amp.auto_cast import downcast_inputs
        a, b = downcast_inputs(a, b, opname="bmm")
        return jnp.matmul(a, b)
    return apply(fn, x, y)


def mv(x, vec, name=None):
    def fn(a, b):
        from paddle_tpu.amp.auto_cast import downcast_inputs
        a, b = downcast_inputs(a, b, opname="mv")
        return jnp.matmul(a, b)
    return apply(fn, x, vec)


def t(input, name=None):
    def fn(v):
        if v.ndim < 2:
            return v
        return jnp.swapaxes(v, -1, -2) if v.ndim == 2 else jnp.transpose(v)
    return apply(fn, input)


def transpose(x, perm, name=None):
    from paddle_tpu.tensor.manipulation import transpose as tr
    return tr(x, perm)


def cross(x, y, axis=9, name=None):
    def fn(a, b):
        ax = axis
        if ax == 9:
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply(fn, x, y)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def fn(v):
        if axis is None:
            vv = v.reshape(-1)
            if p is None or p == "fro" or p == 2:
                out = jnp.sqrt(jnp.sum(jnp.square(vv)))
            elif p == np.inf or p == "inf":
                out = jnp.max(jnp.abs(vv))
            elif p == -np.inf:
                out = jnp.min(jnp.abs(vv))
            elif p == 0:
                out = jnp.sum((vv != 0).astype(v.dtype))
            elif p == 1:
                out = jnp.sum(jnp.abs(vv))
            else:
                out = jnp.sum(jnp.abs(vv) ** p) ** (1.0 / p)
            return out.reshape((1,) * v.ndim) if keepdim else out
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
        pp = 2 if p is None or p == "fro" else p
        if len(ax) == 1:
            a = ax[0]
            if pp == np.inf:
                return jnp.max(jnp.abs(v), axis=a, keepdims=keepdim)
            if pp == -np.inf:
                return jnp.min(jnp.abs(v), axis=a, keepdims=keepdim)
            if pp == 0:
                return jnp.sum((v != 0).astype(v.dtype), axis=a, keepdims=keepdim)
            return jnp.sum(jnp.abs(v) ** pp, axis=a, keepdims=keepdim) ** (1.0 / pp)
        # matrix norm over two axes
        if pp in ("fro", 2, None):
            return jnp.sqrt(jnp.sum(jnp.square(v), axis=ax, keepdims=keepdim))
        if pp == np.inf:
            return jnp.max(jnp.sum(jnp.abs(v), axis=ax[1], keepdims=True), axis=ax[0],
                           keepdims=True) if keepdim else jnp.max(
                jnp.sum(jnp.abs(v), axis=ax[1]), axis=ax[0] if ax[0] < ax[1] else ax[0] - 1)
        if pp == 1:
            return jnp.max(jnp.sum(jnp.abs(v), axis=ax[0], keepdims=True), axis=ax[1],
                           keepdims=True) if keepdim else jnp.max(
                jnp.sum(jnp.abs(v), axis=ax[0]), axis=ax[1] - 1 if ax[0] < ax[1] else ax[1])
        return jnp.sum(jnp.abs(v) ** pp, axis=ax, keepdims=keepdim) ** (1.0 / pp)
    return apply(fn, x)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return norm(x, p=p, axis=list(axis), keepdim=keepdim)


def dist(x, y, p=2, name=None):
    def fn(a, b):
        d = (a - b).reshape(-1)
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype))
        if p == np.inf:
            return jnp.max(jnp.abs(d))
        if p == -np.inf:
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
    return apply(fn, x, y)


def cholesky(x, upper=False, name=None):
    def fn(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2) if upper else L
    return apply(fn, x)


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)
    return apply(fn, x, y)


def det(x, name=None):
    return apply(jnp.linalg.det, x)


def slogdet(x, name=None):
    def fn(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])
    return apply(fn, x)


def svd(x, full_matrices=False, name=None):
    def fn(v):
        u, s, vh = jnp.linalg.svd(v, full_matrices=full_matrices)
        return u, s, jnp.swapaxes(vh, -1, -2).conj()
    return apply(fn, x)


def svdvals(x, name=None):
    return apply(lambda v: jnp.linalg.svd(v, compute_uv=False), x)


def qr(x, mode="reduced", name=None):
    def fn(v):
        return tuple(jnp.linalg.qr(v, mode=mode)) if mode != "r" else (jnp.linalg.qr(v, mode="r"),)
    out = apply(fn, x)
    return out if isinstance(out, tuple) and len(out) > 1 else out[0]


def lu(x, pivot=True, get_infos=False, name=None):
    def fn(v):
        lu_mat, piv = jax.scipy.linalg.lu_factor(v)
        return lu_mat, (piv + 1).astype(jnp.int32)
    lu_mat, piv = apply(fn, x)
    if get_infos:
        info = Tensor(jnp.zeros(unwrap(x).shape[:-2] or (1,), jnp.int32))
        return lu_mat, piv, info
    return lu_mat, piv


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True, name=None):
    def fn(lu_mat, piv):
        m, n = lu_mat.shape[-2:]
        k = min(m, n)
        L = jnp.tril(lu_mat[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_mat.dtype)
        U = jnp.triu(lu_mat[..., :k, :])
        p = jnp.arange(m)
        def body(i, p):
            j = piv[i] - 1
            pi, pj = p[i], p[j]
            return p.at[i].set(pj).at[j].set(pi)
        p = jax.lax.fori_loop(0, piv.shape[-1], body, p)
        P = jnp.eye(m, dtype=lu_mat.dtype)[p].T
        return P, L, U
    return apply(fn, lu_data, lu_pivots)


def eig(x, name=None):
    v = np.asarray(unwrap(x))
    w, vec = np.linalg.eig(v)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(vec))


def eigh(x, UPLO="L", name=None):
    def fn(v):
        return tuple(jnp.linalg.eigh(v, symmetrize_input=True))
    return apply(fn, x)


def eigvals(x, name=None):
    v = np.asarray(unwrap(x))
    return Tensor(jnp.asarray(np.linalg.eigvals(v)))


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda v: jnp.linalg.eigvalsh(v), x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), x)


def inv(x, name=None):
    return apply(jnp.linalg.inv, x)


def solve(x, y, name=None):
    def fn(a, b):
        if b.ndim == a.ndim - 1:
            return jnp.linalg.solve(a, b[..., None])[..., 0]
        return jnp.linalg.solve(a, b)
    return apply(fn, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply(fn, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def fn(a, b):
        sol, res, rank_, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank_.astype(jnp.int32), sv
    return apply(fn, x, y)


def matrix_power(x, n, name=None):
    return apply(lambda v: jnp.linalg.matrix_power(v, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply(lambda v: jnp.linalg.matrix_rank(v, rtol=tol).astype(jnp.int64), x)


def multi_dot(x, name=None):
    return apply(lambda *vs: jnp.linalg.multi_dot(vs), *x)


def matrix_exp(x, name=None):
    return apply(jax.scipy.linalg.expm, x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    def fn(v, fw, aw):
        return jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fw, aweights=aw)
    return apply(fn, x, fweights, aweights)


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda v: jnp.corrcoef(v, rowvar=rowvar), x)


def householder_product(x, tau, name=None):
    def fn(a, t):
        m, n = a.shape[-2:]
        def make_h(carry, i):
            q = carry
            v = jnp.where(jnp.arange(m) < i, 0.0, a[..., :, i].at[..., i].set(1.0))
            v = a[..., :, i] * (jnp.arange(m) > i) + (jnp.arange(m) == i)
            h = jnp.eye(m, dtype=a.dtype) - t[..., i] * jnp.outer(v, v)
            return q @ h, None
        q0 = jnp.eye(m, dtype=a.dtype)
        q, _ = jax.lax.scan(make_h, q0, jnp.arange(t.shape[-1]))
        return q[..., :, :n]
    return apply(fn, x, tau)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def fn(v):
        qq = q or min(6, *v.shape[-2:])
        vv = v - jnp.mean(v, axis=-2, keepdims=True) if center else v
        u, s, vh = jnp.linalg.svd(vv, full_matrices=False)
        return u[..., :qq], s[..., :qq], jnp.swapaxes(vh, -1, -2)[..., :qq]
    return apply(fn, x)


def cond(x, p=None, name=None):
    """Condition number w.r.t. norm `p` (reference tensor/linalg.py:741):
    p in {None, 2, -2} uses singular values; fro/nuc/1/-1/inf/-inf use
    norm(x) * norm(inv(x))."""
    def fn(v):
        if p is None or p in (2, -2):
            s = jnp.linalg.svd(v, compute_uv=False)
            big = s[..., 0]
            small = s[..., -1]
            return big / small if (p is None or p == 2) else small / big
        inv = jnp.linalg.inv(v)

        def mat_norm(m):
            if p == "fro":
                return jnp.sqrt((m * m).sum((-2, -1)))
            if p == "nuc":
                return jnp.linalg.svd(m, compute_uv=False).sum(-1)
            if p in (1, -1):
                colsums = jnp.abs(m).sum(-2)
                return colsums.max(-1) if p == 1 else colsums.min(-1)
            if p in (float("inf"), -float("inf")):
                rowsums = jnp.abs(m).sum(-1)
                return rowsums.max(-1) if p == float("inf") \
                    else rowsums.min(-1)
            raise ValueError(f"unsupported norm order {p!r}")

        return mat_norm(v) * mat_norm(inv)

    return apply(fn, x)
