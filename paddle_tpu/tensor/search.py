"""Search / sort ops. Reference: python/paddle/tensor/search.py."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply, unwrap
from paddle_tpu.core.dtype import convert_dtype
from paddle_tpu.core.tensor import Tensor


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = convert_dtype(dtype)
    def fn(v):
        if axis is None:
            out = jnp.argmax(v.reshape(-1))
            return out.reshape((1,) * v.ndim).astype(dt) if keepdim else out.astype(dt)
        return jnp.argmax(v, axis=axis, keepdims=keepdim).astype(dt)
    return apply(fn, x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = convert_dtype(dtype)
    def fn(v):
        if axis is None:
            out = jnp.argmin(v.reshape(-1))
            return out.reshape((1,) * v.ndim).astype(dt) if keepdim else out.astype(dt)
        return jnp.argmin(v, axis=axis, keepdims=keepdim).astype(dt)
    return apply(fn, x)


def argsort(x, axis=-1, descending=False, stable=True, name=None):
    def fn(v):
        idx = jnp.argsort(v, axis=axis, stable=stable, descending=descending)
        return idx.astype(jnp.int64)
    return apply(fn, x)


def sort(x, axis=-1, descending=False, stable=True, name=None):
    def fn(v):
        out = jnp.sort(v, axis=axis, stable=stable, descending=descending)
        return out
    return apply(fn, x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    import jax.lax
    if isinstance(k, Tensor):
        k = int(k._value)
    def fn(v):
        ax = v.ndim - 1 if axis is None else axis % v.ndim
        vm = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vm, k)
        else:
            vals, idx = jax.lax.top_k(-vm, k)
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax)
    return apply(fn, x)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return apply(lambda c, a, b: jnp.where(c, a, b), condition, x, y)


def where_(condition, x, y, name=None):
    out = where(condition, x, y)
    return x._inplace_assign(out)


def nonzero(x, as_tuple=False):
    v = np.asarray(unwrap(x))
    nz = np.nonzero(v)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.astype(np.int64))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def masked_select(x, mask, name=None):
    from paddle_tpu.tensor.manipulation import masked_select as ms
    return ms(x, mask)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(v):
        ax = axis % v.ndim
        sv = jnp.sort(v, axis=ax)
        si = jnp.argsort(v, axis=ax)
        vals = jnp.take(sv, k - 1, axis=ax)
        idx = jnp.take(si, k - 1, axis=ax).astype(jnp.int64)
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            idx = jnp.expand_dims(idx, ax)
        return vals, idx
    return apply(fn, x)


def mode(x, axis=-1, keepdim=False, name=None):
    v = np.asarray(unwrap(x))
    ax = axis % v.ndim
    vm = np.moveaxis(v, ax, -1)
    flat = vm.reshape(-1, vm.shape[-1])
    vals = np.empty(flat.shape[0], dtype=v.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        # paddle returns the largest value among ties; np.unique sorts ascending
        best = uniq[counts == counts.max()][-1]
        vals[i] = best
        idxs[i] = np.where(row == best)[0][-1]
    out_shape = vm.shape[:-1]
    vals = vals.reshape(out_shape)
    idxs = idxs.reshape(out_shape)
    if keepdim:
        vals = np.expand_dims(vals, ax)
        idxs = np.expand_dims(idxs, ax)
    return Tensor(jnp.asarray(vals)), Tensor(jnp.asarray(idxs))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def fn(s, v):
        side = "right" if right else "left"
        if s.ndim == 1:
            out = jnp.searchsorted(s, v, side=side)
        else:
            import jax as _jax
            out = _jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side=side))(
                s.reshape(-1, s.shape[-1]), v.reshape(-1, v.shape[-1])
            ).reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply(fn, sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    def fn(v, s):
        side = "right" if right else "left"
        out = jnp.searchsorted(s, v, side=side)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply(fn, x, sorted_sequence)


def index_select(x, index, axis=0, name=None):
    from paddle_tpu.tensor.manipulation import index_select as isel
    return isel(x, index, axis)
