"""Tensor creation ops. Reference parity: python/paddle/tensor/creation.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply, unwrap
from paddle_tpu.core.dtype import convert_dtype, get_default_dtype
from paddle_tpu.core.tensor import Parameter, Tensor
from paddle_tpu.core.device import _default_place


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in shape)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if isinstance(data, Tensor):
        v = data._value
    else:
        if isinstance(data, (list, tuple)):
            data = np.asarray(data)
        v = jnp.asarray(data)
    if dtype is not None:
        v = v.astype(convert_dtype(dtype))
    elif not isinstance(data, Tensor) and v.dtype == jnp.float64:
        v = v.astype(get_default_dtype())
    if place is not None:
        v = jax.device_put(v, place.jax_device)
    return Tensor(v, stop_gradient=stop_gradient)


def zeros(shape, dtype=None, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.zeros(_shape(shape), dtype))


def ones(shape, dtype=None, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.ones(_shape(shape), dtype))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    dtype = convert_dtype(dtype)
    if dtype is None:
        dtype = (
            np.dtype("bool") if isinstance(fill_value, bool)
            else np.dtype("int64") if isinstance(fill_value, int)
            else get_default_dtype()
        )
    return Tensor(jnp.full(_shape(shape), fill_value, dtype))


def zeros_like(x, dtype=None, name=None):
    return apply(lambda v: jnp.zeros_like(v, dtype=convert_dtype(dtype)), x)


def ones_like(x, dtype=None, name=None):
    return apply(lambda v: jnp.ones_like(v, dtype=convert_dtype(dtype)), x)


def full_like(x, fill_value, dtype=None, name=None):
    return apply(lambda v: jnp.full_like(v, fill_value, dtype=convert_dtype(dtype)), x)


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = (v.item() if isinstance(v, Tensor) else v for v in (start, end, step))
    if end is None:
        start, end = 0, start
    dtype = convert_dtype(dtype)
    if dtype is None:
        dtype = (
            np.dtype("int64")
            if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
            else get_default_dtype()
        )
    return Tensor(jnp.arange(start, end, step, dtype=dtype))


def linspace(start, stop, num, dtype=None, name=None):
    start, stop, num = (v.item() if isinstance(v, Tensor) else v for v in (start, stop, num))
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.linspace(start, stop, int(num), dtype=dtype))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    start, stop, num = (v.item() if isinstance(v, Tensor) else v for v in (start, stop, num))
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=dtype))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.eye(num_rows, num_columns, dtype=dtype))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = apply(lambda *vs: tuple(jnp.meshgrid(*vs, indexing="ij")), *args)
    return list(outs)


def diag(x, offset=0, padding_value=0, name=None):
    def fn(v):
        if v.ndim == 1:
            d = jnp.diag(v, k=offset)
            if padding_value != 0:
                mask = jnp.eye(*d.shape, k=offset, dtype=bool)
                d = jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
            return d
        return jnp.diag(v, k=offset)
    return apply(fn, x)


def diagflat(x, offset=0, name=None):
    return apply(lambda v: jnp.diagflat(v, k=offset), x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def fn(v):
        n = v.shape[-1] + abs(offset)
        out = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(v)
        if (dim1, dim2) != (-2, -1):
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out
    return apply(fn, x)


def tril(x, diagonal=0, name=None):
    return apply(lambda v: jnp.tril(v, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply(lambda v: jnp.triu(v, k=diagonal), x)


def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def assign(x, output=None):
    v = unwrap(x)
    if isinstance(v, (list, tuple, int, float, bool, np.ndarray)):
        v = jnp.asarray(np.asarray(v))
    if output is None:
        return Tensor(v)
    output._set_value(v.astype(output._value.dtype))
    return output


def clone(x, name=None):
    return x.clone()


def complex(real, imag, name=None):
    return apply(jax.lax.complex, real, imag)


def polar(abs, angle, name=None):
    return apply(lambda a, t: a * jnp.exp(1j * t.astype(jnp.complex64)), abs, angle)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from paddle_tpu.nn import initializer as I
    dtype = convert_dtype(dtype) or get_default_dtype()
    init = default_initializer or (I.Constant(0.0) if is_bias else I.XavierNormal())
    p = Parameter(jnp.zeros(_shape(shape), dtype), name=name)
    init(p)
    return p


def clone_tensor(x):
    return x.clone()
