"""Tensor attribute ops. Reference: python/paddle/tensor/attribute.py."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply, unwrap
from paddle_tpu.core.tensor import Tensor


def shape(input):
    return Tensor(jnp.asarray(unwrap(input).shape, dtype=jnp.int32))


def rank(input):
    return Tensor(jnp.asarray(unwrap(input).ndim, dtype=jnp.int32))


def is_complex(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.integer)


def real(x, name=None):
    return apply(jnp.real, x)


def imag(x, name=None):
    return apply(jnp.imag, x)
