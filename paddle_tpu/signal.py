"""paddle_tpu.signal — STFT / ISTFT.

Reference: python/paddle/signal.py (stft/istft over phi frame+fft kernels).
TPU-native: frame extraction is a gather-free strided reshape under XLA
(jnp.stack of slices compiles to one windowed gather); FFT via jnp.fft.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice into overlapping frames (reference: signal.frame). axis=-1:
    [..., seq] -> [..., frame_length, num_frames]; axis=0: [seq, ...] ->
    [frame_length, num_frames, ...]."""
    if axis not in (-1, 0):
        raise ValueError("frame supports axis -1 or 0")

    def impl(v):
        if axis == 0:
            v = jnp.moveaxis(v, 0, -1)         # -> [..., seq]
        n = v.shape[-1]
        if frame_length > n:
            raise ValueError("frame_length > signal length")
        num = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        out = v[..., idx]                      # [..., num, frame_length]
        out = jnp.swapaxes(out, -1, -2)        # [..., frame_length, num]
        if axis == 0:
            out = jnp.moveaxis(out, (-2, -1), (0, 1))
        return out
    return apply(impl, x)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (reference: signal.overlap_add). axis=-1 takes
    [..., frame_length, num_frames]; axis=0 takes
    [frame_length, num_frames, ...] and returns [seq, ...]."""
    if axis not in (-1, 0):
        raise ValueError("overlap_add supports axis -1 or 0")

    def impl(v):
        if axis == 0:                          # -> [..., fl, num]
            v = jnp.moveaxis(v, (0, 1), (-2, -1))
        fl, num = v.shape[-2], v.shape[-1]
        n = fl + hop_length * (num - 1)
        out = jnp.zeros(v.shape[:-2] + (n,), v.dtype)
        idx = (jnp.arange(num) * hop_length)[:, None] + \
            jnp.arange(fl)[None, :]            # [num, fl]
        upd = jnp.swapaxes(v, -1, -2)          # [..., num, fl]
        out = out.at[..., idx].add(upd)
        if axis == 0:
            out = jnp.moveaxis(out, -1, 0)     # -> [seq, ...]
        return out
    return apply(impl, x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform, matching the reference's semantics:
    input [batch?, signal], output [batch?, freq, frames] complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def impl(v, w):
        if w is None:
            w = jnp.ones(win_length, v.dtype)
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
        if center:
            pads = [(0, 0)] * (v.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            v = jnp.pad(v, pads, mode=pad_mode)
        n = v.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = v[..., idx] * w               # [..., num, n_fft]
        spec = jnp.fft.rfft(frames, axis=-1) if onesided else \
            jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)      # [..., freq, frames]

    w = window._value if isinstance(window, Tensor) else window
    return apply(lambda v: impl(v, w), x)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT (reference: signal.istft): least-squares overlap-add with
    window-envelope normalization."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if return_complex and onesided:
        raise ValueError("return_complex requires onesided=False")

    def impl(v, w):
        if w is None:
            w = jnp.ones(win_length, jnp.float32)
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
        spec = jnp.swapaxes(v, -1, -2)         # [..., frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * w
        num = frames.shape[-2]
        n = n_fft + hop_length * (num - 1)
        idx = (jnp.arange(num) * hop_length)[:, None] + \
            jnp.arange(n_fft)[None, :]
        sig = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
        sig = sig.at[..., idx].add(frames)
        env = jnp.zeros((n,), frames.dtype).at[idx].add(
            (w * w)[None, :].repeat(num, 0))
        sig = sig / jnp.where(env > 1e-11, env, 1.0)
        if center:
            sig = sig[..., n_fft // 2: n - n_fft // 2]
        if length is not None:
            sig = sig[..., :length]
        return sig

    w = window._value if isinstance(window, Tensor) else window
    return apply(lambda v: impl(v, w), x)
