"""Stdlib-wave audio backend (reference:
python/paddle/audio/backends/wave_backend.py): WAV load/save/info on the
stdlib `wave` module — no soundfile dependency, fully offline."""
from __future__ import annotations

import wave as _wave

import numpy as np

from paddle_tpu.audio.backends.backend import AudioInfo

__all__ = ["info", "load", "save"]


def info(filepath):
    with _wave.open(str(filepath), "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8, "PCM_S")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """-> (Tensor [channels, time] (or [time, channels]), sample_rate)."""
    from paddle_tpu.core.tensor import Tensor
    with _wave.open(str(filepath), "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(min(frame_offset, f.getnframes()))
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(max(n, 0))
    if width == 2:
        data = np.frombuffer(raw, dtype=np.int16)
        scale = 32768.0
    elif width == 1:  # unsigned 8-bit WAV
        data = np.frombuffer(raw, dtype=np.uint8).astype(np.int16) - 128
        scale = 128.0
    elif width == 4:
        data = np.frombuffer(raw, dtype=np.int32)
        scale = 2147483648.0
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    data = data.reshape(-1, nch)
    if normalize:
        data = (data.astype(np.float32) / scale)
    if channels_first:
        data = data.T
    return Tensor(np.ascontiguousarray(data)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    from paddle_tpu.core.tensor import Tensor
    arr = src.numpy() if isinstance(src, Tensor) else np.asarray(src)
    if arr.ndim == 1:
        arr = arr[None] if channels_first else arr[:, None]
    if channels_first:
        arr = arr.T  # -> [time, channels]
    if arr.dtype != np.int16:
        arr = np.clip(arr, -1.0, 1.0)
        arr = (arr * 32767.0).astype(np.int16)
    with _wave.open(str(filepath), "wb") as f:
        f.setnchannels(arr.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(arr.tobytes())
