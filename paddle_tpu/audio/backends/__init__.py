"""paddle.audio.backends (reference: python/paddle/audio/backends/):
selection API over pluggable load/save/info backends. The top-level
functions dispatch through the CURRENT backend so
`set_backend('soundfile')` (when that package exists) retargets
paddle.audio.load/save/info exactly as in the reference."""
from __future__ import annotations

from paddle_tpu.audio.backends import wave_backend  # noqa: F401
from paddle_tpu.audio.backends.backend import AudioInfo  # noqa: F401
from paddle_tpu.audio.backends.init_backend import (  # noqa: F401
    _backend_module,
    get_current_backend,
    list_available_backends,
    set_backend,
)

__all__ = ["AudioInfo", "info", "load", "save", "get_current_backend",
           "list_available_backends", "set_backend"]


def info(filepath):
    return _backend_module().info(filepath)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    return _backend_module().load(filepath, frame_offset, num_frames,
                                  normalize, channels_first)


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    return _backend_module().save(filepath, src, sample_rate,
                                  channels_first, encoding,
                                  bits_per_sample)
