"""Audio backend interface (reference:
python/paddle/audio/backends/backend.py). A backend is any module with
`info(filepath)`, `load(filepath, frame_offset, num_frames, normalize,
channels_first)` and `save(filepath, src, sample_rate, ...)`."""
from __future__ import annotations

__all__ = ["AudioInfo"]


class AudioInfo:
    """(reference backend.py AudioInfo)"""

    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding
