"""Soundfile audio backend (reference:
python/paddle/audio/backends/soundfile_backend.py): delegates to the
`soundfile` package when it is installed. This zero-egress image does
not bundle it, so `AVAILABLE` gates registration — the module stays
importable either way and the selection API reports availability
honestly."""
from __future__ import annotations

import numpy as np

from paddle_tpu.audio.backends.backend import AudioInfo

try:
    import soundfile as _sf
    AVAILABLE = True
except ImportError:
    _sf = None
    AVAILABLE = False

__all__ = ["AVAILABLE", "info", "load", "save"]


def _require():
    if _sf is None:
        raise ImportError(
            "the soundfile backend needs the `soundfile` package "
            "(pip install soundfile); use set_backend('wave_backend')")


def info(filepath):
    _require()
    i = _sf.info(str(filepath))
    bits = {"PCM_S8": 8, "PCM_U8": 8, "PCM_16": 16, "PCM_24": 24,
            "PCM_32": 32, "FLOAT": 32, "DOUBLE": 64}.get(i.subtype, 16)
    return AudioInfo(i.samplerate, i.frames, i.channels, bits, i.subtype)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    _require()
    from paddle_tpu.core.tensor import Tensor
    stop = None if num_frames < 0 else frame_offset + num_frames
    data, sr = _sf.read(str(filepath), start=frame_offset, stop=stop,
                        dtype="float32" if normalize else "int16",
                        always_2d=True)
    if channels_first:
        data = data.T
    return Tensor(np.ascontiguousarray(data)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    _require()
    from paddle_tpu.core.tensor import Tensor
    arr = src.numpy() if isinstance(src, Tensor) else np.asarray(src)
    if arr.ndim == 1:
        arr = arr[None] if channels_first else arr[:, None]
    if channels_first:
        arr = arr.T
    subtype = {8: "PCM_S8", 16: "PCM_16", 24: "PCM_24",
               32: "PCM_32"}.get(bits_per_sample, "PCM_16")
    _sf.write(str(filepath), arr, int(sample_rate), subtype=subtype)
