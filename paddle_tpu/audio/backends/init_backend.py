"""Backend selection (reference:
python/paddle/audio/backends/init_backend.py). A registry of backend
modules; `set_backend` swaps which module serves
paddle.audio.{load,save,info}. The stdlib wave backend is always
available; the soundfile backend registers when the package imports."""
from __future__ import annotations

from paddle_tpu.audio.backends import soundfile_backend, wave_backend

__all__ = ["get_current_backend", "list_available_backends", "set_backend"]

_BACKENDS = {"wave_backend": wave_backend}
if soundfile_backend.AVAILABLE:
    _BACKENDS["soundfile"] = soundfile_backend

_current = ["wave_backend"]


def list_available_backends():
    return sorted(_BACKENDS)


def get_current_backend():
    return _current[0]


def set_backend(backend_name):
    if backend_name not in _BACKENDS:
        raise NotImplementedError(
            f"unknown audio backend {backend_name!r}; available: "
            f"{list_available_backends()} (the soundfile backend "
            f"registers only when the `soundfile` package is installed)")
    _current[0] = backend_name


def _backend_module():
    return _BACKENDS[_current[0]]


def _init_set_audio_backend():
    _current[0] = "wave_backend"
