"""paddle.audio.datasets parity (reference:
python/paddle/audio/datasets/{dataset,tess,esc50}.py).

Zero-egress: instead of downloading the TESS/ESC-50 archives, each class
synthesizes deterministic waveforms whose spectral content depends on
the label (distinct fundamental + harmonics per class), so feature
extraction (raw | spectrogram | melspectrogram | mfcc | logmelspectrogram
via paddle_tpu.audio.features) and classification pipelines exercise the
same code paths and measurably learn.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.io import Dataset

__all__ = ["TESS", "ESC50", "AudioClassificationDataset"]

feat_funcs = ["raw", "spectrogram", "melspectrogram",
              "logmelspectrogram", "mfcc"]


class AudioClassificationDataset(Dataset):
    """(waveform-or-feature, label) pairs (reference dataset.py)."""

    def __init__(self, files=None, labels=None, feat_type="raw",
                 sample_rate=16000, duration=1.0, n_classes=2, seed=0,
                 n_samples=64, **feat_kwargs):
        if feat_type not in feat_funcs:
            raise ValueError(f"feat_type must be one of {feat_funcs}")
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs
        self.sample_rate = sample_rate
        self._n = int(sample_rate * duration)
        self._n_classes = n_classes
        rng = np.random.default_rng(seed)
        if files is not None:
            self.files, self.labels = files, labels
            self._synth = False
        else:
            self._synth = True
            self.labels = [int(i % n_classes) for i in range(n_samples)]
            self._phases = rng.random(n_samples)
            self._noise_seeds = rng.integers(0, 2 ** 31, n_samples)

    def _waveform(self, idx):
        label = self.labels[idx]
        t = np.arange(self._n, dtype=np.float32) / self.sample_rate
        f0 = 120.0 * (label + 1)  # class-dependent fundamental
        rng = np.random.default_rng(int(self._noise_seeds[idx]))
        w = np.zeros_like(t)
        for h, amp in ((1, 1.0), (2, 0.5), (3, 0.25)):
            w += amp * np.sin(2 * np.pi * f0 * h * t
                              + 2 * np.pi * self._phases[idx])
        w += 0.05 * rng.standard_normal(self._n).astype(np.float32)
        return (0.5 * w / np.abs(w).max()).astype(np.float32)

    def _convert_to_record(self, idx):
        import paddle_tpu
        from paddle_tpu.audio import features

        if self._synth:
            waveform = self._waveform(idx)
        else:
            from paddle_tpu.audio.backends import load
            wav, sr = load(self.files[idx])
            self.sample_rate = sr
            waveform = wav.numpy().reshape(-1)
        if self.feat_type == "raw":
            return waveform, np.int64(self.labels[idx])
        x = paddle_tpu.to_tensor(waveform[None, :])
        if self.feat_type == "spectrogram":
            feat = features.Spectrogram(**self.feat_kwargs)(x)
        elif self.feat_type == "melspectrogram":
            feat = features.MelSpectrogram(sr=self.sample_rate,
                                           **self.feat_kwargs)(x)
        elif self.feat_type == "logmelspectrogram":
            feat = features.LogMelSpectrogram(sr=self.sample_rate,
                                              **self.feat_kwargs)(x)
        else:
            feat = features.MFCC(sr=self.sample_rate, **self.feat_kwargs)(x)
        return feat.numpy()[0], np.int64(self.labels[idx])

    def __getitem__(self, idx):
        return self._convert_to_record(idx)

    def __len__(self):
        return len(self.labels)


class TESS(AudioClassificationDataset):
    """Toronto emotional speech set: 7 emotion classes
    (reference tess.py:26)."""

    n_class = 7
    label_list = ["angry", "disgust", "fear", "happy", "neutral",
                  "ps", "sad"]

    def __init__(self, mode="train", feat_type="raw", archive=None,
                 **kwargs):
        n = 70 if mode == "train" else 21
        super().__init__(feat_type=feat_type, n_classes=self.n_class,
                         seed=0 if mode == "train" else 1, n_samples=n,
                         **kwargs)

    def meta_info(self, idx):
        return {"label": self.label_list[self.labels[idx]]}


class ESC50(AudioClassificationDataset):
    """Environmental sound classification, 50 classes
    (reference esc50.py)."""

    n_class = 50

    def __init__(self, mode="train", split=1, feat_type="raw", archive=None,
                 **kwargs):
        n = 200 if mode == "train" else 50
        super().__init__(feat_type=feat_type, n_classes=self.n_class,
                         seed=2 if mode == "train" else 3, n_samples=n,
                         duration=0.5, **kwargs)
