"""Audio functional ops.

Reference parity: python/paddle/audio/functional/functional.py
(hz_to_mel :23, mel_to_hz :79, mel_frequencies :124, fft_frequencies
:164, compute_fbank_matrix :187, power_to_db :260, create_dct :304) and
functional/window.py (get_window :330).

TPU-native: all of these are small constant-factory / elementwise
computations — plain jnp, returned as Tensors so they drop into jitted
feature pipelines.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window", "WindowFunctionRegister", "window_function_register"]


def _jnp(x):
    return x._value if isinstance(x, Tensor) else x


def hz_to_mel(freq, htk=False):
    """Hz -> mel. htk=True uses the HTK formula; default is Slaney
    (linear below 1 kHz, log above)."""
    scalar = not isinstance(freq, Tensor)
    f = jnp.asarray(_jnp(freq), jnp.float32)
    if htk:
        mel = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(f / min_log_hz) / logstep,
                        mel)
    return float(mel) if scalar and mel.ndim == 0 else Tensor(mel)


def mel_to_hz(mel, htk=False):
    scalar = not isinstance(mel, Tensor)
    m = jnp.asarray(_jnp(mel), jnp.float32)
    if htk:
        f = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        f = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        f = jnp.where(m >= min_log_mel,
                      min_log_hz * jnp.exp(logstep * (m - min_log_mel)), f)
    return float(f) if scalar and f.ndim == 0 else Tensor(f)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    lo = _jnp(hz_to_mel(Tensor(jnp.asarray(f_min)), htk))
    hi = _jnp(hz_to_mel(Tensor(jnp.asarray(f_max)), htk))
    mels = jnp.linspace(lo, hi, n_mels)
    return Tensor(_jnp(mel_to_hz(Tensor(mels), htk)).astype(dtype))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(jnp.linspace(0, float(sr) / 2,
                               1 + n_fft // 2).astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filter bank [n_mels, 1 + n_fft//2]."""
    if f_max is None:
        f_max = float(sr) / 2
    fftfreqs = fft_frequencies(sr, n_fft)._value
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)._value
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]         # [n_mels+2, F]
    lower = -ramps[:-2] / fdiff[:-1][:, None]
    upper = ramps[2:] / fdiff[1:][:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    elif isinstance(norm, (int, float)):
        w_norm = jnp.sum(jnp.abs(weights) ** norm, axis=1) ** (1.0 / norm)
        weights = weights / jnp.maximum(w_norm[:, None], 1e-10)
    return Tensor(weights.astype(dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """Power spectrogram -> dB, clipped top_db below the peak."""
    s = jnp.asarray(_jnp(spect))
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
    log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return Tensor(log_spec)


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II matrix [n_mels, n_mfcc] (matches the reference orientation:
    mel features @ dct)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == "ortho":
        dct = dct * jnp.where(k == 0, 1.0 / math.sqrt(n_mels),
                              math.sqrt(2.0 / n_mels))[None, :]
    else:
        dct = dct * 2.0
    return Tensor(dct.astype(dtype))


def _extend(M, sym):
    return (M + 1, True) if not sym else (M, False)


def _truncate(w, trunc):
    return w[:-1] if trunc else w


def get_window(window, win_length, fftbins=True, dtype="float64"):
    """Window factory: hann/hamming/blackman/cosine/triang/bohman/
    gaussian/exponential/taylor/tukey/kaiser (scipy-compatible
    formulas)."""
    if isinstance(window, tuple):
        name, *args = window
    else:
        name, args = window, []
    sym = not fftbins
    M, trunc = _extend(win_length, sym)
    n = np.arange(M, dtype=np.float64)

    if name == "hann":
        w = 0.5 - 0.5 * np.cos(2 * np.pi * n / (M - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * n / (M - 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * n / (M - 1))
             + 0.08 * np.cos(4 * np.pi * n / (M - 1)))
    elif name == "cosine":
        w = np.sin(np.pi / M * (n + 0.5))
    elif name == "triang":
        k = np.arange(1, (M + 1) // 2 + 1)
        if M % 2 == 0:
            half = (2 * k - 1.0) / M
            w = np.concatenate([half, half[::-1]])
        else:
            half = 2 * k / (M + 1.0)
            w = np.concatenate([half, half[-2::-1]])
    elif name == "bohman":
        fac = np.abs(np.linspace(-1, 1, M))
        w = (1 - fac) * np.cos(np.pi * fac) + np.sin(np.pi * fac) / np.pi
        w[0] = w[-1] = 0.0
    elif name == "gaussian":
        std = args[0] if args else 7.0
        nn = n - (M - 1) / 2
        w = np.exp(-0.5 * (nn / std) ** 2)
    elif name == "exponential":
        center = args[0] if args else None
        tau = args[1] if len(args) > 1 else 1.0
        if center is None:
            center = (M - 1) / 2
        w = np.exp(-np.abs(n - center) / tau)
    elif name == "tukey":
        alpha = args[0] if args else 0.5
        if alpha <= 0:
            w = np.ones(M)
        elif alpha >= 1:
            w = 0.5 - 0.5 * np.cos(2 * np.pi * n / (M - 1))
        else:
            width = int(np.floor(alpha * (M - 1) / 2.0))
            n1 = n[0:width + 1]
            n2 = n[width + 1:M - width - 1]
            n3 = n[M - width - 1:]
            w1 = 0.5 * (1 + np.cos(np.pi * (-1 + 2.0 * n1 /
                                            alpha / (M - 1))))
            w2 = np.ones(n2.shape[0])
            w3 = 0.5 * (1 + np.cos(np.pi * (-2.0 / alpha + 1 + 2.0 * n3 /
                                            alpha / (M - 1))))
            w = np.concatenate([w1, w2, w3])
    elif name == "kaiser":
        beta = args[0] if args else 14.0
        w = np.i0(beta * np.sqrt(1 - ((n - (M - 1) / 2)
                                      / ((M - 1) / 2)) ** 2)) / np.i0(beta)
    elif name in window_function_register._functions_dict:
        w = np.asarray(window_function_register.get(name)(M, *args),
                       dtype=np.float64)
    else:
        raise ValueError(f"unsupported window: {window!r}")
    return Tensor(jnp.asarray(_truncate(w, trunc)).astype(dtype))


class WindowFunctionRegister:
    """Custom-window registry (reference audio/functional/window.py:22):
    @window_function_register.register() adds a window factory that
    get_window resolves by function name."""

    def __init__(self):
        self._functions_dict = {}

    def register(self, func=None):
        def add_subfunction(f):
            self._functions_dict[f.__name__] = f
            return f
        if func is not None:
            return add_subfunction(func)
        return add_subfunction

    def get(self, name):
        if name not in self._functions_dict:
            raise ValueError(
                f"no window registered under {name!r}; known: "
                f"{sorted(self._functions_dict)}")
        return self._functions_dict[name]


window_function_register = WindowFunctionRegister()
