"""paddle.audio parity namespace (reference: python/paddle/audio)."""
from paddle_tpu.audio import backends, datasets, features, functional  # noqa: F401
from paddle_tpu.audio.backends import info, load, save  # noqa: F401
