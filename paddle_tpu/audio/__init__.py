"""paddle.audio parity namespace (reference: python/paddle/audio)."""
from paddle_tpu.audio import features, functional  # noqa: F401
