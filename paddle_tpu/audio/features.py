"""Audio feature extraction layers.

Reference parity: python/paddle/audio/features/layers.py — Spectrogram
(:28), MelSpectrogram (:110), LogMelSpectrogram (:210), MFCC (:313).

TPU-native: the STFT runs through paddle_tpu.signal.stft (framed matmul
against the DFT basis — MXU-friendly, statically shaped); the mel
projection is a single [n_mels, F] matmul; everything jits.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.audio import functional as AF
from paddle_tpu.core.dispatch import apply
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu import signal

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=1.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        assert power > 0, "Power of spectrogram must be > 0."
        self.power = power
        self.n_fft = n_fft
        self.hop_length = hop_length
        self.center = center
        self.pad_mode = pad_mode
        win_length = win_length or n_fft
        self.fft_window = AF.get_window(window, win_length, fftbins=True,
                                        dtype=dtype)

    def forward(self, x):
        st = signal.stft(x, self.n_fft, self.hop_length,
                         self.fft_window.shape[0], window=self.fft_window,
                         center=self.center, pad_mode=self.pad_mode)
        return apply(lambda v: jnp.abs(v) ** self.power, st)


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=2048, hop_length=512,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        if f_max is None:
            f_max = sr // 2
        self.fbank_matrix = AF.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype)

    def forward(self, x):
        spect = self._spectrogram(x)                 # [..., F, T]
        return apply(lambda f, s: jnp.matmul(f, s),
                     self.fbank_matrix, spect)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=2048, hop_length=512,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return AF.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, norm="ortho", **melkwargs):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(sr=sr, **melkwargs)
        n_mels = self._log_melspectrogram._melspectrogram \
            .fbank_matrix.shape[0]
        self.dct_matrix = AF.create_dct(n_mfcc, n_mels, norm)

    def forward(self, x):
        logmel = self._log_melspectrogram(x)         # [..., n_mels, T]
        return apply(lambda d, m: jnp.einsum("mk,...mt->...kt", d, m),
                     self.dct_matrix, logmel)
