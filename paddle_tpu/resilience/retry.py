"""Retry with exponential backoff, deterministic jitter, and
per-exception-class policies.

Design points:

- **Deterministic jitter.** Thundering-herd protection normally wants
  randomness, but a chaos suite wants replayability — so jitter comes
  from a ``random.Random(seed)`` owned by the decorated callable, and
  two runs with the same seed produce the same delay sequence.  Seed it
  per host (e.g. ``seed=jax.process_index()``) to spread a fleet.
- **Per-exception policies.** A flaky filesystem deserves patience; an
  assertion does not.  ``policies={TimeoutError: RetryPolicy(...)}``
  overrides the default policy for matching exception classes;
  an exception matching NO policy (and not ``retry_on``) re-raises
  immediately.
- **Telemetry.** Every retry records a ``resilience.retry`` span and
  bumps ``resilience_retries_total{fn=...}``; a call that eventually
  succeeds after retries records a recovery event — pairing with
  injected faults in the chaos report.
"""
from __future__ import annotations

import functools
import random
import time

from paddle_tpu.resilience.faultinject import note_recovery

__all__ = ["RetryPolicy", "RetryExhausted", "retry", "compute_backoff"]


class RetryExhausted(RuntimeError):
    """Raised when every attempt failed; ``__cause__`` is the last
    underlying exception, ``attempts`` how many ran."""

    def __init__(self, fn_name, attempts, last):
        self.attempts = attempts
        super().__init__(
            f"{fn_name} failed after {attempts} attempts "
            f"({type(last).__name__}: {last})")


class RetryPolicy:
    """How to retry one class of failure.

    backoff delay for attempt k (0-based retry index) is::

        min(backoff * multiplier**k, max_backoff) * (1 + U(-jitter, 0))

    i.e. jitter only ever SHORTENS the wait (never exceeds the declared
    ceiling) and ``jitter=0`` is exact exponential backoff.
    """

    def __init__(self, max_attempts=3, backoff=0.05, multiplier=2.0,
                 max_backoff=30.0, jitter=0.5):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self.max_attempts = int(max_attempts)
        self.backoff = float(backoff)
        self.multiplier = float(multiplier)
        self.max_backoff = float(max_backoff)
        self.jitter = float(jitter)

    def __repr__(self):
        return (f"RetryPolicy(max_attempts={self.max_attempts}, "
                f"backoff={self.backoff}, multiplier={self.multiplier}, "
                f"max_backoff={self.max_backoff}, jitter={self.jitter})")


def compute_backoff(policy, attempt, rng):
    """Delay in seconds before retry `attempt` (0-based)."""
    base = min(policy.backoff * policy.multiplier ** attempt,
               policy.max_backoff)
    if policy.jitter:
        base *= 1.0 - rng.random() * policy.jitter
    return base


def _policy_for(exc, default, policies):
    for cls, pol in policies.items():
        if isinstance(exc, cls):
            return pol
    return default


def retry(fn=None, *, max_attempts=3, backoff=0.05, multiplier=2.0,
          max_backoff=30.0, jitter=0.5, retry_on=(Exception,),
          policies=None, seed=0, sleep=time.sleep, on_retry=None):
    """Decorator (bare or parameterized)::

        @retry(max_attempts=5, backoff=0.1,
               policies={OSError: RetryPolicy(max_attempts=8)})
        def flaky_write(...): ...

    `retry_on` bounds which exceptions are retryable AT ALL under the
    default policy; `policies` maps exception classes to dedicated
    :class:`RetryPolicy` overrides (checked first, so a class can be
    retryable via `policies` without widening `retry_on`).
    `on_retry(exc, attempt, delay)` observes each scheduled retry.
    """
    if fn is not None and callable(fn):          # bare @retry form
        return retry()(fn)
    default = RetryPolicy(max_attempts, backoff, multiplier, max_backoff,
                          jitter)
    policies = dict(policies or {})

    def deco(f):
        name = getattr(f, "__qualname__", repr(f))

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            rng = random.Random(seed)
            attempt = 0
            while True:
                try:
                    out = f(*args, **kwargs)
                    if attempt:
                        note_recovery("retry", "exception", fn=name,
                                      attempts=attempt + 1)
                    return out
                except Exception as e:
                    pol = _policy_for(e, None, policies)
                    if pol is None:
                        if not isinstance(e, tuple(retry_on)):
                            raise
                        pol = default
                    attempt += 1
                    if attempt >= pol.max_attempts:
                        raise RetryExhausted(name, attempt, e) from e
                    delay = compute_backoff(pol, attempt - 1, rng)
                    _record_retry(name, e, attempt, delay)
                    if on_retry is not None:
                        on_retry(e, attempt, delay)
                    if delay > 0:
                        sleep(delay)

        wrapper.retry_policy = default
        return wrapper

    return deco


def _record_retry(name, exc, attempt, delay):
    try:
        from paddle_tpu import observability as obs
        with obs.span("resilience.retry", fn=name, attempt=attempt,
                      exc=type(exc).__name__, delay_s=round(delay, 4)):
            pass
        obs.registry().counter(
            "resilience_retries_total", labels={"fn": name},
            help="retries scheduled by resilience.retry").inc()
    except Exception:
        pass
