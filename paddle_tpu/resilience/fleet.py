"""Fleet-grade fault tolerance: timeout-bounded coordination, rank
heartbeats with a fleet watchdog, sharded distributed checkpoints, and
elastic reconfigure-and-resume.

Every multi-process path in this repo rides the jax.distributed
coordination service's key-value store (``distributed/collective.py``'s
``_coord_*`` eager collectives, the launch rendezvous, the elastic
heartbeat server).  Before this module, every one of those blocking
gets assumed no rank ever dies — one SIGKILLed host stranded every
survivor forever.  The fleet layer turns that into a bounded, observable,
recoverable protocol (docs/resilience.md "Distributed fault tolerance"):

1. **Timeout-bounded KV gets** — :func:`kv_get_bytes` slices a blocking
   get into short coordinator round-trips with seeded-backoff retries
   (PR 6 :class:`~paddle_tpu.resilience.retry.RetryPolicy` shape) under
   one deadline, raising a machine-readable :class:`CollectiveTimeout`
   naming the missing rank instead of hanging.  An ``abort_if`` hook
   lets a caller bail out early the moment the fleet watchdog reaches a
   DEAD verdict for the awaited peer.
2. **Rank heartbeats + fleet watchdog** — every rank runs a
   :class:`HeartbeatPublisher` (monotonic sequence + progress counter
   through the KV store; ``elastic.notify_progress()`` feeds the
   progress counter so a slow gradient-accumulate window is still
   progress); a :class:`FleetMonitor` classifies peers
   HEALTHY → SUSPECT → DEAD with hysteresis (PR 6
   :class:`~paddle_tpu.resilience.health.HealthMonitor` shape) and
   drives the ``fleet_rank_state{rank=}`` /
   ``fleet_last_heartbeat_age_s{rank=}`` gauges plus
   ``resilience.fleet.*`` spans through the observability registry.
3. **Sharded distributed checkpoints** — :class:`DistributedCheckpointer`
   writes one shard per rank through ``framework.io.write_atomic``;
   rank 0 commits a quorum MANIFEST (sha256 per shard, world size,
   mesh spec) only after every shard digest is durable, and ``load()``
   can reconstruct state at a *different* world size by resharding the
   dp-stacked leaves — skipping torn entries per the PR 6 last-good
   contract.
4. **Elastic reconfigure-and-resume** — on a :class:`CollectiveTimeout`
   or DEAD verdict, :func:`reconfigure` re-rendezvouses the survivors
   under a fresh key namespace at the shrunk world size; the training
   loop reloads the last-good distributed checkpoint and resumes.

Scope note: the coordination service lives in global rank 0's process
(jax.distributed's design), so rank 0 itself dying is unrecoverable
in-process — that failure mode needs the external launcher to restart
the job (exit-code protocol, PR 6).  Every *other* rank's death is
recoverable here, and that is the failure mode that dominates real
fleets (preemption of one host).

jaxlib quirk (pinned by tests/test_fleet.py): this jaxlib's
``blocking_key_value_get_bytes`` segfaults on ONE-byte stored values
(the compressed-payload path), so :func:`kv_set_bytes` pads every
payload to >= 2 bytes.  All fleet payloads are JSON and naturally
bigger; the pad is a guard for callers storing raw flags.
"""
from __future__ import annotations

import json
import os
import pickle
import random
import threading
import time
import uuid
from enum import IntEnum

from paddle_tpu.resilience import faultinject
from paddle_tpu.resilience.retry import RetryPolicy, compute_backoff

__all__ = [
    "CollectiveTimeout",
    "DistributedCheckpointer",
    "FleetConfig",
    "FleetMonitor",
    "HeartbeatPublisher",
    "LocalKVClient",
    "RankState",
    "WorldView",
    "configure",
    "coord_namespace",
    "coord_shutdown",
    "finalize",
    "get_config",
    "get_monitor",
    "get_publisher",
    "install_monitor",
    "install_publisher",
    "kv_get_bytes",
    "kv_set_bytes",
    "notify_fleet_progress",
    "reconfigure",
    "world",
]


# --------------------------------------------------------------- config
def _env_float(name, default):
    v = os.environ.get(name)
    try:
        return float(v) if v else float(default)
    except ValueError:
        return float(default)


class FleetConfig:
    """Timeout budgets for the coordination path.

    Every knob has an env override so the launcher (and the chaos
    suite) can shrink the budgets without touching training code:

    - ``collective_timeout_s`` (``PTPU_FLEET_TIMEOUT_S``): total wait
      for one peer's contribution to an eager collective before
      :class:`CollectiveTimeout`;
    - ``kv_slice_s`` (``PTPU_FLEET_KV_SLICE_S``): one blocking-get
      round trip — the granularity at which ``abort_if`` (the DEAD
      verdict) is polled;
    - ``heartbeat_interval_s`` (``PTPU_FLEET_HB_INTERVAL_S``) and the
      derived SUSPECT/DEAD ages (3x / 6x by default, overridable);
    - ``rendezvous_timeout_s`` (``PTPU_FLEET_RENDEZVOUS_TIMEOUT_S``):
      reconfigure join-barrier budget;
    - ``progress_timeout_s`` (``PTPU_FLEET_PROGRESS_TIMEOUT_S``,
      0/unset = disabled): frozen-progress → SUSPECT livelock window.
    """

    def __init__(self, collective_timeout_s=None, kv_slice_s=None,
                 heartbeat_interval_s=None, suspect_after_s=None,
                 dead_after_s=None, rendezvous_timeout_s=None,
                 progress_timeout_s=None, retry=None):
        self.collective_timeout_s = (
            collective_timeout_s if collective_timeout_s is not None
            else _env_float("PTPU_FLEET_TIMEOUT_S", 60.0))
        self.kv_slice_s = (kv_slice_s if kv_slice_s is not None
                           else _env_float("PTPU_FLEET_KV_SLICE_S", 1.0))
        self.heartbeat_interval_s = (
            heartbeat_interval_s if heartbeat_interval_s is not None
            else _env_float("PTPU_FLEET_HB_INTERVAL_S", 5.0))
        self.suspect_after_s = (
            suspect_after_s if suspect_after_s is not None
            else _env_float("PTPU_FLEET_SUSPECT_AFTER_S",
                            3.0 * self.heartbeat_interval_s))
        self.dead_after_s = (
            dead_after_s if dead_after_s is not None
            else _env_float("PTPU_FLEET_DEAD_AFTER_S",
                            6.0 * self.heartbeat_interval_s))
        self.rendezvous_timeout_s = (
            rendezvous_timeout_s if rendezvous_timeout_s is not None
            else _env_float("PTPU_FLEET_RENDEZVOUS_TIMEOUT_S",
                            self.collective_timeout_s))
        # progress staleness -> SUSPECT (None/0 = heartbeat liveness
        # only; env-enabled like every other knob)
        if progress_timeout_s is None:
            progress_timeout_s = _env_float(
                "PTPU_FLEET_PROGRESS_TIMEOUT_S", 0.0) or None
        self.progress_timeout_s = progress_timeout_s
        if not (0 < self.suspect_after_s < self.dead_after_s):
            raise ValueError(
                "need 0 < suspect_after_s < dead_after_s, got "
                f"{self.suspect_after_s}/{self.dead_after_s}")
        # short max_backoff: the slice-get itself blocks server-side,
        # backoff only spaces the coordinator round trips
        self.retry = retry or RetryPolicy(
            max_attempts=1_000_000, backoff=0.02, multiplier=2.0,
            max_backoff=0.5, jitter=0.5)


_config = FleetConfig()
_config_lock = threading.Lock()


def get_config():
    return _config


def configure(**overrides):
    """Replace the process-wide :class:`FleetConfig` (call before the
    training loop; returns the new config)."""
    global _config
    with _config_lock:
        _config = FleetConfig(**overrides)
        return _config


# --------------------------------------------------------------- errors
class CollectiveTimeout(RuntimeError):
    """A coordination-path wait exceeded its deadline (or the fleet
    watchdog reached a DEAD verdict for the awaited peer).  Machine-
    readable: ``site``/``key``/``missing_rank``/``waited_s``/
    ``timeout_s``/``namespace``/``verdict`` — the elastic recovery path
    branches on these, never on the message text."""

    def __init__(self, site, key=None, missing_rank=None, waited_s=0.0,
                 timeout_s=0.0, namespace=None, verdict=None):
        self.site = str(site)
        self.key = key
        self.missing_rank = missing_rank
        self.waited_s = float(waited_s)
        self.timeout_s = float(timeout_s)
        self.namespace = namespace
        self.verdict = verdict       # e.g. "deadline" or "dead-verdict"
        who = (f"rank {missing_rank}" if missing_rank is not None
               else f"key {key!r}")
        super().__init__(
            f"collective timeout at {self.site!r}: {who} missing after "
            f"{self.waited_s:.2f}s (budget {self.timeout_s:.2f}s, "
            f"verdict={self.verdict or 'deadline'})")

    def to_dict(self):
        return {"site": self.site, "key": self.key,
                "missing_rank": self.missing_rank,
                "waited_s": round(self.waited_s, 3),
                "timeout_s": self.timeout_s,
                "namespace": self.namespace,
                "verdict": self.verdict or "deadline"}


# ---------------------------------------------------------------- world
class WorldView:
    """The fleet's current membership: the GLOBAL ranks (launch-time
    process ids — stable across reconfigurations) that are members,
    this process's global rank, and the contiguous fleet rank derived
    from the member list.  Generation 0 is the launch world; every
    :func:`reconfigure` bumps the generation and shrinks ``members``."""

    def __init__(self, members, global_rank, generation=0,
                 launch_id="local"):
        self.members = tuple(int(m) for m in members)
        self.global_rank = int(global_rank)
        if self.global_rank not in self.members:
            raise ValueError(
                f"global rank {self.global_rank} not in members "
                f"{self.members}")
        self.generation = int(generation)
        self.launch_id = str(launch_id)

    @property
    def rank(self):
        """Contiguous fleet rank (index into the member list)."""
        return self.members.index(self.global_rank)

    @property
    def size(self):
        return len(self.members)

    @property
    def namespace(self):
        return f"ptpu/{self.launch_id}/g{self.generation}"

    def to_dict(self):
        return {"members": list(self.members),
                "global_rank": self.global_rank, "rank": self.rank,
                "size": self.size, "generation": self.generation,
                "launch_id": self.launch_id}

    def __repr__(self):
        return (f"WorldView(members={self.members}, "
                f"global_rank={self.global_rank}, "
                f"generation={self.generation})")


_world = None
_world_lock = threading.Lock()
_launch_id = [None]


def _client():
    """The jax.distributed coordination-service client, or None."""
    try:
        from jax._src import distributed as jd
        return jd.global_state.client
    except Exception:
        return None


def _ensure_launch_id(client=None):
    """A per-run id namespacing every coordination key, so an aborted
    run's debris can never collide with (or strand) the next run on a
    long-lived coordinator.  Agreement order: the launcher's
    ``PADDLE_LAUNCH_ID`` env wins; else rank 0 publishes a fresh uuid
    through the (fresh-per-run) KV store and peers read it; else
    single-process ``local``."""
    if _launch_id[0] is not None:
        return _launch_id[0]
    # agreement happens OUTSIDE _world_lock (it blocks on the KV
    # store; holding the lock across network waits is the RL103 class
    # this module polices).  launch() calls this once at bootstrap; a
    # concurrent duplicate agreement is harmless (same env value, or
    # peers read whichever uuid rank 0 published last).
    lid = os.environ.get("PADDLE_LAUNCH_ID")
    if not lid:
        client = client if client is not None else _client()
        if client is not None:
            import jax
            key = "ptpu/launch/current"
            if jax.process_index() == 0:
                lid = uuid.uuid4().hex[:8]
                _kv_set_str(client, key, lid)
            else:
                # timeout-bounded like every other coordination wait:
                # a coordinator that dies before rank 0 publishes must
                # surface as CollectiveTimeout, not a 120s opaque hang
                lid = kv_get_bytes(
                    client, key, get_config().rendezvous_timeout_s,
                    site="fleet.kv_get", missing_rank=0).decode()
        else:
            lid = "local"
    with _world_lock:
        if _launch_id[0] is None:
            _launch_id[0] = str(lid)
        return _launch_id[0]


def world():
    """The installed :class:`WorldView` (after a reconfigure) or the
    launch-time default derived from jax.distributed."""
    wv = _world
    if wv is not None:
        return wv
    try:
        import jax
        n, r = jax.process_count(), jax.process_index()
    except Exception:
        n, r = 1, 0
    return WorldView(range(n), r, generation=0,
                     launch_id=_launch_id[0] or "local")


def _set_world(wv):
    global _world
    with _world_lock:
        _world = wv
    return wv


def coord_namespace():
    """Key namespace for the CURRENT world generation — every
    coordination key (collectives, heartbeats, checkpoints, joins)
    lives under it, so one ``key_value_delete`` of the namespace reaps
    a whole generation (clean exit, reconfigure)."""
    wv = _world
    if wv is not None:
        return wv.namespace
    return f"ptpu/{_launch_id[0] or 'local'}/g0"


def coord_shutdown(client=None):
    """Clean-exit reap: fleet rank 0 deletes the current generation's
    whole key namespace (registered via atexit by the launcher — an
    aborted run skips it, which is exactly why keys are launch-id
    namespaced)."""
    client = client if client is not None else _client()
    if client is None:
        return
    wv = world()
    if wv.rank != 0:
        return
    try:
        client.key_value_delete(coord_namespace())
    except Exception:
        pass


_finalized = [False]


def finalize(timeout_s=30.0, client=None):
    """Fleet check-out barrier — the ONLY safe place for the clean-exit
    namespace reap, and mandatory before ``os._exit`` once a peer has
    died (the jax client's destructor-time shutdown barrier can never
    complete against a dead task).  Every member publishes a done
    marker; the COORDINATOR HOST (global rank 0 — the process whose
    death takes the whole KV service with it, and the only rank that
    may not exit early) lingers until all members checked out (bounded,
    best-effort), THEN reaps the namespace — reaping before the
    check-out would delete keys a slower peer is still mid-read on
    (the exact leak-vs-strand tension the per-run namespace exists
    for).  Registered via atexit by ``launch()``; idempotent."""
    if _finalized[0]:
        return
    _finalized[0] = True
    client = client if client is not None else _client()
    if client is None:
        return
    wv = world()
    try:
        kv_set_bytes(client,
                     f"{wv.namespace}/fleet/done/{wv.global_rank}",
                     b"ok")
    except Exception:
        return
    if wv.global_rank != 0:
        return
    # ONE shared deadline across all members — per-member budgets
    # would stack to (n-1) * timeout_s when many peers died, wedging
    # rank 0's atexit for minutes on a large fleet
    deadline = time.monotonic() + float(timeout_s)
    for m in wv.members:
        if m == wv.global_rank:
            continue
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        try:
            kv_get_bytes(client, f"{wv.namespace}/fleet/done/{m}",
                         remaining, site="fleet.kv_get",
                         missing_rank=m)
        except Exception:
            pass      # best-effort: a wedged peer must not trap rank 0
    coord_shutdown(client)
    # one beat of grace so peers' in-flight RPC cycles drain before the
    # service goes away with this process
    time.sleep(0.2)


# ----------------------------------------------------------- KV helpers
def _kv_set_str(client, key, value):
    try:
        client.key_value_set(key, value, allow_overwrite=True)
    except TypeError:        # older client without the kwarg
        try:
            client.key_value_delete(key)
        except Exception:
            pass
        client.key_value_set(key, value)


def kv_set_bytes(client, key, data):
    """Store bytes, overwriting (heartbeats re-publish the same key
    every interval) and padded to >= 2 bytes: this jaxlib segfaults
    inside ``blocking_key_value_get_bytes`` on a one-byte stored value
    (the compressed-payload path), so the choke point guarantees no
    fleet payload can ever trip it.  Readers must tolerate a trailing
    pad byte on sub-2-byte payloads (JSON/pickle payloads never
    are)."""
    if len(data) < 2:
        data = bytes(data) + b"\x00" * (2 - len(data))
    try:
        client.key_value_set_bytes(key, bytes(data),
                                   allow_overwrite=True)
    except TypeError:            # older client without the kwarg
        try:
            client.key_value_delete(key)
        except Exception:
            pass
        client.key_value_set_bytes(key, bytes(data))


def kv_get_bytes(client, key, timeout_s=None, *, site="fleet.kv_get",
                 missing_rank=None, abort_if=None, config=None,
                 seed=None):
    """Deadline-bounded blocking get: short coordinator round trips
    (``config.kv_slice_s``) under one deadline, seeded-backoff spacing
    between attempts (deterministic — chaos replayable), and an
    ``abort_if()`` poll after each MISSED slice so a DEAD verdict from
    the fleet watchdog aborts the wait within one slice instead of
    burning the full budget — but never before trying: data a peer
    published before dying must still be returned.  Raises
    :class:`CollectiveTimeout` naming ``missing_rank`` (or the key) —
    never hangs.

    Fault site ``fleet.kv_get``: ``exception`` raises
    :class:`~paddle_tpu.resilience.faultinject.WorkerFault` before the
    first round trip; ``slow`` delays it — both deterministic on the
    per-site occurrence counter.
    """
    config = config or get_config()
    timeout_s = (float(timeout_s) if timeout_s is not None
                 else config.collective_timeout_s)
    faultinject.fire(site, key=key, missing_rank=missing_rank)
    t0 = time.monotonic()
    deadline = t0 + timeout_s
    # stable key-derived default seed (NOT hash(): str hashes are
    # salted per process, which would unseed the chaos-replayable
    # backoff sequence this function documents)
    import zlib
    rng = random.Random(seed if seed is not None
                        else zlib.crc32(key.encode()) & 0xffff)
    attempt = 0
    last_exc = None
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            exc = CollectiveTimeout(
                site, key=key, missing_rank=missing_rank,
                waited_s=time.monotonic() - t0, timeout_s=timeout_s,
                namespace=coord_namespace(), verdict="deadline")
            _record_timeout(exc)
            # chain the last underlying client error: a dead
            # coordinator must not masquerade as a merely-absent key
            raise exc from last_exc
        slice_ms = max(1, int(min(remaining, config.kv_slice_s) * 1000))
        try:
            return client.blocking_key_value_get_bytes(key, slice_ms)
        except Exception as e:
            # the DEAD verdict is consulted only AFTER a missed slice:
            # data a peer published before dying (a durable shard
            # digest, an already-complete allgather round) must still
            # be returned, not discarded into a spurious timeout — a
            # dead publisher's key does not disappear
            if abort_if is not None and abort_if():
                exc = CollectiveTimeout(
                    site, key=key, missing_rank=missing_rank,
                    waited_s=time.monotonic() - t0,
                    timeout_s=timeout_s, namespace=coord_namespace(),
                    verdict="dead-verdict")
                _record_timeout(exc)
                raise exc from e
            # DEADLINE_EXCEEDED for this slice (or a transient
            # coordinator error): back off deterministically and retry
            # until OUR deadline decides.  The exponent is clamped —
            # max_backoff saturates the VALUE long before, but
            # multiplier**attempt itself overflows float at ~1024
            last_exc = e
            delay = min(compute_backoff(config.retry, min(attempt, 32),
                                        rng),
                        max(0.0, deadline - time.monotonic()))
            attempt += 1
            if delay > 0:
                time.sleep(delay)


def _record_timeout(exc):
    try:
        from paddle_tpu import observability as obs
        with obs.span("resilience.fleet.timeout", **exc.to_dict()):
            pass
        obs.registry().counter(
            "fleet_collective_timeouts_total",
            labels={"site": exc.site},
            help="coordination waits that exceeded their deadline").inc()
    except Exception:
        pass


class LocalKVClient:
    """In-process stand-in for the jax.distributed coordination-service
    client (same method subset, same blocking semantics) so the fleet
    machinery — publisher, watchdog, distributed checkpoints,
    reconfigure — is unit-testable and benchable with rank-per-thread
    worlds, no gRPC coordinator needed."""

    def __init__(self):
        self._data = {}
        self._cond = threading.Condition()

    def key_value_set(self, key, value, allow_overwrite=False):
        with self._cond:
            if key in self._data and not allow_overwrite:
                raise ValueError(f"key {key!r} already set")
            self._data[key] = str(value)
            self._cond.notify_all()

    def key_value_set_bytes(self, key, value, allow_overwrite=False):
        with self._cond:
            if key in self._data and not allow_overwrite:
                raise ValueError(f"key {key!r} already set")
            self._data[key] = bytes(value)
            self._cond.notify_all()

    def _blocking_get(self, key, timeout_in_ms):
        deadline = time.monotonic() + timeout_in_ms / 1000.0
        with self._cond:
            while key not in self._data:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    if key not in self._data:
                        raise TimeoutError(
                            f"DEADLINE_EXCEEDED waiting for {key!r}")
            return self._data[key]

    def blocking_key_value_get(self, key, timeout_in_ms):
        return str(self._blocking_get(key, timeout_in_ms))

    def blocking_key_value_get_bytes(self, key, timeout_in_ms):
        v = self._blocking_get(key, timeout_in_ms)
        return v if isinstance(v, bytes) else str(v).encode()

    def key_value_dir_get(self, prefix):
        with self._cond:
            return sorted((k, str(v)) for k, v in self._data.items()
                          if k.startswith(prefix))

    def key_value_dir_get_bytes(self, prefix):
        with self._cond:
            return sorted(
                (k, v if isinstance(v, bytes) else str(v).encode())
                for k, v in self._data.items() if k.startswith(prefix))

    def key_value_delete(self, key):
        """Key plus directory semantics (the coordination service reaps
        ``key`` and every ``key/...`` child)."""
        with self._cond:
            for k in [k for k in self._data
                      if k == key or k.startswith(key.rstrip("/") + "/")]:
                del self._data[k]


# ----------------------------------------------------- heartbeat plane
class RankState(IntEnum):
    HEALTHY = 0
    SUSPECT = 1
    DEAD = 2


class HeartbeatPublisher:
    """One per rank: a daemon thread publishes
    ``<ns>/fleet/hb/<global_rank>`` every ``interval_s`` with a JSON
    payload ``{"seq": n, "t": wall, "progress": p}`` — ``seq`` is the
    publisher's own monotonic beat counter (the watchdog measures
    staleness by LOCAL time since it last saw ``seq`` advance, so
    cross-host clock skew cannot fake liveness), ``progress`` is bumped
    by :meth:`beat` / ``elastic.notify_progress()`` so a slow
    gradient-accumulate window (k-1 of every k microbatches never reach
    ``Optimizer.step``) still reads as forward progress.

    The key namespace is re-read every publish, so a reconfigure's
    generation bump redirects beats automatically.

    ``payload_fn`` (optional) is called once per beat, outside the
    publisher lock, and its dict rides along as
    ``payload["telemetry"]`` — the serving fleet uses it to ship queue
    depth / page occupancy / health state with every heartbeat
    (docs/serving.md "Multi-host fleet").  A failing payload_fn never
    suppresses the beat: liveness must not hinge on telemetry.

    Fault site ``fleet.heartbeat`` (kinds ``exception`` / ``slow``):
    an injected exception skips that beat (counted in
    ``missed_beats``) — the publisher thread itself must survive, a
    dead publisher is indistinguishable from a dead rank.
    """

    def __init__(self, client=None, rank=None, interval_s=None,
                 world_fn=None, time_fn=time.time, payload_fn=None):
        self._client = client if client is not None else _client()
        self._world_fn = world_fn or world
        self._rank = (int(rank) if rank is not None
                      else self._world_fn().global_rank)
        self._interval = (float(interval_s) if interval_s is not None
                          else get_config().heartbeat_interval_s)
        self._time = time_fn
        # optional per-beat telemetry (serving fleet: queue depth, page
        # occupancy, health state) merged as payload["telemetry"]; the
        # callable runs OUTSIDE the publisher lock — it reads engine
        # state that takes its own locks (RL103)
        self._payload_fn = payload_fn
        self._lock = threading.Lock()
        self._seq = 0
        self._progress = 0
        self.missed_beats = 0
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = None

    @property
    def rank(self):
        return self._rank

    @property
    def seq(self):
        with self._lock:
            return self._seq

    @property
    def progress(self):
        with self._lock:
            return self._progress

    def start(self):
        if self._thread is None and self._client is not None:
            self._stop.clear()       # restartable after stop()
            self._wake.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"paddle_tpu-fleet-hb-{self._rank}")
            self._thread.start()
        return self

    def beat(self):
        """Record forward progress (called from
        ``elastic.notify_progress()`` every optimizer/microbatch step).
        Deliberately does NOT wake the publisher thread: the next
        interval beat carries the updated counter — waking per step
        would turn the publish rate into the training-step rate and
        flood the single gRPC coordinator exactly when the fleet is
        busiest."""
        with self._lock:
            self._progress += 1

    def publish_once(self):
        """One beat, synchronously (the thread loop body; also callable
        directly in tests)."""
        try:
            faultinject.fire("fleet.heartbeat", rank=self._rank)
        except faultinject.WorkerFault:
            with self._lock:
                self.missed_beats += 1
            return False
        now = self._time()          # user-supplied clock: never call it
        telemetry = None            # under the publisher lock (RL103)
        if self._payload_fn is not None:
            try:
                telemetry = self._payload_fn()
            except Exception:
                telemetry = None    # beat still goes out (liveness
                #                     must not hinge on telemetry)
        with self._lock:
            self._seq += 1
            payload = {"seq": self._seq, "t": now,
                       "progress": self._progress}
            if telemetry is not None:
                payload["telemetry"] = telemetry
        key = f"{coord_namespace()}/fleet/hb/{self._rank}"
        try:
            kv_set_bytes(self._client, key,
                         json.dumps(payload).encode())
            return True
        except Exception:
            with self._lock:
                self.missed_beats += 1
            return False

    def _run(self):
        while not self._stop.is_set():
            self.publish_once()
            self._wake.wait(self._interval)
            self._wake.clear()

    def stop(self):
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


_publisher = None
_monitor = None


def install_publisher(pub):
    global _publisher
    with _world_lock:
        _publisher = pub
    return pub


def get_publisher():
    return _publisher


def notify_fleet_progress():
    """``distributed.elastic.notify_progress()`` forwards here: every
    watchdog beat is also fleet progress (near-free without an
    installed publisher)."""
    pub = _publisher
    if pub is not None:
        pub.beat()


def install_monitor(mon):
    global _monitor
    with _world_lock:
        _monitor = mon
    return mon


def get_monitor():
    return _monitor


class FleetMonitor:
    """The fleet watchdog: reads every member's heartbeat key and
    classifies peers HEALTHY → SUSPECT → DEAD with hysteresis.

    Staleness is LOCAL-clock time since this monitor last observed the
    peer's ``seq`` advance (never a cross-host wall-clock difference):

    - HEALTHY → SUSPECT at age > ``suspect_after_s``
    - SUSPECT → DEAD    at age > ``dead_after_s``
    - SUSPECT → HEALTHY the moment a fresh ``seq`` lands
    - DEAD is terminal for the generation — a DEAD verdict feeds
      :func:`reconfigure`, never silent resurrection (a rank that
      was declared dead may have been evicted for cause).

    With ``progress_timeout_s`` set, a peer whose beats flow but whose
    ``progress`` counter is frozen for that long is demoted to SUSPECT
    (livelock: the host is alive, training is not) — it recovers the
    moment progress advances.

    Every poll refreshes ``fleet_rank_state{rank=}`` and
    ``fleet_last_heartbeat_age_s{rank=}`` gauges (scraped by
    ``observability.export.serve_prometheus`` with zero extra
    plumbing); every transition records a
    ``resilience.fleet.transition`` span.  ``on_dead(ranks)`` fires
    outside the lock (PR 7 health-callback lesson) once per newly-DEAD
    set.
    """

    def __init__(self, client=None, config=None, world_fn=None,
                 on_dead=None, time_fn=time.monotonic,
                 poll_interval_s=None):
        self._client = client if client is not None else _client()
        self._config = config or get_config()
        self._world_fn = world_fn or world
        self.on_dead = on_dead
        self._time = time_fn
        self._poll_interval = (
            float(poll_interval_s) if poll_interval_s is not None
            else self._config.heartbeat_interval_s / 2.0)
        self._lock = threading.Lock()
        self._seen = {}      # rank -> (seq, progress, first_seen_local,
        #                               seq_local, progress_local)
        self._payloads = {}  # rank -> latest full beat payload (carries
        #                      the serving "telemetry" dict when the
        #                      publisher has a payload_fn)
        self._states = {}    # rank -> RankState
        self._quarantined = {}   # rank -> reason (sticky SUSPECT)
        self._holds = {}     # rank -> local-clock hold deadline (boot)
        self.transitions = []  # [(rank, old, new, age_s)]
        self._stop = threading.Event()
        self._thread = None

    # ---- classification ----
    def poll(self):
        """One watchdog pass; returns ``{global_rank: RankState}`` for
        the current world's members."""
        wv = self._world_fn()
        now = self._time()
        beats = {}
        try:
            pairs = self._client.key_value_dir_get_bytes(
                f"{coord_namespace()}/fleet/hb/")
        except Exception:
            # a failed read is OUR outage, not peer silence: aging
            # peers on zero evidence would condemn the whole healthy
            # fleet (DEAD is terminal) after one coordinator blip
            # longer than dead_after_s — no evidence, no verdict change
            with self._lock:
                return {r: self._states.get(r, RankState.HEALTHY)
                        for r in wv.members}
        for key, raw in pairs:
            try:
                r = int(key.rsplit("/", 1)[-1])
                beats[r] = json.loads(bytes(raw).decode())
            except (ValueError, json.JSONDecodeError):
                continue
        newly_dead = []
        events = []
        gauge_updates = []
        with self._lock:
            for r in wv.members:
                old = self._states.get(r, RankState.HEALTHY)
                seen = self._seen.get(r)
                b = beats.get(r)
                if b is not None:
                    self._payloads[r] = b
                    if seen is None or b["seq"] > seen[0]:
                        prog_local = (now if seen is None
                                      or b.get("progress", 0) > seen[1]
                                      else seen[4])
                        seen = (b["seq"], b.get("progress", 0),
                                seen[2] if seen else now, now, prog_local)
                    elif b.get("progress", 0) > seen[1]:
                        seen = (seen[0], b.get("progress", 0), seen[2],
                                seen[3], now)
                    self._seen[r] = seen
                elif seen is None:
                    # no beat yet: grace-period from first observation
                    seen = (0, 0, now, now, now)
                    self._seen[r] = seen
                age = now - seen[3]
                new = self._classify(old, age, now - seen[4])
                hold = self._holds.get(r)
                if hold is not None:
                    if now >= hold:
                        self._holds.pop(r, None)
                    elif new is RankState.DEAD:
                        # verdicts held (mid-boot): a replica building
                        # its engine legitimately goes silent longer
                        # than dead_after_s — DEAD here would be
                        # terminal for a rank that is about to come up.
                        # Cap at SUSPECT; the first post-boot beat
                        # clears it, and the hold expires with the
                        # boot deadline for a rank that never does.
                        new = RankState.SUSPECT
                if r in self._quarantined and new is not RankState.DEAD:
                    # externally quarantined (SDC digest vote): pinned
                    # at SUSPECT — a fresh heartbeat must NOT clear it
                    # (the host is alive; its math is not trusted).
                    # Silence still escalates SUSPECT -> DEAD above.
                    new = RankState.SUSPECT
                if new is not old:
                    self._states[r] = new
                    self.transitions.append((r, old, new, age))
                    events.append((r, old, new, age))
                    if new is RankState.DEAD:
                        newly_dead.append(r)
                elif r not in self._states:
                    self._states[r] = new
                gauge_updates.append((r, self._states[r], age))
            states = {r: self._states[r] for r in wv.members}
        # telemetry strictly OUTSIDE the monitor lock: is_dead() sits
        # on every blocked collective's abort path, and the registry
        # takes its own lock (a contended scrape must not freeze the
        # dead-verdict machinery) — same discipline as _record/on_dead
        for upd in gauge_updates:
            self._set_gauges(*upd)
        for evt in events:
            self._record(*evt)
        if newly_dead and self.on_dead is not None:
            try:
                self.on_dead(sorted(newly_dead))
            except Exception:
                pass
        return states

    def _classify(self, old, age, progress_age):
        if old is RankState.DEAD:
            return old
        if age > self._config.dead_after_s and old is RankState.SUSPECT:
            return RankState.DEAD
        if age > self._config.suspect_after_s:
            return RankState.SUSPECT
        pt = self._config.progress_timeout_s
        if pt is not None and progress_age > pt:
            return RankState.SUSPECT
        return RankState.HEALTHY

    def states(self):
        with self._lock:
            return dict(self._states)

    def dead_ranks(self):
        with self._lock:
            return sorted(r for r, s in self._states.items()
                          if s is RankState.DEAD)

    def is_dead(self, rank):
        with self._lock:
            return self._states.get(rank) is RankState.DEAD

    def telemetry(self, rank):
        """Latest beat payload's ``telemetry`` dict for `rank` (the
        serving fleet publishes queue depth / page occupancy / health
        state per beat), or None when the rank has never beaten or
        beats carry no telemetry."""
        with self._lock:
            b = self._payloads.get(int(rank))
        if b is None:
            return None
        t = b.get("telemetry")
        return dict(t) if isinstance(t, dict) else None

    # ---- external quarantine (SDC digest vote) ----
    def mark_suspect(self, rank, reason=None):
        """Quarantine `rank` at SUSPECT on EXTERNAL evidence (the
        sentinel's cross-rank digest vote names an SDC-suspect whose
        heartbeats are perfectly healthy).  Sticky: heartbeat recovery
        does not clear it — only :meth:`clear_suspect` or the terminal
        DEAD verdict supersedes.  The caller decides the next move
        (typically :func:`reconfigure` excluding the suspect)."""
        rank = int(rank)
        evt = None
        with self._lock:
            self._quarantined[rank] = str(reason or "quarantined")
            old = self._states.get(rank, RankState.HEALTHY)
            if old not in (RankState.SUSPECT, RankState.DEAD):
                self._states[rank] = RankState.SUSPECT
                evt = (rank, old, RankState.SUSPECT, 0.0)
                self.transitions.append(evt)
        if evt is not None:
            # telemetry outside the monitor lock (poll() discipline)
            self._set_gauges(rank, RankState.SUSPECT, 0.0)
            self._record(*evt)
        return RankState.SUSPECT

    def clear_suspect(self, rank):
        """Lift an external quarantine; the rank's state recovers
        through the ordinary heartbeat classification at the next
        poll (DEAD stays terminal)."""
        with self._lock:
            self._quarantined.pop(int(rank), None)

    def hold_verdict(self, rank, for_s):
        """Suspend DEAD escalation for `rank` for `for_s` seconds —
        the boot-phase grace.  A replica building its engine (AOT
        cache load, first compile) can legitimately starve its beat
        publisher longer than ``dead_after_s``, and DEAD is terminal:
        one spurious verdict during a slow boot would permanently
        evict a rank that is seconds from coming up.  The caller's
        boot deadline (``rendezvous_timeout_s``) bounds the hold, so
        a rank that never boots still dies on schedule."""
        deadline = self._time() + float(for_s)
        with self._lock:
            self._holds[int(rank)] = deadline

    def release_verdict_hold(self, rank):
        """End a boot-phase hold and restart the rank's staleness
        clock: the held window's silence was sanctioned, so the first
        post-boot beat must not race ``dead_after_s`` worth of
        leftover age."""
        rank = int(rank)
        now = self._time()
        with self._lock:
            self._holds.pop(rank, None)
            seen = self._seen.get(rank)
            if seen is not None:
                self._seen[rank] = (seen[0], seen[1], seen[2], now, now)

    def suspect_ranks(self):
        with self._lock:
            return sorted(r for r, s in self._states.items()
                          if s is RankState.SUSPECT)

    def quarantined_ranks(self):
        with self._lock:
            return sorted(self._quarantined)

    # ---- telemetry ----
    def _set_gauges(self, rank, state, age):
        try:
            from paddle_tpu import observability as obs
            reg = obs.registry()
            reg.gauge("fleet_rank_state", labels={"rank": str(rank)},
                      help="fleet watchdog verdict per rank "
                           "(0=HEALTHY 1=SUSPECT 2=DEAD)"
                      ).set(int(state))
            reg.gauge("fleet_last_heartbeat_age_s",
                      labels={"rank": str(rank)},
                      help="seconds since this rank's heartbeat seq "
                           "last advanced").set(round(max(0.0, age), 3))
        except Exception:
            pass

    def _record(self, rank, old, new, age):
        try:
            from paddle_tpu import observability as obs
            with obs.span("resilience.fleet.transition", rank=rank,
                          from_state=old.name, to_state=new.name,
                          age_s=round(age, 3)):
                pass
            obs.registry().counter(
                "fleet_rank_transitions_total",
                labels={"to": new.name},
                help="fleet watchdog state transitions").inc()
        except Exception:
            pass

    # ---- optional thread ----
    def start(self):
        if self._thread is None and self._client is not None:
            self._stop.clear()       # restartable after stop()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="paddle_tpu-fleet-watchdog")
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self._poll_interval):
            try:
                self.poll()
            except Exception:
                # the watchdog must outlive transient coordinator
                # errors; a persistently failing poll shows up as
                # frozen gauges, not a dead thread
                pass

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# ------------------------------------------- distributed checkpointing
# one source of truth for the manifest filename: the single-process
# checkpointer's module (whose read/write helpers this class reuses)
from paddle_tpu.resilience.checkpoint import _MANIFEST  # noqa: E402

_FLEET_FORMAT = "fleet-1"


class DistributedCheckpointer:
    """Sharded, quorum-committed, reshardable checkpoints.

    ``save(step, sharded=..., replicated=...)`` is collective:

    1. every fleet rank writes its OWN shard
       (``step-<step>/shard-<rank>-of-<size>.pkl``) through
       ``framework.io.write_atomic`` (the ``io.save`` fault site — torn
       shards are injectable and detectable) and publishes the shard's
       sha256 through the coordination KV;
    2. fleet rank 0 gathers every digest (timeout-bounded — a dead
       rank fails the save with :class:`CollectiveTimeout` instead of
       wedging it), then commits the quorum MANIFEST entry recording
       step, world size, mesh spec, and one ``{rank, file, bytes,
       sha256}`` row per shard — the manifest can under-promise but
       never over-promise (PR 6 invariant);
    3. every other rank blocks (timeout-bounded) on the commit marker,
       so a returned ``save()`` means globally durable.

    ``sharded`` leaves are arrays whose axis 0 is the dp axis: shard r
    holds its slice.  ``load(world_size=W)`` re-stacks every verified
    shard along axis 0 and re-splits into W equal parts — resuming at a
    SHRUNK (or grown) world size after a reconfigure.  ``replicated``
    state (identical on every rank — params, optimizer moments) is
    stored once, in rank 0's shard.  An entry restores only if EVERY
    shard verifies; a torn shard fails the whole entry and ``load()``
    falls back to the previous one (recorded recovery, last-good
    contract).
    """

    def __init__(self, directory, keep=3, client=None, world=None,
                 timeout_s=None, mesh_spec=None):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = int(keep)
        self._client = client if client is not None else _client()
        self._world = world            # None -> fleet.world() per call
        self._timeout_s = timeout_s
        self.mesh_spec = mesh_spec
        self._lock = threading.Lock()
        # per-instance save round: SPMD call order is identical on
        # every rank (the _COORD_ROUND assumption), so the same round
        # id names the same collective save fleet-wide — and versions
        # the digest/commit keys, so re-saving the SAME step can never
        # race against a previous save's leftover markers
        self._save_round = 0

    def _wv(self):
        return self._world if self._world is not None else world()

    def _timeout(self):
        return (self._timeout_s if self._timeout_s is not None
                else get_config().collective_timeout_s)

    # ------------------------------------------------------------ save
    def _shard_file(self, step, rank, size):
        return os.path.join(f"step-{int(step):08d}",
                            f"shard-{rank:05d}-of-{size:05d}.pkl")

    def save(self, step, sharded=None, replicated=None):
        """Collective checkpoint at `step`; returns the manifest path
        once the quorum entry is durably committed fleet-wide."""
        from paddle_tpu import observability as obs
        from paddle_tpu.framework import io as fio
        wv = self._wv()
        t0 = time.perf_counter()
        payload = {"rank": wv.rank, "world_size": wv.size,
                   "sharded": fio._to_saveable(sharded)}
        if wv.rank == 0:
            payload["replicated"] = fio._to_saveable(replicated)
        data = pickle.dumps(payload, protocol=4)
        rel = self._shard_file(step, wv.rank, wv.size)
        path = os.path.join(self.directory, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fio.write_atomic(path, data)
        from paddle_tpu.resilience.checkpoint import digest_bytes
        entry = {"rank": wv.rank, "file": rel, "bytes": len(data),
                 "sha256": digest_bytes(data)}
        if wv.size == 1:
            self._commit(step, wv, [entry])
        elif self._client is None:
            raise RuntimeError(
                "distributed save at world size > 1 needs a "
                "coordination client (jax.distributed or a shared "
                "LocalKVClient) — without one, each rank would race "
                "its own single-shard manifest commit")
        else:
            self._save_round += 1
            base = (f"{coord_namespace()}/fleet/ckpt/"
                    f"r{self._save_round}/{int(step)}")
            kv_set_bytes(self._client, f"{base}/{wv.rank}",
                         json.dumps(entry).encode())
            if wv.rank == 0:
                shards = [entry]
                # ONE shared deadline across the whole gather (several
                # dead peers must not stack per-peer budgets) and the
                # watchdog's DEAD verdict aborts a doomed wait in
                # seconds — the _coord_get/finalize discipline
                mon = get_monitor()
                gather_deadline = time.monotonic() + self._timeout()
                for peer in range(1, wv.size):
                    g = wv.members[peer]
                    raw = kv_get_bytes(
                        self._client, f"{base}/{peer}",
                        max(0.001,
                            gather_deadline - time.monotonic()),
                        site="fleet.kv_get", missing_rank=g,
                        abort_if=(None if mon is None
                                  else lambda g=g: mon.is_dead(g)))
                    shards.append(json.loads(raw.decode()))
                # every peer has published a round-r digest, which it
                # only does AFTER finishing round r-1 (it read r-1's
                # commit marker) — so round r-1's keys are provably
                # consumed and reaping them bounds coordinator growth
                # to one round per checkpointer
                if self._save_round > 1:
                    try:
                        self._client.key_value_delete(
                            f"{coord_namespace()}/fleet/ckpt/"
                            f"r{self._save_round - 1}")
                    except Exception:
                        pass
                self._commit(step, wv, shards)
                kv_set_bytes(self._client, f"{base}/committed", b"ok")
            else:
                kv_get_bytes(
                    self._client, f"{base}/committed",
                    self._timeout(), site="fleet.kv_get",
                    missing_rank=wv.members[0])
        obs.registry().counter(
            "fleet_checkpoint_saves_total",
            help="distributed checkpoint save() calls").inc()
        with obs.span("resilience.fleet.ckpt.save", step=int(step),
                      rank=wv.rank, world_size=wv.size,
                      bytes=len(data),
                      save_ms=round((time.perf_counter() - t0) * 1e3,
                                    3)):
            pass
        return os.path.join(self.directory, _MANIFEST)

    def _commit(self, step, wv, shards):
        """Rank 0 only: quorum manifest entry + retention, one atomic
        rewrite (the PR 6 ``_commit`` shape — prune folded into the
        same write, payload dirs deleted after)."""
        from paddle_tpu.resilience.checkpoint import (read_manifest,
                                                      write_manifest)
        with self._lock:
            manifest = read_manifest(self.directory,
                                     fmt=_FLEET_FORMAT)
            ckpts = [c for c in manifest.get("checkpoints", ())
                     if c["step"] != int(step)]
            ckpts.append({
                "step": int(step),
                "world_size": wv.size,
                "members": list(wv.members),
                "generation": wv.generation,
                "mesh": self.mesh_spec,
                "shards": sorted(shards, key=lambda s: s["rank"]),
                "time_utc": time.strftime("%Y-%m-%d %H:%M:%S UTC",
                                          time.gmtime()),
            })
            ckpts.sort(key=lambda c: c["step"])
            drop, ckpts = ckpts[:-self.keep], ckpts[-self.keep:]
            write_manifest(self.directory,
                           {"format": _FLEET_FORMAT,
                            "checkpoints": ckpts})
            for c in drop:
                d = os.path.join(self.directory,
                                 f"step-{int(c['step']):08d}")
                for s in c.get("shards", ()):
                    try:
                        os.remove(os.path.join(self.directory,
                                               s["file"]))
                    except OSError:
                        pass
                try:
                    os.rmdir(d)
                except OSError:
                    pass

    # ------------------------------------------------------------ load
    def steps(self):
        from paddle_tpu.resilience.checkpoint import read_manifest
        man = read_manifest(self.directory, fmt=_FLEET_FORMAT)
        return [c["step"] for c in man["checkpoints"]]

    def _verify_entry(self, entry):
        """All-or-nothing: the shard list must cover every rank of the
        recorded world size and every shard must exist with its
        manifested digest; returns {rank: payload_bytes} or None (an
        incomplete entry — e.g. corrupted manifest debris — is
        unverified, feeding the last-good fallback, never a crash)."""
        from paddle_tpu.resilience.checkpoint import digest_bytes
        shards = entry.get("shards")
        world_size = entry.get("world_size")
        if not isinstance(shards, list) or world_size is None:
            # not a fleet entry at all (e.g. a single-process format-1
            # manifest sharing the directory): unverified, not a crash
            return None
        out = {}
        try:
            if sorted(s["rank"] for s in shards) != \
                    list(range(world_size)):
                return None
            for s in shards:
                path = os.path.join(self.directory, s["file"])
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except OSError:
                    return None
                if (len(data) != s["bytes"]
                        or digest_bytes(data) != s["sha256"]):
                    return None
                out[s["rank"]] = data
        except (KeyError, TypeError):
            # truncated-but-valid-JSON debris (shard rows missing
            # fields): exactly the torn state the last-good fallback
            # exists for — unverified, never a crash out of load()
            return None
        return out

    def load(self, step=None, world_size=None, rank=None,
             strict=False):
        """Restore the newest fully-verified entry (or exactly `step`)
        resharded for ``world_size`` (default: the current fleet
        world).  Returns ``(step, {"sharded": ..., "replicated": ...,
        "world_size": saved_ws})`` or None.  A torn shard fails its
        whole entry and falls back to the previous one, recorded as a
        recovery.

        Cost note: the all-or-nothing quorum contract makes every rank
        read (and digest-verify) every shard of the entry it restores —
        W-fold read amplification on the recovery path.  Acceptable at
        current state sizes; a future per-shard leaf-metadata sidecar
        could keep verification whole-entry while unpickling only the
        shards whose dp rows the new rank actually needs."""
        from paddle_tpu import observability as obs
        from paddle_tpu.resilience.checkpoint import read_manifest
        wv = self._wv()
        world_size = int(world_size) if world_size is not None \
            else wv.size
        rank = int(rank) if rank is not None else wv.rank
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} outside world {world_size}")
        entries = read_manifest(self.directory,
                                fmt=_FLEET_FORMAT)["checkpoints"]
        if step is not None:
            entries = [c for c in entries if c["step"] == int(step)]
        skipped = 0
        for entry in reversed(entries):
            blobs = self._verify_entry(entry)
            if blobs is None:
                skipped += 1
                obs.registry().counter(
                    "fleet_checkpoint_corrupt_total",
                    help="distributed checkpoint entries that failed "
                         "shard digest verification").inc()
                continue
            if skipped:
                faultinject.note_recovery(
                    "io.save", "torn_write",
                    fallback_step=entry["step"], skipped=skipped,
                    distributed=True)
            payloads = {r: pickle.loads(b) for r, b in blobs.items()}
            sharded = self._reshard(entry, payloads, world_size, rank)
            state = {"sharded": sharded,
                     "replicated": payloads[0].get("replicated"),
                     "world_size": entry["world_size"]}
            with obs.span("resilience.fleet.ckpt.load",
                          step=entry["step"], skipped=skipped,
                          saved_world=entry["world_size"],
                          world_size=world_size):
                return entry["step"], state
        if strict and entries:
            from paddle_tpu.resilience.checkpoint import \
                CheckpointCorruption
            raise CheckpointCorruption(
                f"all {len(entries)} distributed manifest entries "
                f"under {self.directory} failed verification")
        return None

    @staticmethod
    def _reshard(entry, payloads, world_size, rank):
        """Re-split the dp axis: stack every saved shard's leaves along
        axis 0 (saved rank order), then slice the new rank's equal
        chunk.  Leaf structure must match across shards (same save()
        call produced them)."""
        import jax
        import numpy as np
        shards = [payloads[r]["sharded"]
                  for r in range(entry["world_size"])]
        if shards[0] is None:
            return None

        def merge(*leaves):
            if not all(isinstance(v, np.ndarray) for v in leaves):
                if len(set(map(repr, leaves))) == 1:
                    return leaves[0]     # identical non-array leaf
                raise TypeError(
                    "sharded checkpoint leaves must be arrays (dp axis "
                    f"0); got {[type(v).__name__ for v in leaves]}")
            total = np.concatenate(leaves, axis=0)
            if total.shape[0] % world_size:
                raise ValueError(
                    f"cannot reshard axis-0 length {total.shape[0]} "
                    f"into {world_size} equal parts")
            per = total.shape[0] // world_size
            return total[rank * per:(rank + 1) * per]

        return jax.tree_util.tree_map(merge, *shards)


# --------------------------------------------- elastic reconfiguration
def reconfigure(dead_ranks, client=None, config=None, world_view=None,
                install=True, reap=None):
    """Re-form the fleet without the DEAD ranks: bump the generation,
    re-rendezvous the survivors under the fresh key namespace
    (timeout-bounded join barrier — a survivor that fails to appear
    raises :class:`CollectiveTimeout` naming it), reap the previous
    generation's keys, and install the shrunk :class:`WorldView` (new
    contiguous fleet ranks = survivor order).  Returns the new view;
    the caller then reloads the last-good distributed checkpoint at
    the new world size and resumes.

    ``world_view``/``install=False`` support rank-per-thread tests and
    the bench lane, where a process-global world would be shared.
    ``reap`` (default: same as ``install``) controls the old-generation
    key sweep — with ``install=False`` the process-global namespace is
    STILL the old generation (shared publishers/monitors/in-flight
    saves keep using it), so only the caller who owns the global world
    may safely delete it."""
    from paddle_tpu import observability as obs
    config = config or get_config()
    client = client if client is not None else _client()
    old = world_view if world_view is not None else world()
    dead = {int(r) for r in dead_ranks}
    if old.global_rank in dead:
        raise ValueError(
            f"this rank ({old.global_rank}) is in the dead set {dead}")
    survivors = [m for m in old.members if m not in dead]
    if not set(dead) & set(old.members):
        raise ValueError(f"dead ranks {sorted(dead)} not in world "
                         f"{old.members}")
    t0 = time.perf_counter()
    new = WorldView(survivors, old.global_rank,
                    generation=old.generation + 1,
                    launch_id=old.launch_id)
    ns = new.namespace
    if client is not None and new.size > 1:
        # each survivor's join marker carries its PROPOSED member list:
        # divergent watchdog verdicts (rank A holds {2,3} dead, rank B
        # only {3}) would otherwise let two different worlds install at
        # the same generation and silently desynchronize every later
        # collective — a loud mismatch error here converts split-brain
        # into a restartable failure
        proposal = json.dumps(list(new.members)).encode()
        kv_set_bytes(client, f"{ns}/fleet/join/{old.global_rank}",
                     proposal)
        # one shared join deadline (not per-survivor — deaths DURING
        # the reconfigure must not stack budgets), with the watchdog's
        # DEAD verdict aborting a doomed wait early
        mon = get_monitor()
        join_deadline = time.monotonic() + config.rendezvous_timeout_s
        for peer in survivors:
            if peer == old.global_rank:
                continue
            raw = kv_get_bytes(client, f"{ns}/fleet/join/{peer}",
                               max(0.001,
                                   join_deadline - time.monotonic()),
                               site="fleet.kv_get", missing_rank=peer,
                               abort_if=(None if mon is None
                                         else (lambda p=peer:
                                               mon.is_dead(p))),
                               config=config)
            theirs = json.loads(raw.decode())
            if tuple(theirs) != new.members:
                raise RuntimeError(
                    f"fleet reconfigure split-brain: rank "
                    f"{old.global_rank} proposes members "
                    f"{list(new.members)} but rank {peer} proposes "
                    f"{theirs} (divergent DEAD verdicts) — refusing "
                    f"to install generation {new.generation}; "
                    f"restart the job")
    if install:
        _set_world(new)
        # fresh namespace -> fresh round counters for the eager
        # coordination collectives
        from paddle_tpu.distributed import collective
        collective.reset_coord_rounds()
    reap = install if reap is None else reap
    if reap and client is not None and new.rank == 0:
        try:
            client.key_value_delete(old.namespace)
        except Exception:
            pass
    elapsed_ms = round((time.perf_counter() - t0) * 1e3, 3)
    try:
        reg = obs.registry()
        reg.gauge("fleet_world_size",
                  help="current fleet world size").set(new.size)
        reg.gauge("fleet_generation",
                  help="fleet reconfiguration generation").set(
                      new.generation)
        reg.counter("fleet_reconfigures_total",
                    help="elastic fleet reconfigurations").inc()
        with obs.span("resilience.fleet.reconfigure",
                      dead=sorted(dead), world_size=new.size,
                      generation=new.generation,
                      reconfigure_ms=elapsed_ms):
            pass
    except Exception:
        pass
    return new


def _reset_for_tests():
    """Test hook: drop installed world/publisher/monitor/launch id."""
    global _world, _publisher, _monitor
    with _world_lock:
        _world = None
        _publisher = None
        _monitor = None
        _launch_id[0] = None
        _finalized[0] = False
