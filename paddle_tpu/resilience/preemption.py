"""Preemption: drain, checkpoint, exit — instead of dying mid-step.

TPU hosts get evicted (spot/preemptible VMs, maintenance events) with a
signal and a short grace window.  The handler turns that into a
cooperative protocol:

1. the signal (default SIGTERM) only sets a flag — signal context does
   no real work;
2. the training loop polls :meth:`PreemptionHandler.check` once per
   step; at the first step boundary after the signal it drains: flush
   async checkpoint writes, save a final checkpoint, and (optionally)
   exit with a distinct code the launcher maps to "restart me";
3. the drain beats the elastic watchdog (``distributed.elastic``)
   before and after the checkpoint write, so a slow final save is not
   misdiagnosed as a stall and killed halfway through — this is the
   heartbeats-and-restarts composition contract.

The fault-injection kind ``preempt`` calls :func:`request_preemption`
on the installed handler, so chaos plans exercise exactly the
production path minus the actual signal delivery.
"""
from __future__ import annotations

import os
import signal as _signal
import sys
import threading
import time

__all__ = ["PreemptionHandler", "request_preemption", "install",
           "get_handler", "uninstall"]

_active_handler = None
_lock = threading.Lock()


def install(handler):
    """Make `handler` the process-wide preemption target (what the
    ``preempt`` fault kind and external callers hit)."""
    global _active_handler
    with _lock:
        _active_handler = handler
    return handler


def get_handler():
    return _active_handler


def uninstall(handler=None):
    """Clear the process-wide handler (only if it is `handler`, when
    given) — the counterpart :class:`PreemptionHandler.__exit__` and
    ``ElasticManager.stop`` use so a stopped loop's handler cannot
    swallow later preemption requests."""
    global _active_handler
    with _lock:
        if handler is not None and _active_handler is not handler:
            return
        _active_handler = None


def request_preemption(reason="external"):
    """Flag the installed handler (no-op without one, so fault plans
    with ``preempt`` faults are harmless in loops that opted out)."""
    h = _active_handler
    if h is not None:
        h.request(reason)
    return h is not None


class PreemptionHandler:
    """Cooperative drain-and-checkpoint on preemption.

    Usage::

        ckpt = Checkpointer("run/ckpt", async_save=True)
        with PreemptionHandler(checkpointer=ckpt) as pre:
            start, _ = auto_resume(ckpt, model, opt)
            for step in range(start, steps):
                train_step(...)
                if pre.check(step, lambda: {"step": step,
                                            "model": model.state_dict(),
                                            "optimizer": opt.state_dict()}):
                    break                    # drained + checkpointed

    `exit_code` non-None additionally ``os._exit``\\ s after the drain
    (the launcher restarts the job; 44 is distinct from the watchdog's
    43).  Tests and library code leave it None and observe the bool.
    """

    def __init__(self, checkpointer=None, signals=None,
                 exit_code=None, auto_install=True):
        self.checkpointer = checkpointer
        self.exit_code = exit_code
        self._flag = threading.Event()
        self.reason = None
        self.drained = False
        self.drain_step = None
        self._notice_pending = False
        self._notice_lock = threading.Lock()
        self._prev = {}
        self._signals = tuple(signals) if signals is not None \
            else (_signal.SIGTERM,)
        if auto_install:
            install(self)

    # ---- signal / request plumbing ----
    def install_signal_handlers(self):
        """Bind the OS signals (main thread only — callers running in
        worker threads use :func:`request_preemption` instead)."""
        for sig in self._signals:
            self._prev[sig] = _signal.signal(sig, self._on_signal)
        return self

    def uninstall_signal_handlers(self):
        for sig, prev in self._prev.items():
            try:
                _signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()

    def _on_signal(self, signum, frame):
        # Signal context does NOTHING but set the flag.  A Python
        # signal handler runs between bytecodes of whatever the main
        # thread was doing — buffered-stderr writes there can deadlock
        # on the io lock the interrupted code may hold, so the operator
        # notice is deferred to the next check() poll.
        if not self._flag.is_set():
            self.reason = f"signal:{_signal.Signals(signum).name}"
            self._notice_pending = True
            self._flag.set()

    def request(self, reason="external"):
        if not self._flag.is_set():
            # same publish order as _on_signal: reason and the pending
            # notice must be visible BEFORE the flag — a concurrently
            # polling check() may drain (and exit) the moment the flag
            # is up, and must find the notice to flush
            self.reason = reason
            self._notice_pending = True
            self._flag.set()
            self._flush_notice()

    def _flush_notice(self):
        """Emit the queued operator notice exactly once (called from
        ordinary thread context only, never from the signal handler —
        which is why the handler sets the flag lock-free while this
        side test-and-clears under a lock)."""
        with self._notice_lock:
            pending, self._notice_pending = self._notice_pending, False
        if pending:
            print(f"[paddle_tpu.resilience] preemption requested "
                  f"({self.reason}); will drain at the next step "
                  f"boundary", file=sys.stderr, flush=True)

    @property
    def preempted(self):
        return self._flag.is_set()

    # ---- the step-boundary poll ----
    def check(self, step, state_fn=None):
        """Call once per training step.  Returns False on the hot path;
        on a pending preemption it drains (checkpoint via `state_fn` or
        the checkpointer's queued writes), records telemetry, optionally
        exits, and returns True — the loop should break."""
        if not self._flag.is_set():
            return False
        self._flush_notice()    # notice deferred from signal context
        self.drain(step, state_fn)
        if self.exit_code is not None:
            os._exit(self.exit_code)
        return True

    def drain(self, step, state_fn=None):
        from paddle_tpu import observability as obs
        from paddle_tpu.distributed import elastic
        t0 = time.perf_counter()
        # heartbeat AROUND the save: the final checkpoint of a big model
        # can exceed the watchdog window; a drain is progress, not a stall
        elastic.notify_progress()
        if self.checkpointer is not None and state_fn is not None:
            self.checkpointer.save(step, state_fn())
        if self.checkpointer is not None:
            self.checkpointer.wait()
        elastic.notify_progress()
        self.drained = True
        self.drain_step = step
        obs.registry().counter(
            "resilience_preemptions_total",
            help="preemption drains completed").inc()
        with obs.span("resilience.preempt.drain", step=step,
                      reason=self.reason or "",
                      drain_ms=round((time.perf_counter() - t0) * 1e3,
                                     3)):
            pass

    def reset(self):
        """Re-arm after a handled preemption (tests, multi-run loops)."""
        self._flag.clear()
        self.reason = None
        self.drained = False
        self.drain_step = None
        self._notice_pending = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.uninstall_signal_handlers()
        uninstall(self)
        return False
