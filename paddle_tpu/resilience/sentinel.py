"""Training sentinel — in-trace anomaly detection, skip/rollback
policy, and SDC localization.

PR 14 made a dead rank a bounded, recoverable event; this module closes
the remaining unguarded failure class: corrupted math.  NaN/Inf
gradients, loss spikes, and silent data corruption (SDC) survive every
other gate because they are *values*, not crashes — nothing throws, the
step commits, and the poison spreads through the next gradient sync or
the shared KV pool.

Four pieces (docs/resilience.md "Numerics sentinel" has the protocol
tables):

- **Fused anomaly probes** — ``to_static(guard=True)`` and
  ``Optimizer(guard=True)`` compute a per-step scalar summary (loss
  value + finite flag, global gradient sum-of-squares, non-finite
  region count) INSIDE the already-compiled train program.  Detection
  therefore costs zero extra compiles (the probe is part of the one
  traced program — provable from the observability recompile log) and
  <2% cost-model bytes on the optimized gpt target (the fused Adam
  kernel folds the gradient reduction into the pass that already holds
  g in registers; perfgate's ``sentinel`` target pins it).  The
  optimizer half also GATES: a parameter region whose gradient is
  non-finite commits a zero update (params, moments, and bias-
  correction powers hold) — the GradScaler-shaped skip, traced once,
  selected per step by data.

- **Policy machine** (:class:`TrainingSentinel`) — the PR 6 health-
  machine shape over the probe stream: an anomaly becomes a machine-
  readable :class:`AnomalyDetected` (step, kind, site), the step counts
  as SKIPPED (the in-trace gate already committed the zero update), and
  ``skip_limit`` consecutive anomalies trigger an automatic ROLLBACK to
  the last good :class:`~paddle_tpu.resilience.checkpoint.Checkpointer`
  entry with an LR cooldown.  Instrumented: ``sentinel_anomaly_total``
  ``{kind,site}``, ``sentinel_last_good_step``, ``resilience.sentinel``
  spans.

- **Localization** — :class:`BatchLineage` records the (step, seed,
  microbatch) lineage; :func:`replay_bisect` binary-searches a
  deterministic replay predicate over the window since the last good
  checkpoint to name the poison batch in ``O(log n)`` replays.

- **Cross-rank digest vote** (:func:`digest_vote`) — each rank
  publishes a :func:`tree_digest` of its local copy of REPLICATED
  state (post-sync gradients, or the updated parameter replicas)
  through the PR 14 timeout-bounded KV machinery; a STRICT-majority
  digest names dissenting ranks as SDC suspects (no strict majority =
  inconclusive, never a coin-flip quarantine), fed to
  ``FleetMonitor.mark_suspect`` (quarantine) and from there to
  :func:`~paddle_tpu.resilience.fleet.reconfigure` (evict + elastic
  resume).  Replicated dp state is bit-identical across ranks by
  construction (same synced grads, same update math), so any
  divergence is hardware- or host-local corruption by definition —
  pre-sync LOCAL grads legitimately differ per rank and must not be
  voted.

Threading: :class:`TrainingSentinel` takes its lock only around state
transitions; telemetry, the ``on_anomaly`` callback, and the rollback
restore run OUTSIDE it (the PR 7 health-callback lesson — a callback
feeding back into ``observe()`` must not deadlock).
"""
from __future__ import annotations

import enum
import hashlib
import threading
import time
from collections import OrderedDict, deque

import numpy as np

__all__ = [
    "AnomalyDetected",
    "BatchLineage",
    "DigestVote",
    "GuardSummary",
    "SentinelAction",
    "TrainingSentinel",
    "digest_vote",
    "install",
    "current",
    "localize_poison",
    "note_anomaly",
    "replay_bisect",
    "tree_digest",
    "uninstall",
]

# anomaly kinds the policy machine classifies
KINDS = ("nan_loss", "nan_grad", "grad_norm", "loss_spike",
         "nan_logits", "scale_overflow")


class AnomalyDetected(RuntimeError):
    """Machine-readable anomaly event: WHEN (step), WHAT (kind), WHERE
    (site), plus kind-specific context.  Recorded on
    ``TrainingSentinel.anomalies``; raised only where a caller opts in
    (the sentinel's default policy is skip/rollback, not crash)."""

    def __init__(self, step, kind, site="train", **ctx):
        self.step = int(step) if step is not None else None
        self.kind = str(kind)
        self.site = str(site)
        self.ctx = dict(ctx)
        super().__init__(
            f"anomaly at step {self.step}: kind={self.kind} "
            f"site={self.site}"
            + (f" {self.ctx}" if self.ctx else ""))

    def to_dict(self):
        return {"step": self.step, "kind": self.kind, "site": self.site,
                **self.ctx}


def note_anomaly(kind, site, step=None, **ctx):
    """THE telemetry choke point for anomalies — every detector
    (training sentinel, serving guard) records through here so
    ``sentinel_anomaly_total{kind,site}`` and the
    ``resilience.sentinel.anomaly`` span stream are complete no matter
    who detected.  Returns the :class:`AnomalyDetected` record."""
    evt = AnomalyDetected(step, kind, site, **ctx)
    try:
        from paddle_tpu import observability as obs
        with obs.span("resilience.sentinel.anomaly", step=evt.step,
                      kind=evt.kind, site=evt.site):
            pass
        obs.registry().counter(
            "sentinel_anomaly_total",
            labels={"kind": evt.kind, "site": evt.site},
            help="anomalies detected by the training sentinel").inc()
    except Exception:
        pass
    return evt


# ------------------------------------------------------------- summary
class GuardSummary:
    """Parsed ``Optimizer(guard=True)`` probe: one (4,) f32 state-tensor
    row per step — ``[good, grad_sumsq, bad_regions, regions]``.

    ``good`` is the GLOBAL verdict (1.0 iff every gradient region was
    finite); ``grad_sumsq`` is the f32-accumulated global sum of squared
    gradients (its sqrt is the global grad-norm; a non-finite value IS
    the anomaly signal — an overflowing norm is anomaly-worthy even
    when every element is finite); ``bad_regions``/``regions`` count the
    gated update regions (whole parameters on the unfused path, kernel
    row-blocks on the fused path) that committed a zero update.
    """

    __slots__ = ("good", "grad_sumsq", "bad_regions", "regions")

    def __init__(self, good, grad_sumsq, bad_regions, regions):
        self.good = bool(good)
        self.grad_sumsq = float(grad_sumsq)
        self.bad_regions = int(bad_regions)
        self.regions = int(regions)

    @classmethod
    def from_array(cls, arr):
        a = np.asarray(arr, np.float64).reshape(-1)
        if a.size < 4:
            raise ValueError(f"guard summary needs 4 slots, got {a.size}")
        return cls(a[0] >= 0.5, a[1], int(a[2]), int(a[3]))

    @property
    def grad_norm(self):
        return float(np.sqrt(self.grad_sumsq)) \
            if np.isfinite(self.grad_sumsq) and self.grad_sumsq >= 0 \
            else float(self.grad_sumsq)

    def to_dict(self):
        return {"good": self.good, "grad_sumsq": self.grad_sumsq,
                "bad_regions": self.bad_regions, "regions": self.regions}

    def __repr__(self):
        return (f"GuardSummary(good={self.good}, "
                f"grad_sumsq={self.grad_sumsq:.6g}, "
                f"bad_regions={self.bad_regions}/{self.regions})")


class SentinelAction(enum.IntEnum):
    OK = 0
    SKIP = 1
    ROLLBACK = 2


# ------------------------------------------------------ policy machine
class TrainingSentinel:
    """The skip/rollback policy machine over the in-trace probe stream.

    ``observe(step, loss=..., summary=...)`` classifies the step:

    - non-finite loss                      → ``nan_loss``
    - summary verdict bad (gated regions)  → ``nan_grad``
    - ``grad_norm_limit`` exceeded         → ``grad_norm``
    - finite loss > ``spike_factor`` × the rolling median of the last
      ``spike_window`` clean losses        → ``loss_spike``

    Any anomaly returns :attr:`SentinelAction.SKIP` (the in-trace
    optimizer gate already committed the zero update for NaN/Inf
    gradients; spikes are post-commit observations whose remedy is the
    rollback below).  ``skip_limit`` CONSECUTIVE anomalies trigger
    :meth:`rollback`: restore model+optimizer from the
    ``last_good_step``-anchored ``Checkpointer`` entry (newest good as
    the fallback), multiply the LR by ``lr_cooldown``, and
    return :attr:`SentinelAction.ROLLBACK` — the caller rewinds its
    data iterator to :attr:`resume_step`.  Because the fault-injection
    lineage is deterministic, a transient fault's rollback-resume
    trajectory EXACTLY matches the fault-free run (the chaos acceptance
    proof in tests/test_sentinel.py).

    ``note_checkpoint(step)`` marks a landed checkpoint as the rollback
    anchor — call it only for steps the sentinel saw clean.
    """

    def __init__(self, checkpointer=None, model=None, optimizer=None,
                 skip_limit=3, lr_cooldown=0.5, spike_factor=None,
                 spike_window=8, grad_norm_limit=None, on_anomaly=None,
                 auto_rollback=True):
        if skip_limit < 1:
            raise ValueError("skip_limit must be >= 1")
        self.checkpointer = checkpointer
        self.model = model
        self.optimizer = optimizer
        self.skip_limit = int(skip_limit)
        self.lr_cooldown = float(lr_cooldown)
        self.spike_factor = (float(spike_factor)
                             if spike_factor is not None else None)
        self.spike_window = int(spike_window)
        self.grad_norm_limit = (float(grad_norm_limit)
                                if grad_norm_limit is not None else None)
        self.on_anomaly = on_anomaly
        self.auto_rollback = bool(auto_rollback)
        self._lock = threading.Lock()
        self.anomalies = []          # [AnomalyDetected]
        self.skip_streak = 0
        self.skips_total = 0
        self.rollbacks = 0
        self.last_good_step = None   # newest clean-step checkpoint
        self.resume_step = None      # set by rollback()
        self._recent = deque(maxlen=max(1, self.spike_window))
        self.last_probe = None
        self._gauge("sentinel_last_good_step", -1)

    # ---- helpers ----
    @staticmethod
    def _gauge(name, value):
        try:
            from paddle_tpu import observability as obs
            obs.registry().gauge(
                name, help="training-sentinel state").set(value)
        except Exception:
            pass

    def _classify(self, step, loss, summary):
        """(kind, ctx) of the worst anomaly this step, or (None, {})."""
        if summary is not None and not summary.good:
            return "nan_grad", {"bad_regions": summary.bad_regions,
                                "regions": summary.regions}
        if loss is not None and not np.isfinite(loss):
            return "nan_loss", {"loss": float(loss)}
        if summary is not None and self.grad_norm_limit is not None \
                and summary.grad_norm > self.grad_norm_limit:
            return "grad_norm", {"grad_norm": summary.grad_norm,
                                 "limit": self.grad_norm_limit}
        if loss is not None and self.spike_factor is not None \
                and len(self._recent) >= self._recent.maxlen:
            med = float(np.median(self._recent))
            if med > 0 and loss > self.spike_factor * med:
                return "loss_spike", {"loss": float(loss),
                                      "median": med,
                                      "factor": self.spike_factor}
        return None, {}

    # ---- the policy step ----
    def observe(self, step, loss=None, summary=None, site="train"):
        """Feed one step's probes; returns the action taken.

        `loss` is a python float (NaN allowed — the to_static guard
        probe delivers it without an extra device sync); `summary` is
        an optimizer :class:`GuardSummary`, a raw (4,) array, or None.
        """
        if summary is not None and not isinstance(summary, GuardSummary):
            summary = GuardSummary.from_array(summary)
        loss = float(loss) if loss is not None else None
        with self._lock:
            kind, ctx = self._classify(step, loss, summary)
            if kind is None:
                self.skip_streak = 0
                if loss is not None:
                    self._recent.append(loss)
                return SentinelAction.OK
            self.skip_streak += 1
            self.skips_total += 1
            streak = self.skip_streak
            do_rollback = (streak >= self.skip_limit
                           and self.auto_rollback
                           and self.checkpointer is not None)
            if do_rollback:
                self.skip_streak = 0
        # telemetry + callback + restore OUTSIDE the lock
        evt = note_anomaly(kind, site, step=step, streak=streak, **ctx)
        self.anomalies.append(evt)
        try:
            from paddle_tpu import observability as obs
            obs.registry().counter(
                "sentinel_skips_total",
                help="training steps skipped by the sentinel").inc()
        except Exception:
            pass
        if self.on_anomaly is not None:
            try:
                self.on_anomaly(evt)
            except Exception:
                pass
        if do_rollback and self.rollback(reason=evt) is not None:
            return SentinelAction.ROLLBACK
        # no restorable checkpoint: the step is still skipped — the
        # caller sees SKIP (never a ROLLBACK with resume_step=None)
        return SentinelAction.SKIP

    def note_probe(self, fn_name, probe):
        """Informational hook fed by ``to_static(guard=True)`` when
        this sentinel is the ambient one (:func:`install`): keeps the
        latest probe per traced function so ``observe()`` callers can
        read the loss without plumbing it themselves."""
        with self._lock:
            self.last_probe = dict(probe, fn=str(fn_name))

    def note_checkpoint(self, step):
        """A checkpoint landed for a step the caller believes clean —
        it becomes the rollback anchor (``sentinel_last_good_step``)."""
        with self._lock:
            if self.skip_streak == 0:
                self.last_good_step = int(step)
        if self.last_good_step == int(step):
            self._gauge("sentinel_last_good_step", int(step))

    def rollback(self, reason=None):
        """Restore model+optimizer from the ``last_good_step`` anchor
        (``note_checkpoint``) — NOT blindly the newest entry, which a
        caller saving unconditionally every loop may have captured
        mid-anomaly-streak for post-commit kinds (loss_spike,
        grad_norm) — apply the LR cooldown, and return the step to
        resume from (also kept on :attr:`resume_step`).  Falls back to
        the newest good entry when the anchor is unset or its entry
        was pruned/corrupted; returns None without any restorable
        checkpoint (the caller decides whether cold-start is
        acceptable — nothing is counted as a rollback then)."""
        if self.checkpointer is None:
            return None
        from paddle_tpu.resilience.checkpoint import auto_resume
        t0 = time.perf_counter()
        anchor = self.last_good_step
        start = 0
        if anchor is not None:
            start, _extra = auto_resume(self.checkpointer, self.model,
                                        self.optimizer, step=anchor)
        if start == 0:
            start, _extra = auto_resume(self.checkpointer, self.model,
                                        self.optimizer)
        got_ckpt = start > 0
        if not got_ckpt:
            with self._lock:
                self.resume_step = None
            return None
        if self.optimizer is not None and self.lr_cooldown != 1.0:
            try:
                self.optimizer.set_lr(
                    self.optimizer.get_lr() * self.lr_cooldown)
            except RuntimeError:
                # an LRScheduler owns the LR — cooldown is the
                # scheduler's job then; the rollback still restores
                pass
        with self._lock:
            self.rollbacks += 1
            self.resume_step = start
            self._recent.clear()
        try:
            from paddle_tpu import observability as obs
            with obs.span("resilience.sentinel.rollback",
                          resume_step=self.resume_step,
                          kind=getattr(reason, "kind", None),
                          restore_ms=round(
                              (time.perf_counter() - t0) * 1e3, 3)):
                pass
            obs.registry().counter(
                "sentinel_rollbacks_total",
                help="sentinel-triggered checkpoint rollbacks").inc()
        except Exception:
            pass
        from paddle_tpu.resilience.faultinject import note_recovery
        note_recovery("optimizer.grads", "rollback",
                      resume_step=self.resume_step)
        return self.resume_step


# ---------------------------------------------------- ambient sentinel
_current = None
_current_lock = threading.Lock()


def install(sentinel):
    """Install the process-ambient sentinel consulted by the
    ``to_static(guard=True)`` probe hook (purely informational — the
    policy still runs through explicit ``observe()`` calls)."""
    global _current
    with _current_lock:
        _current = sentinel
    return sentinel


def uninstall(sentinel=None):
    global _current
    with _current_lock:
        if sentinel is not None and _current is not sentinel:
            return
        _current = None


def current():
    return _current


# ------------------------------------------------- lineage + bisection
class BatchLineage:
    """Bounded (step → microbatch identity) recorder for deterministic
    replay: ``record(step, seed=..., batch=...)`` at every step, and
    after an anomaly the localizer replays entries between the last
    good checkpoint and the flagged step.  ``batch`` may be the actual
    batch (kept by reference) or any identity (ids, a digest)."""

    def __init__(self, capacity=256):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries = OrderedDict()    # step -> dict

    def record(self, step, seed=None, batch=None, **meta):
        e = {"step": int(step), "seed": seed, "batch": batch, **meta}
        with self._lock:
            self._entries[int(step)] = e
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
        return e

    def get(self, step):
        with self._lock:
            return self._entries.get(int(step))

    def steps(self):
        with self._lock:
            return list(self._entries)

    def __len__(self):
        with self._lock:
            return len(self._entries)


def replay_bisect(predicate, lo, hi):
    """Minimal step ``k`` in ``[lo, hi]`` with ``predicate(k)`` true —
    ``predicate(k)`` must mean "replaying steps lo..k from the last
    good state trips the guard", which is monotone in ``k`` (once the
    poison batch is consumed the prefix stays anomalous).  Returns None
    when even ``predicate(hi)`` is clean (the anomaly does not
    reproduce — a transient, not a data fault).  ``O(log(hi-lo))``
    predicate calls; each call is one deterministic replay."""
    lo, hi = int(lo), int(hi)
    if lo > hi:
        raise ValueError(f"need lo <= hi, got {lo} > {hi}")
    if not predicate(hi):
        return None
    while lo < hi:
        mid = (lo + hi) // 2
        if predicate(mid):
            hi = mid
        else:
            lo = mid + 1
    return lo


def localize_poison(replay, last_good_step, bad_step):
    """Name the poison batch: ``replay(upto)`` restores the last good
    checkpoint and re-runs steps ``last_good_step+1 .. upto`` with the
    guard armed, returning True iff any step tripped.  Wraps
    :func:`replay_bisect` with the training-loop convention (the poison
    step is strictly after the last good checkpoint)."""
    return replay_bisect(replay, int(last_good_step) + 1, int(bad_step))


# ------------------------------------------------- cross-rank SDC vote
def tree_digest(tree):
    """Deterministic sha256 over a pytree of arrays/Tensors — the
    cross-rank comparison unit.  Vote only values that are REPLICATED
    across ranks (post-sync gradients, updated parameter replicas):
    those are bit-identical by construction, so digest divergence IS
    corruption — pre-sync local grads legitimately differ and would
    make every rank a dissenter.  Dict leaves hash under their sorted
    keys; every leaf contributes its shape/dtype header plus raw bytes
    (host transfer — size the voted tree accordingly)."""
    h = hashlib.sha256()

    def leaf_bytes(v):
        v = getattr(v, "_value", v)          # paddle Tensor -> array
        a = np.asarray(v)
        h.update(f"{a.shape}:{a.dtype}|".encode())
        h.update(a.tobytes())

    def walk(node, path):
        if isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], f"{path}/{k}")
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(v, f"{path}/{i}")
        else:
            h.update(path.encode())
            leaf_bytes(node)

    walk(tree, "")
    return h.hexdigest()


class DigestVote:
    """One vote round's outcome: per-global-rank digests, the majority
    digest (STRICT majority — held by more than half the members), and
    the dissenting SUSPECT ranks.

    Without a strict majority the vote is INCONCLUSIVE
    (``conclusive=False``, ``majority=None``, no suspects): in a
    2-member world any divergence is a 1-1 tie, and naming a "suspect"
    there would be a coin flip that can quarantine the healthy rank —
    the caller must fall back to a different oracle (rollback both, or
    replay-bisect locally)."""

    def __init__(self, step, site, digests, majority, suspects, mine):
        self.step = int(step)
        self.site = str(site)
        self.digests = dict(digests)      # global rank -> digest
        self.majority = majority          # None when inconclusive
        self.suspects = tuple(suspects)   # global ranks, sorted
        self.mine = mine

    @property
    def conclusive(self):
        return self.majority is not None

    @property
    def agree(self):
        return self.conclusive and not self.suspects

    @property
    def self_suspect(self):
        return self.conclusive and self.mine != self.majority

    def to_dict(self):
        return {"step": self.step, "site": self.site,
                "majority": self.majority,
                "conclusive": self.conclusive,
                "suspects": list(self.suspects),
                "self_suspect": self.self_suspect,
                "digests": dict(self.digests)}

    def __repr__(self):
        return (f"DigestVote(step={self.step}, site={self.site!r}, "
                f"conclusive={self.conclusive}, "
                f"suspects={list(self.suspects)})")


# own-key reap bookkeeping: votes are collective and lockstep, so when
# THIS rank starts round r it has finished round r_prev's gather —
# which proves every rank PUBLISHED r_prev, which proves every rank
# FINISHED every round before r_prev.  Keys of those provably-consumed
# rounds are deleted (each rank deletes its own), bounding coordinator
# growth to two live rounds per (namespace, site).
_vote_rounds = {}
_vote_lock = threading.Lock()


def digest_vote(value, *, step, site="grads", client=None,
                world_view=None, timeout_s=None, monitor=None):
    """One cross-rank digest vote round (collective — every member must
    call it with the same ``step``/``site``).

    ``value`` is a pytree (digested via :func:`tree_digest`) or an
    already-computed digest string.  Rank digests travel through the
    PR 14 timeout-bounded KV machinery under ONE shared deadline with
    the watchdog's DEAD-verdict abort wired (a dead peer fails the
    vote in seconds as :class:`~paddle_tpu.resilience.fleet
    .CollectiveTimeout`, never a hang).  Dissenting ranks are fed to
    ``monitor.mark_suspect`` (quarantine) when a
    :class:`~paddle_tpu.resilience.fleet.FleetMonitor` rides along —
    the SUSPECT ⇒ :func:`~paddle_tpu.resilience.fleet.reconfigure`
    hand-off is the caller's (see docs/resilience.md).
    """
    from paddle_tpu.resilience import fleet

    mine = value if isinstance(value, str) else tree_digest(value)
    wv = world_view if world_view is not None else fleet.world()
    if wv.size <= 1:
        return DigestVote(step, site, {wv.global_rank: mine}, mine, (),
                          mine)
    cl = client if client is not None else fleet._client()
    if cl is None:
        raise RuntimeError(
            "digest_vote in a multi-rank world needs the coordination-"
            "service client (jax.distributed) or an explicit client=")
    cfg = fleet.get_config()
    timeout_s = (float(timeout_s) if timeout_s is not None
                 else cfg.collective_timeout_s)
    ns = wv.namespace
    rnd = int(step)

    def key_for(fleet_rank, r=rnd):
        return f"{ns}/sentinel/vote/{site}/s{r}/r{fleet_rank}"

    # reap provably-consumed earlier rounds (see _vote_rounds note)
    hist_key = (ns, str(site), wv.rank)
    with _vote_lock:
        prior = _vote_rounds.get(hist_key, [])
        reap = prior[:-1]                   # all but my previous round
        _vote_rounds[hist_key] = prior[-1:] + [rnd]
    for r in reap:
        try:
            cl.key_value_delete(key_for(wv.rank, r))
        except Exception:
            pass

    fleet.kv_set_bytes(cl, key_for(wv.rank), mine.encode())
    abort_if = None
    if monitor is not None:
        members = wv.members

        def abort_if():   # noqa: F811 — deliberate rebind
            return any(monitor.is_dead(m) for m in members)

    digests = {wv.global_rank: mine}
    deadline = time.monotonic() + timeout_s
    for i, grank in enumerate(wv.members):
        if i == wv.rank:
            continue
        remaining = max(0.05, deadline - time.monotonic())
        raw = fleet.kv_get_bytes(cl, key_for(i), remaining,
                                 site="sentinel.vote",
                                 missing_rank=grank, abort_if=abort_if,
                                 config=cfg)
        digests[grank] = bytes(raw).decode().rstrip("\x00")

    counts = {}
    for d in digests.values():
        counts[d] = counts.get(d, 0) + 1
    top = max(counts.values())
    if top * 2 > len(digests):
        # STRICT majority only: every rank computes the same winner
        # (a strict majority is unique).  Anything less — a 1-1 tie in
        # a 2-member world, a 3-way split — is inconclusive: naming a
        # suspect there would be a coin flip on digest sort order
        majority = next(d for d, c in counts.items() if c == top)
        suspects = tuple(sorted(r for r, d in digests.items()
                                if d != majority))
    else:
        majority, suspects = None, ()
    vote = DigestVote(step, site, digests, majority, suspects, mine)
    try:
        from paddle_tpu import observability as obs
        with obs.span("resilience.sentinel.vote", step=vote.step,
                      site=vote.site, suspects=list(suspects)):
            pass
        obs.registry().counter(
            "sentinel_digest_votes_total",
            help="cross-rank digest vote rounds").inc()
        if not vote.conclusive:
            obs.registry().counter(
                "sentinel_vote_inconclusive_total",
                help="digest votes with no strict majority").inc()
        if suspects:
            obs.registry().counter(
                "sentinel_sdc_suspects_total",
                help="ranks named SDC-suspect by a digest vote").inc(
                    len(suspects))
    except Exception:
        pass
    for s in suspects:
        note_anomaly("sdc_suspect", f"sentinel.vote.{site}", step=step,
                     rank=s)
        if monitor is not None:
            monitor.mark_suspect(
                s, reason=f"digest vote {site}@{step}")
    return vote


def _reset_for_tests():
    """Test isolation: forget vote-round reap history and the ambient
    sentinel."""
    global _current
    with _vote_lock:
        _vote_rounds.clear()
    with _current_lock:
        _current = None
