"""Crash-safe manifested checkpointing.

Layout of a checkpoint directory::

    run/
      MANIFEST.json            # atomic, rewritten on every save
      ckpt-00000010.pkl        # atomic write-then-rename payloads
      ckpt-00000020.pkl

The manifest is the source of truth: every entry records step, file,
byte size, and a sha256 content digest.  ``load()`` verifies the digest
before unpickling; a torn or corrupted checkpoint is skipped with a
recorded recovery event and the next-older GOOD checkpoint restores
instead — so the failure mode of a torn write is "resume a few steps
earlier", never "run dead".

Write path durability: payloads and the manifest both go through
``framework.io.write_atomic`` (temp file + fsync + ``os.replace``), and
the manifest is updated only AFTER its payload is durably in place —
the manifest can under-promise (a payload with no entry: harmless
debris) but never over-promise (an entry whose payload is missing or
half-written and undetectable).

``async_save=True`` moves serialization's WRITE half off the training
thread: the state is snapshotted (pickled) synchronously at ``save()``
time — so later in-place mutation of the live tensors cannot tear the
checkpoint — and the disk write + manifest update happen on a single
background writer thread (one thread: writes stay ordered).
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import queue
import threading
import time

from paddle_tpu.framework import io as fio

__all__ = ["CheckpointCorruption", "Checkpointer", "auto_resume",
           "digest_bytes", "read_manifest", "write_manifest"]

_MANIFEST = "MANIFEST.json"
_FORMAT = 1


class CheckpointCorruption(RuntimeError):
    """Raised by ``load(strict=True)`` when every manifest entry fails
    its digest check (the default ``strict=False`` returns None so
    callers can cold-start)."""


def digest_bytes(data):
    """sha256 hex digest — THE checkpoint content-digest function,
    shared with :class:`~paddle_tpu.resilience.fleet
    .DistributedCheckpointer` so single-process and fleet manifests
    stay mutually verifiable."""
    return hashlib.sha256(data).hexdigest()


_digest = digest_bytes


def read_manifest(directory, fmt=_FORMAT):
    """Parse ``<directory>/MANIFEST.json``; unreadable/absent yields an
    empty manifest of format `fmt` (cold start is not an error)."""
    path = os.path.join(directory, _MANIFEST)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {"format": fmt, "checkpoints": []}


def write_manifest(directory, manifest):
    """Atomic manifest rewrite through the shared durable-write choke
    point (distinct ``io.manifest`` fault site: occurrence-indexed
    plans can tear the Nth payload without counting interleaved
    manifest rewrites).  Callers hold their checkpointer lock across
    this write ON PURPOSE: the manifest read-modify-write must be
    serialized or a concurrent save's entry is silently dropped — the
    deliberate ordering PR 7 reviewed (write-under-lock, baselined for
    the method form this helper replaces)."""
    fio.write_atomic(os.path.join(directory, _MANIFEST),  # racelint: disable=RL103
                     json.dumps(manifest, indent=1).encode(),
                     site="io.manifest")


class Checkpointer:
    """Step-indexed crash-safe checkpoint manager.

    - ``save(step, state)``: atomic payload write + digest + manifest
      update + retention pruning (keep the ``keep`` newest).
    - ``load(step=None)``: newest (or exact) GOOD checkpoint as
      ``(step, state)``; digest-verified with automatic fallback to the
      last good entry on corruption.
    - ``save_train_state`` / :func:`auto_resume`: the training-loop
      convenience pair.

    The observability spans (``resilience.checkpoint.save/load``) and
    the ``resilience_checkpoint_*`` counters make checkpoint health
    visible in the same telemetry stream as everything else.
    """

    def __init__(self, directory, keep=3, async_save=False):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = int(keep)
        self.async_save = bool(async_save)
        self._lock = threading.Lock()
        self._q = None
        self._writer = None
        self._writer_error = [None]
        if self.async_save:
            self._q = queue.Queue()
            self._writer = threading.Thread(
                target=self._writer_loop, daemon=True,
                name="paddle_tpu-ckpt-writer")
            self._writer.start()

    # ------------------------------------------------------------ save
    def _file_for(self, step):
        return f"ckpt-{int(step):08d}.pkl"

    def save(self, step, state):
        """Checkpoint `state` (any picklable pytree; Tensors are
        converted to host arrays) at `step`.  Returns the payload path
        (the write may still be in flight under ``async_save``)."""
        from paddle_tpu import observability as obs
        t0 = time.perf_counter()
        data = pickle.dumps(fio._to_saveable(state), protocol=4)
        entry = {
            "step": int(step),
            "file": self._file_for(step),
            "bytes": len(data),
            "sha256": _digest(data),
            "time_utc": time.strftime("%Y-%m-%d %H:%M:%S UTC",
                                      time.gmtime()),
        }
        if self.async_save:
            self._raise_writer_error()
            self._q.put((data, entry))
        else:
            self._commit(data, entry)
        obs.registry().counter(
            "resilience_checkpoint_writes_total",
            help="checkpoint save() calls").inc()
        with obs.span("resilience.checkpoint.save", step=int(step),
                      bytes=len(data), async_save=self.async_save,
                      serialize_ms=round(
                          (time.perf_counter() - t0) * 1e3, 3)):
            pass
        return os.path.join(self.directory, entry["file"])

    def _commit(self, data, entry):
        """Durably write payload THEN manifest (ordering is the crash-
        safety invariant); retention-pruned entries are dropped from
        the SAME manifest write (one fsync'd rewrite per save, and
        ``io.manifest`` fault occurrences advance once per save) and
        their payloads deleted after — the manifest never references a
        deleted payload.  The lock serializes the manifest
        read-modify-write when sync-mode save() runs from more than one
        thread (a concurrent entry must never be silently dropped)."""
        with self._lock:
            fio.write_atomic(os.path.join(self.directory, entry["file"]),
                             data)
            manifest = self._read_manifest()
            ckpts = [c for c in manifest.get("checkpoints", ())
                     if c["step"] != entry["step"]]
            ckpts.append(entry)
            ckpts.sort(key=lambda c: c["step"])
            drop, ckpts = ckpts[:-self.keep], ckpts[-self.keep:]
            self._write_manifest({"format": _FORMAT,
                                  "checkpoints": ckpts})
            for c in drop:
                try:
                    os.remove(os.path.join(self.directory, c["file"]))
                except OSError:
                    pass

    def _writer_loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            if isinstance(item, threading.Event):   # wait() flush marker
                item.set()
                continue
            data, entry = item
            try:
                self._commit(data, entry)
            except BaseException as e:  # surfaced on next save()/wait()
                self._writer_error[0] = e

    def _raise_writer_error(self):
        err = self._writer_error[0]
        if err is not None:
            self._writer_error[0] = None
            raise RuntimeError(
                "async checkpoint writer failed") from err

    def wait(self):
        """Block until queued async writes are durably committed (call
        before process exit / in the preemption drain).  A flush marker
        through the single ordered writer thread is the barrier."""
        if self._q is not None:
            done = threading.Event()
            self._q.put(done)
            done.wait()
            self._raise_writer_error()

    def close(self):
        if self._writer is not None:
            self.wait()
            self._q.put(None)
            self._writer.join(timeout=5)
            self._writer = None

    # ------------------------------------------------------------ load
    def _read_manifest(self):
        return read_manifest(self.directory)

    def _write_manifest(self, manifest):
        write_manifest(self.directory, manifest)

    def steps(self):
        """Manifest-recorded steps, ascending (unverified)."""
        return [c["step"] for c in self._read_manifest()["checkpoints"]]

    def latest_step(self):
        s = self.steps()
        return s[-1] if s else None

    def verify(self, entry):
        """Does `entry`'s payload exist with the manifested digest?"""
        path = os.path.join(self.directory, entry["file"])
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        if len(data) != entry["bytes"] or _digest(data) != entry["sha256"]:
            return None
        return data

    def load(self, step=None, strict=False):
        """Restore the newest GOOD checkpoint (or exactly `step`).

        Returns ``(step, state)``; ``None`` when nothing restorable
        exists and ``strict=False``.  Corrupt entries (torn write, bit
        rot, missing payload) are skipped with a recorded recovery —
        the fallback-to-last-good behavior the chaos suite proves.
        """
        from paddle_tpu import observability as obs
        if self.async_save:
            self.wait()
        entries = self._read_manifest()["checkpoints"]
        if step is not None:
            entries = [c for c in entries if c["step"] == int(step)]
        skipped = 0
        for entry in reversed(entries):
            data = self.verify(entry)
            if data is None:
                skipped += 1
                obs.registry().counter(
                    "resilience_checkpoint_corrupt_total",
                    help="checkpoints that failed digest verification"
                ).inc()
                continue
            if skipped:
                from paddle_tpu.resilience.faultinject import note_recovery
                note_recovery("io.save", "torn_write",
                              fallback_step=entry["step"],
                              skipped=skipped)
            with obs.span("resilience.checkpoint.load",
                          step=entry["step"], skipped=skipped):
                return entry["step"], pickle.loads(data)
        if strict and entries:
            raise CheckpointCorruption(
                f"all {len(entries)} manifest entries under "
                f"{self.directory} failed digest verification")
        return None

    # ------------------------------------------- training conveniences
    def save_train_state(self, step, model=None, optimizer=None,
                         extra=None):
        state = {"step": int(step)}
        if model is not None:
            state["model"] = model.state_dict()
        if optimizer is not None:
            state["optimizer"] = optimizer.state_dict()
        if extra is not None:
            state["extra"] = extra
        return self.save(step, state)


def auto_resume(checkpointer, model=None, optimizer=None, step=None):
    """Resume a training loop from the newest good checkpoint.

    Restores model/optimizer state in place and returns
    ``(start_step, extra)`` — ``start_step`` is the step AFTER the
    checkpointed one (0 on cold start), ``extra`` whatever
    ``save_train_state(extra=...)`` recorded (or None)::

        ckpt = Checkpointer("run/ckpt", keep=3)
        start, _ = auto_resume(ckpt, model, opt)
        for step in range(start, total_steps):
            ...
            if step % 10 == 9:
                ckpt.save_train_state(step, model, opt)

    ``step`` pins the restore to exactly that checkpointed step (the
    sentinel's last-good anchor); cold-start (0) when that entry is
    gone or corrupt.
    """
    got = checkpointer.load(step=step)
    if got is None:
        return 0, None
    step, state = got
    if model is not None and "model" in state:
        model.set_state_dict(state["model"])
    if optimizer is not None and "optimizer" in state:
        optimizer.set_state_dict(state["optimizer"])
    return int(state.get("step", step)) + 1, state.get("extra")
