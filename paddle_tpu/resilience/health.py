"""Engine health state machine: HEALTHY → DEGRADED → DRAINING.

Driven by a single pressure signal in [0, 1] (for the serving engine:
live page-pool occupancy).  Transitions carry hysteresis so the state
cannot flap across a threshold every step:

- HEALTHY  → DEGRADED  at pressure >= ``degraded_at``
- DEGRADED → DRAINING  at pressure >= ``drain_at``
- DEGRADED → HEALTHY   at pressure <= ``recover_at`` (< degraded_at)
- DRAINING → DEGRADED  at pressure <= ``redegrade_at`` (< drain_at)

Semantics the serving engine attaches (docs/resilience.md):

- HEALTHY: admit everything the page budget allows.
- DEGRADED: keep admitting (the scheduler's page gate already slows
  intake) but the state is exported — a router in front of replicas
  uses it to shift load.
- DRAINING: REJECT new admissions (explicit backpressure) and let
  running requests finish — the graceful-degradation mode the
  Gemma-on-TPU study treats as table stakes.

Every transition is recorded as a ``resilience.health`` span plus the
``serving_health_state`` gauge (0/1/2), so dashboards and the chaos
suite read the same signal.
"""
from __future__ import annotations

import enum
import threading
from collections import deque

__all__ = ["HealthState", "HealthMonitor"]


class HealthState(enum.IntEnum):
    HEALTHY = 0
    DEGRADED = 1
    DRAINING = 2


class HealthMonitor:
    """Hysteretic three-state monitor over a [0, 1] pressure signal."""

    def __init__(self, degraded_at=0.85, drain_at=0.97, recover_at=0.70,
                 redegrade_at=None, on_transition=None, gauge=None):
        if not 0.0 <= recover_at < degraded_at < drain_at <= 1.0:
            raise ValueError(
                "need 0 <= recover_at < degraded_at < drain_at <= 1, "
                f"got {recover_at}/{degraded_at}/{drain_at}")
        self.degraded_at = float(degraded_at)
        self.drain_at = float(drain_at)
        self.recover_at = float(recover_at)
        self.redegrade_at = float(redegrade_at) if redegrade_at is not None \
            else self.degraded_at
        if self.redegrade_at >= self.drain_at:
            raise ValueError("redegrade_at must be < drain_at")
        self.on_transition = on_transition
        self._gauge = gauge            # observability Gauge or None
        self._lock = threading.Lock()
        self._state = HealthState.HEALTHY
        self.transitions = []          # [(from, to, pressure)]
        self.last_pressure = 0.0
        self._pending = deque()        # transitions awaiting emission
        self._emitting = False         # one drainer at a time
        if self._gauge is not None:
            self._gauge.set(int(self._state))

    @property
    def state(self):
        return self._state

    @property
    def admitting(self):
        """DRAINING is the only state that refuses admissions."""
        return self._state != HealthState.DRAINING

    def update(self, pressure):
        """Feed the current pressure; returns the (possibly new) state.

        The transition decision and the `transitions` append happen
        under the lock; gauge/span recording and the `on_transition`
        callback run only AFTER it is released.  The callback is
        arbitrary user code: under the non-reentrant lock, a callback
        that feeds pressure back through ``update()`` (a drain hook
        reacting to DRAINING) deadlocks the monitor, and a slow one
        convoys every other updater.  Emission goes through a FIFO
        queue drained by one thread at a time, so gauge values and
        callback invocations arrive in TRANSITION order even when two
        updates race — the gauge can never be left stale showing a
        state older than the monitor's."""
        pressure = float(pressure)
        with self._lock:
            old = self._state
            new = self._next_state(old, pressure)
            self.last_pressure = pressure
            if new is not old:
                self._state = new
                self.transitions.append((old, new, pressure))
                self._pending.append((old, new, pressure))
        if new is not old:
            self._drain_events()
        return new

    def _drain_events(self):
        """Emit queued transitions in order.  Exactly one thread
        drains at a time; a thread arriving while another is emitting
        (including a reentrant update() from inside on_transition)
        leaves its event queued — the active drainer's loop picks it
        up, preserving FIFO delivery without holding any lock across
        user code."""
        while True:
            with self._lock:
                if self._emitting or not self._pending:
                    return
                self._emitting = True
                evt = self._pending.popleft()
            try:
                self._record(*evt)
            finally:
                with self._lock:
                    self._emitting = False

    def _next_state(self, state, p):
        if state == HealthState.HEALTHY:
            if p >= self.drain_at:
                return HealthState.DRAINING
            if p >= self.degraded_at:
                return HealthState.DEGRADED
            return state
        if state == HealthState.DEGRADED:
            if p >= self.drain_at:
                return HealthState.DRAINING
            if p <= self.recover_at:
                return HealthState.HEALTHY
            return state
        # DRAINING recovers stepwise: pool pressure must fall below the
        # re-degrade threshold first; full recovery goes through DEGRADED
        if p <= self.redegrade_at:
            return HealthState.DEGRADED
        return state

    def _record(self, old, new, pressure):
        if self._gauge is not None:
            try:
                self._gauge.set(int(new))
            except Exception:
                pass
        try:
            from paddle_tpu import observability as obs
            with obs.span("resilience.health", from_state=old.name,
                          to_state=new.name,
                          pressure=round(pressure, 4)):
                pass
        except Exception:
            pass
        if self.on_transition is not None:
            try:
                self.on_transition(old, new, pressure)
            except Exception:
                pass
