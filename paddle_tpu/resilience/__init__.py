"""paddle_tpu.resilience — fault-tolerant training & serving, plus the
deterministic fault-injection harness that proves it.

The pieces (docs/resilience.md has the architecture):

- :mod:`checkpoint` — crash-safe checkpointing: atomic write-then-
  rename payloads, a digest-bearing manifest with retention, corruption
  detection with automatic fallback to the last good checkpoint,
  optional async host-side writes, and :func:`auto_resume` for training
  loops;
- :mod:`retry` + :mod:`preemption` — a :func:`retry` decorator with
  exponential backoff, deterministic jitter and per-exception-class
  policies, and a :class:`PreemptionHandler` that drains and
  checkpoints at the step boundary after a preemption signal, beating
  the ``distributed.elastic`` watchdog through the drain;
- :mod:`health` — the HEALTHY → DEGRADED → DRAINING state machine the
  serving engine drives from live page-pool occupancy;
- :mod:`faultinject` — seeded, deterministic fault plans executed
  through hook points in ``framework/io.py``, ``optimizer/`` and
  ``serving/engine.py``, with every injected fault and recovery
  recorded through ``paddle_tpu.observability``;
- :mod:`sentinel` — the training sentinel: in-trace anomaly probes
  (``to_static(guard=True)`` / ``Optimizer(guard=True)``), the
  :class:`sentinel.TrainingSentinel` skip/rollback policy machine,
  deterministic replay bisection to name a poison batch, and the
  cross-rank parameter/gradient digest vote that localizes silent data
  corruption to a rank (SUSPECT ⇒ quarantine ⇒ reconfigure);
- :mod:`fleet` — distributed fault tolerance: timeout-bounded
  coordination (:class:`fleet.CollectiveTimeout` instead of a hung
  collective), rank heartbeats + the HEALTHY→SUSPECT→DEAD fleet
  watchdog, sharded quorum-manifested :class:`fleet
  .DistributedCheckpointer` with reshard-on-shrink, and elastic
  :func:`fleet.reconfigure` so survivors of a dead rank re-form at the
  smaller world size and resume.

Quickstart::

    from paddle_tpu import resilience as R

    ckpt = R.Checkpointer("run/ckpt", keep=3, async_save=True)
    with R.PreemptionHandler(checkpointer=ckpt) as pre:
        start, _ = R.auto_resume(ckpt, model, opt)
        for step in range(start, steps):
            train_step(batch(step))
            if step % 10 == 9:
                ckpt.save_train_state(step, model, opt)
            if pre.check(step, lambda: {"step": step,
                                        "model": model.state_dict(),
                                        "optimizer": opt.state_dict()}):
                break
"""
from paddle_tpu.resilience import faultinject, fleet, sentinel
from paddle_tpu.resilience.sentinel import (AnomalyDetected,
                                            BatchLineage, DigestVote,
                                            GuardSummary,
                                            SentinelAction,
                                            TrainingSentinel,
                                            digest_vote,
                                            localize_poison,
                                            replay_bisect, tree_digest)
from paddle_tpu.resilience.checkpoint import (CheckpointCorruption,
                                              Checkpointer, auto_resume)
from paddle_tpu.resilience.faultinject import (FaultInjector, FaultPlan,
                                               FaultSpec, WorkerFault)
from paddle_tpu.resilience.fleet import (CollectiveTimeout,
                                         DistributedCheckpointer,
                                         FleetMonitor,
                                         HeartbeatPublisher, RankState,
                                         WorldView, reconfigure)
from paddle_tpu.resilience.health import HealthMonitor, HealthState
from paddle_tpu.resilience.preemption import (PreemptionHandler,
                                              request_preemption)
from paddle_tpu.resilience.retry import (RetryExhausted, RetryPolicy,
                                         retry)

__all__ = [
    "AnomalyDetected",
    "BatchLineage",
    "CheckpointCorruption",
    "Checkpointer",
    "CollectiveTimeout",
    "DigestVote",
    "DistributedCheckpointer",
    "FaultInjector",
    "FaultPlan",
    "FaultSpec",
    "FleetMonitor",
    "GuardSummary",
    "HealthMonitor",
    "HealthState",
    "HeartbeatPublisher",
    "PreemptionHandler",
    "RankState",
    "RetryExhausted",
    "RetryPolicy",
    "SentinelAction",
    "TrainingSentinel",
    "WorkerFault",
    "WorldView",
    "auto_resume",
    "digest_vote",
    "faultinject",
    "fleet",
    "localize_poison",
    "reconfigure",
    "replay_bisect",
    "request_preemption",
    "retry",
    "sentinel",
    "tree_digest",
]
