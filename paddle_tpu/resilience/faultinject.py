"""Deterministic fault injection — seeded fault plans over named hook
sites.

The chaos contract (docs/resilience.md): a fault plan is DATA (JSON-able
list of :class:`FaultSpec`), execution is DETERMINISTIC (faults match on
the per-site occurrence counter, never wall clock or a free-running
RNG), and every injected fault and every recovery is recorded through
``paddle_tpu.observability`` — so a chaos run leaves the same audit
trail a production incident would.

Hook sites instrumented in this repo:

=====================  ====================================================
site                   where / supported kinds
=====================  ====================================================
``io.save``            ``framework/io.py`` atomic writer — ``torn_write``
                       (truncate payload / abort the rename),
                       ``exception``, ``slow``
``io.manifest``        checkpoint MANIFEST.json rewrites (same writer,
                       separate occurrence counter)
``optimizer.step``     ``Optimizer.step`` (eager) — ``exception``,
                       ``preempt``, ``slow``
``serving.decode``     ``LLMEngine`` decode step — ``exception`` (the
                       engine evicts-and-requeues the offending request),
                       ``slow``
``serving.pool``       ``LLMEngine`` decode capacity pass —
                       ``pool_exhaust`` (forces one preemption round
                       through the REAL victim-selection path)
``fleet.heartbeat``    ``resilience.fleet.HeartbeatPublisher`` beat —
                       ``exception`` (beat skipped, counted in
                       ``missed_beats``; the publisher thread
                       survives), ``slow``
``fleet.kv_get``       every timeout-bounded coordination-service get
                       (``fleet.kv_get_bytes``: eager collectives,
                       checkpoint quorum, reconfigure join) —
                       ``exception``, ``slow``
``fleet.rank_kill``    chaos-worker per-step hook — ``rank_kill``
                       delivers a REAL ``SIGKILL`` to the calling
                       process (the dead-host fault of the
                       multi-process chaos suite; only meaningful in a
                       sacrificial worker subprocess)
``serving.fleet.step`` serving-fleet replica step loop
                       (``serving/fleet/server.py``) — ``rank_kill``
                       (dead serving host), ``wedge`` (SIGSTOP the
                       whole process: alive to the OS, frozen to the
                       fleet — the watchdog-TIMEOUT fault, as opposed
                       to the crash fault; ``payload["park_s"]``
                       parks only the calling thread for that many
                       seconds instead, the in-process variant whose
                       heartbeats stop because the chaos harness beats
                       from the parked loop), ``exception``, ``slow``
``optimizer.grads``    ``Optimizer.step`` gradient intake (eager) —
                       ``bitflip`` flips one mantissa/exponent bit of
                       one gradient element (silent data corruption:
                       values change, nothing is NaN), ``nan_grad``
                       poisons one element to NaN; both are applied by
                       :func:`corrupt_array` at the call site, are
                       deterministic on (plan seed, occurrence), and
                       target ``payload["param"]`` by name (default:
                       the first parameter with a gradient)
``serving.traffic.tick``  traffic-driver scheduling quantum
                       (``serving/traffic/driver.py``) — ``qps_surge``
                       (returned to the driver, which injects
                       ``payload["requests"]`` extra arrivals compiled
                       from the spec's own seed: even the surge is
                       replay-identical), ``slow``, ``exception``
``serving.logits``     ``LLMEngine`` guarded decode step — ``nan_grad``
                       poisons the victim request's logits row to NaN,
                       ``bitflip`` to +inf, through a traced poison
                       operand (zeros when clean, so the compiled
                       program never changes); the victim is
                       ``payload["request_id"]`` or the latest-arrived
                       live request.  Requires
                       ``EngineConfig(guard=True)`` — unguarded
                       engines never consult the site
=====================  ====================================================

Usage::

    plan = FaultPlan([
        FaultSpec("io.save", "torn_write", at=2),     # 3rd save is torn
        FaultSpec("optimizer.step", "preempt", at=5),
    ], seed=0)
    with FaultInjector(plan):
        train()

Call sites use :func:`fire`: near-free when no plan is installed (one
global ``is None`` check), and generic kinds (``exception`` / ``slow`` /
``preempt``) are executed by :func:`fire` itself so a hook point is one
line.  Site-specific kinds (``torn_write``, ``pool_exhaust``) are
returned to the caller to interpret.
"""
from __future__ import annotations

import threading
import time

__all__ = [
    "FaultSpec", "FaultPlan", "FaultInjector", "WorkerFault",
    "corrupt_array", "fire", "active_plan", "note_recovery",
]

KINDS = ("torn_write", "exception", "preempt", "pool_exhaust", "slow",
         "rank_kill", "wedge", "bitflip", "nan_grad", "qps_surge")


class WorkerFault(RuntimeError):
    """The exception an ``exception``-kind fault raises.  Carries the
    site and any targeting payload (e.g. ``request_id`` for serving
    faults) so recovery code can identify the offender."""

    def __init__(self, site, spec, **ctx):
        self.site = site
        self.spec = spec
        self.ctx = dict(ctx)
        self.request_id = (spec.payload or {}).get("request_id")
        super().__init__(
            f"injected fault at {site!r} (kind={spec.kind}, "
            f"occurrence={spec.at})")


class FaultSpec:
    """One fault: WHERE (site), WHAT (kind), WHEN (occurrence index).

    - ``at``: 0-based occurrence index at the site; the fault fires on
      occurrences ``[at, at + times)``.  Matching on the occurrence
      counter (not wall time) is what makes replays deterministic.
    - ``payload``: kind-specific knobs — ``torn_write``:
      ``{"keep_fraction": 0.5}`` or ``{"abort_rename": True}``;
      ``slow``: ``{"sleep_s": 0.05}``; serving ``exception``:
      ``{"request_id": "req-3"}``.
    """

    def __init__(self, site, kind, at=0, times=1, payload=None):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; one of {KINDS}")
        if at < 0 or times < 1:
            raise ValueError("at must be >= 0 and times >= 1")
        self.site = str(site)
        self.kind = str(kind)
        self.at = int(at)
        self.times = int(times)
        self.payload = dict(payload) if payload else {}

    def matches(self, occurrence):
        return self.at <= occurrence < self.at + self.times

    def to_dict(self):
        return {"site": self.site, "kind": self.kind, "at": self.at,
                "times": self.times, "payload": dict(self.payload)}

    @classmethod
    def from_dict(cls, d):
        return cls(d["site"], d["kind"], d.get("at", 0),
                   d.get("times", 1), d.get("payload"))

    def __repr__(self):
        return (f"FaultSpec({self.site!r}, {self.kind!r}, at={self.at}, "
                f"times={self.times})")


class FaultPlan:
    """An ordered, seeded collection of faults (the chaos-suite input).

    The seed parameterizes nothing today beyond being recorded with
    every injection event — it exists so a future probabilistic fault
    kind has a deterministic anchor, and so two chaos runs can be
    distinguished in the observability log.
    """

    def __init__(self, faults=(), seed=0, name="fault-plan"):
        self.faults = [f if isinstance(f, FaultSpec)
                       else FaultSpec.from_dict(f) for f in faults]
        self.seed = int(seed)
        self.name = str(name)

    def faults_for(self, site):
        return [f for f in self.faults if f.site == site]

    def to_dict(self):
        return {"name": self.name, "seed": self.seed,
                "faults": [f.to_dict() for f in self.faults]}

    @classmethod
    def from_dict(cls, d):
        return cls(d.get("faults", ()), d.get("seed", 0),
                   d.get("name", "fault-plan"))

    def __repr__(self):
        return (f"FaultPlan({self.name!r}, seed={self.seed}, "
                f"{len(self.faults)} faults)")


class FaultInjector:
    """Installs a :class:`FaultPlan` for the duration of a ``with``
    block.  Tracks per-site occurrence counters and a log of every
    injection (``injector.injected``) for post-hoc assertions."""

    def __init__(self, plan):
        self.plan = plan
        self._counts = {}
        self._lock = threading.Lock()
        self.injected = []          # [(site, FaultSpec, occurrence)]

    # ---- plan execution ----
    def poll(self, site, **ctx):
        """Advance the site's occurrence counter; return the matching
        FaultSpec (recorded) or None."""
        hit = None
        # counter advance, spec match AND log append in ONE critical
        # section: hook sites fire from any thread (serving decode,
        # checkpoint writer), and the injection log is read for
        # post-hoc ordering assertions — entries must land in
        # occurrence order
        with self._lock:
            occ = self._counts.get(site, 0)
            self._counts[site] = occ + 1
            for spec in self.plan.faults_for(site):
                if spec.matches(occ):
                    self.injected.append((site, spec, occ))
                    hit = spec
                    break
        if hit is not None:
            # telemetry outside the lock: spans/counters take their
            # own locks and must stay innermost
            _record_injection(self.plan, site, hit, occ, ctx)
        return hit

    def occurrences(self, site):
        with self._lock:
            return self._counts.get(site, 0)

    # ---- installation ----
    def __enter__(self):
        install(self)
        return self

    def __exit__(self, *exc):
        uninstall(self)
        return False


_active = None
_active_lock = threading.Lock()


def install(injector):
    global _active
    with _active_lock:
        if _active is not None:
            raise RuntimeError("a FaultInjector is already installed "
                               "(nesting fault plans is not supported)")
        _active = injector
    return injector


def uninstall(injector=None):
    global _active
    with _active_lock:
        if injector is not None and _active is not injector:
            return
        _active = None


def active_plan():
    inj = _active
    return inj.plan if inj is not None else None


def fire(site, **ctx):
    """The one-line hook call sites use.

    Returns None (the overwhelmingly common case: no plan installed, or
    no fault due at this occurrence).  Generic kinds execute here:

    - ``exception`` → raises :class:`WorkerFault`;
    - ``slow``      → sleeps ``payload["sleep_s"]`` (default 0.01);
    - ``preempt``   → requests preemption on the installed
      :class:`~paddle_tpu.resilience.preemption.PreemptionHandler`.

    Site-specific kinds (``torn_write``, ``pool_exhaust``,
    ``qps_surge``) return the spec for the caller to interpret.
    """
    inj = _active
    if inj is None:
        return None
    spec = inj.poll(site, **ctx)
    if spec is None:
        return None
    if spec.kind == "exception":
        raise WorkerFault(site, spec, **ctx)
    if spec.kind == "slow":
        time.sleep(float(spec.payload.get("sleep_s", 0.01)))
        return spec
    if spec.kind == "preempt":
        from paddle_tpu.resilience import preemption
        preemption.request_preemption(reason=f"injected at {site}")
        return spec
    if spec.kind == "rank_kill":
        # the dead-host fault: a REAL, unhandleable SIGKILL — no atexit,
        # no flushes, no drain; exactly what a preempted host looks like
        # to its peers.  Flush the injection record first (it is this
        # process's last testimony).
        import os
        import signal
        import sys
        sys.stderr.flush()
        sys.stdout.flush()
        os.kill(os.getpid(), signal.SIGKILL)
    if spec.kind == "wedge":
        # the wedged-host fault: unlike rank_kill the process stays
        # ALIVE to the OS but stops making progress — heartbeats cease
        # and only the watchdog's bounded-timeout DEAD verdict can
        # unblock the fleet (the timeout path, not the crash path).
        # Default is a real SIGSTOP (freezes every thread, including a
        # heartbeat publisher thread); ``payload["park_s"]`` parks just
        # the calling thread for a bounded time instead — the
        # in-process variant for tests whose beats are driven from the
        # parked loop itself.
        park_s = spec.payload.get("park_s")
        if park_s is not None:
            time.sleep(float(park_s))
            return spec
        import os
        import signal
        import sys
        sys.stderr.flush()
        sys.stdout.flush()
        os.kill(os.getpid(), signal.SIGSTOP)
        return spec
    return spec


def corrupt_array(spec, value, seed=0, occurrence=0):
    """Apply a ``bitflip`` / ``nan_grad`` spec to ONE element of
    `value` (any array-like); returns a numpy copy in the input's own
    float dtype (non-float inputs corrupt through float32) — every
    other element is bit-identical to the input, which is what makes
    the digest-vote proofs sound.

    Deterministic: the target element and (for ``bitflip``) the flipped
    bit come from ``payload["index"]`` / ``payload["bit"]`` when given,
    else from a PRNG seeded by (plan seed, spec.at, occurrence) — and
    since call sites leave `occurrence` at 0, every firing of one
    ``times=N`` spec hits the SAME element: persistent-fault semantics
    (one sticky bad lane), replay-stable like every other kind.  A
    bitflip targets a HIGH exponent bit by default (bit width-2: 30
    for f32 words, 62 for f64): depending on the victim's exponent the
    element becomes huge-but-finite (the grad-norm channel catches it)
    or NaN/inf (the finite guard does) — both are one real hardware
    flip.  Pass a low ``payload["bit"]`` for the strictly-silent
    variant only a digest vote can see; ``nan_grad`` is the always-
    loud variant the finite guard must catch within one step.
    """
    import numpy as np
    if spec.kind not in ("bitflip", "nan_grad"):
        raise ValueError(f"corrupt_array cannot apply kind {spec.kind!r}")
    arr = np.array(value, copy=True)
    if arr.dtype not in (np.float32, np.float64):
        arr = arr.astype(np.float32)
    flat = arr.reshape(-1)
    if flat.size == 0:
        return arr
    import random as _random
    rng = _random.Random(int(seed) * 1000003
                         + int(spec.at) * 101 + int(occurrence))
    idx = int(spec.payload.get("index", rng.randrange(flat.size)))
    idx %= flat.size
    if spec.kind == "nan_grad":
        flat[idx] = np.nan
    else:
        utype = np.uint32 if arr.dtype == np.float32 else np.uint64
        width = 32 if arr.dtype == np.float32 else 64
        bit = int(spec.payload.get("bit", width - 2)) % width
        word = flat[idx:idx + 1].view(utype)
        word ^= utype(1 << bit)
    return arr


# ---- observability wiring ------------------------------------------------
def _record_injection(plan, site, spec, occurrence, ctx):
    try:
        from paddle_tpu import observability as obs
        with obs.span("resilience.fault", site=site, kind=spec.kind,
                      occurrence=occurrence, plan=plan.name,
                      seed=plan.seed):
            pass
        obs.registry().counter(
            "resilience_faults_injected_total",
            labels={"site": site, "kind": spec.kind},
            help="faults injected by the chaos harness").inc()
    except Exception:
        # fault injection must never be broken by telemetry teardown
        # ordering (e.g. interpreter shutdown)
        pass


def note_recovery(site, kind, **attrs):
    """Record a successful recovery from a (possibly injected) fault —
    checkpoint fallback-to-last-good, decode evict-and-requeue, a retry
    that eventually succeeded.  Same span/metric channel as injections
    so the chaos report pairs every fault with its recovery."""
    try:
        from paddle_tpu import observability as obs
        with obs.span("resilience.recovery", site=site, kind=kind,
                      **attrs):
            pass
        obs.registry().counter(
            "resilience_recoveries_total",
            labels={"site": site, "kind": kind},
            help="successful recoveries from faults").inc()
    except Exception:
        pass
