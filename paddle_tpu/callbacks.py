"""paddle.callbacks parity (reference: python/paddle/callbacks.py is a
re-export of the hapi callbacks)."""
from paddle_tpu.hapi.callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
    ReduceLROnPlateau,
    VisualDL,
    WandbCallback,
)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "VisualDL", "ReduceLROnPlateau",
           "WandbCallback"]
