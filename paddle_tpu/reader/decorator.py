"""paddle.reader.decorator submodule path (reference keeps the
decorators importable both as paddle.reader.* and
paddle.reader.decorator.*)."""
from paddle_tpu.reader import (  # noqa: F401
    buffered,
    cache,
    chain,
    compose,
    firstn,
    map_readers,
    multiprocess_reader,
    shuffle,
    xmap_readers,
)
