"""paddle.reader — legacy reader decorators (reference:
python/paddle/reader/decorator.py). A *reader* is a zero-arg callable
returning an iterable of samples; decorators compose them. Kept for
migrating fluid-era input pipelines — new code uses paddle.io.
DataLoader (which these can feed through an IterableDataset).
"""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers", "multiprocess_reader"]


def cache(reader):
    """Cache the first COMPLETED pass in memory; later passes replay it
    (reference decorator.py:47). A partially-consumed first pass (e.g.
    under firstn, or an early epoch break) leaves the cache unarmed
    instead of committing a truncated/duplicated prefix."""
    all_data = []
    filled = [False]

    def _impl():
        if filled[0]:
            yield from all_data
            return
        data = []
        for item in reader():
            data.append(item)
            yield item
        # commit only on full consumption
        all_data[:] = data
        filled[0] = True

    return _impl


def map_readers(func, *readers):
    """Zip several readers and map `func` over the tuples
    (reference decorator.py:87)."""
    def _impl():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return _impl


def shuffle(reader, buf_size):
    """Buffered shuffle (reference decorator.py:129)."""
    def _impl():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return _impl


def chain(*readers):
    """Concatenate readers (reference decorator.py:178)."""
    def _impl():
        for r in readers:
            yield from r()

    return _impl


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples (reference decorator.py:243).
    check_alignment=True (default) raises when lengths differ."""
    check_alignment = kwargs.pop("check_alignment", True)

    def _make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    _HOLE = object()

    def _impl():
        rs = [r() for r in readers]
        for vals in itertools.zip_longest(*rs, fillvalue=_HOLE):
            holes = sum(v is _HOLE for v in vals)
            if holes and check_alignment:
                # zip_longest sees the ragged round regardless of which
                # reader is longer (plain zip would eat the extra item)
                raise RuntimeError(
                    "compose: readers have different lengths "
                    "(check_alignment=True)")
            yield sum((_make_tuple(v) for v in vals if v is not _HOLE),
                      ())

    return _impl


def buffered(reader, size):
    """Background-thread prefetch buffer (reference decorator.py:301).
    Source errors re-raise in the CONSUMER (a mid-stream failure must
    not masquerade as a clean shorter stream)."""
    end = object()
    err = object()

    def _impl():
        q = _queue.Queue(maxsize=size)

        def fill():
            try:
                for item in reader():
                    q.put(item)
            except BaseException as e:  # noqa: BLE001 — forwarded
                q.put((err, e))
                return
            q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                return
            if isinstance(item, tuple) and len(item) == 2 \
                    and item[0] is err:
                raise item[1]
            yield item

    return _impl


def firstn(reader, n):
    """First n samples (reference decorator.py:363)."""
    def _impl():
        return itertools.islice(reader(), n)

    return _impl


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker THREADS (reference
    decorator.py:408 uses threads too; the name is historical). With
    order=True results keep input order. A mapper or source exception
    re-raises in the consumer instead of hanging the pipeline."""
    end = object()

    def _impl():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        results = {}
        lock = threading.Condition()
        done_workers = [0]
        failure = [None]
        next_idx = [0]     # ordered mode: the index the consumer needs

        def fail(e):
            with lock:
                if failure[0] is None:
                    failure[0] = e
                lock.notify_all()

        def feed():
            try:
                for i, item in enumerate(reader()):
                    in_q.put((i, item))
            except BaseException as e:  # noqa: BLE001 — forwarded
                fail(e)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            try:
                while True:
                    task = in_q.get()
                    if task is end:
                        return
                    i, item = task
                    mapped = mapper(item)
                    if order:
                        with lock:
                            # bounded like the unordered path: an
                            # out-of-order completion waits while the
                            # buffer is full — EXCEPT the one index the
                            # consumer is blocked on (admitting it is
                            # what unblocks the pipeline; refusing it
                            # would deadlock at results == buffer_size)
                            while (len(results) >= buffer_size
                                   and i != next_idx[0]
                                   and failure[0] is None):
                                lock.wait(0.05)
                            if failure[0] is not None:
                                return
                            results[i] = mapped
                            lock.notify_all()
                    else:
                        out_q.put(mapped)
            except BaseException as e:  # noqa: BLE001 — forwarded
                fail(e)
            finally:
                with lock:
                    done_workers[0] += 1
                    lock.notify_all()

        threads = [threading.Thread(target=feed, daemon=True)] + \
            [threading.Thread(target=work, daemon=True)
             for _ in range(process_num)]
        for t in threads:
            t.start()
        if order:
            i = 0
            while True:
                with lock:
                    while i not in results:
                        if failure[0] is not None:
                            raise failure[0]
                        if done_workers[0] == process_num and \
                                i not in results:
                            return
                        lock.wait(0.05)
                    item = results.pop(i)
                    next_idx[0] = i + 1
                    lock.notify_all()   # wake workers blocked on a
                yield item              # full results buffer
                i += 1
        else:
            while True:
                if failure[0] is not None:
                    raise failure[0]
                try:
                    yield out_q.get(timeout=0.05)
                except _queue.Empty:
                    if failure[0] is not None:
                        raise failure[0]
                    if done_workers[0] == process_num and out_q.empty():
                        return

    return _impl


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Reference decorator.py:504 interleaves readers from worker
    processes; here the readers run in threads (samples may be jax/host
    arrays that must not cross a fork) and interleave round-robin."""
    _END = object()

    def _impl():
        its = [r() for r in readers]
        while its:
            nxt = []
            for it in its:
                item = next(it, _END)    # None is a legitimate sample
                if item is not _END:
                    yield item
                    nxt.append(it)
            its = nxt

    return _impl
