"""Orbax-backed checkpointing: async, sharding-aware, multi-host.

Reference parity: the reference's checkpoint/resume stack
(python/paddle/framework/io.py + fleet checkpointing utilities): rank 0
serializes state_dicts; distributed runs save per-rank shards. TPU-native
design: Orbax writes each jax.Array directly from its device shards (every
host writes only the shards it owns — no gather), asynchronously off the
training thread; restore re-shards to the target Mesh layout. paddle.save/
load stays for small pickle state_dicts; this is the scale path.
"""
from __future__ import annotations

import os

import jax
import numpy as np

from paddle_tpu.core.tensor import Tensor

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False


def _require_orbax():
    if not _HAS_ORBAX:
        raise RuntimeError("orbax-checkpoint is not installed")


def _to_pytree(obj):
    """Tensors -> jax.Arrays (zero-copy), leave other leaves alone."""
    if isinstance(obj, Tensor):
        return obj._value
    if isinstance(obj, dict):
        return {k: _to_pytree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_pytree(v) for v in obj]
    return obj


def _apply_state(target, loaded):
    """Write loaded values back into a Tensor-bearing state_dict; plain
    (immutable jax/np) array leaves are REPLACED in their containers."""
    if isinstance(target, Tensor):
        import jax.numpy as jnp
        target._set_value(jnp.asarray(loaded).astype(target._value.dtype))
        return target
    if isinstance(target, dict):
        missing = [k for k in target if k not in loaded]
        if missing:
            import warnings
            warnings.warn(f"checkpoint restore: {len(missing)} target keys "
                          f"not in checkpoint (e.g. {missing[:3]}) keep "
                          "their current values")
        for k in target:
            if k in loaded:
                target[k] = _apply_state(target[k], loaded[k])
        return target
    if isinstance(target, (list, tuple)):
        if len(loaded) != len(target):
            import warnings
            warnings.warn(f"checkpoint restore: sequence length mismatch "
                          f"(target {len(target)} vs loaded {len(loaded)})")
        out = [_apply_state(t, l) for t, l in zip(target, loaded)]
        if isinstance(target, tuple):
            return tuple(out)
        target[:len(out)] = out
        return target
    return loaded


def save_checkpoint(state, path, async_save=False):
    """Save a (possibly Tensor-bearing, possibly sharded) pytree.

    async_save=True returns immediately; the write completes in background
    threads (call wait_until_finished() on the returned checkpointer before
    process exit)."""
    _require_orbax()
    path = os.path.abspath(path)
    ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler()) \
        if async_save else ocp.StandardCheckpointer()
    ckptr.save(path, _to_pytree(state), force=True)
    if not async_save:
        ckptr.wait_until_finished()
    return ckptr


def _abstract_tree(tpl):
    """ShapeDtypeStruct template (with shardings) for a restore target."""
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(
            np.shape(a), a.dtype, sharding=getattr(a, "sharding", None))
        if hasattr(a, "dtype") else a, tpl)


def load_checkpoint(path, target=None):
    """Restore a checkpoint. With `target` (a Tensor-bearing state_dict or
    pytree of arrays), values restore INTO it — sharded arrays resume with
    their target shardings; without, returns a pytree of np arrays."""
    _require_orbax()
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if target is None:
        loaded = ckptr.restore(path)
        return jax.tree_util.tree_map(np.asarray, loaded)
    loaded = ckptr.restore(path, _abstract_tree(_to_pytree(target)))
    return _apply_state(target, loaded)


class CheckpointManager:
    """Step-indexed manager (reference analogue: fleet save/load with
    retained checkpoints): rotation via max_to_keep, optional async saves,
    automatic latest-step resume."""

    def __init__(self, directory, max_to_keep=5, async_save=True,
                 save_interval_steps=1):
        _require_orbax()
        self.directory = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, enable_async_checkpointing=async_save,
            save_interval_steps=save_interval_steps)
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step, state):
        return self._mgr.save(step, args=ocp.args.StandardSave(
            _to_pytree(state)))

    def restore(self, step=None, target=None):
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        if target is None:
            loaded = self._mgr.restore(step)
            return jax.tree_util.tree_map(np.asarray, loaded)
        loaded = self._mgr.restore(step, args=ocp.args.StandardRestore(
            _abstract_tree(_to_pytree(target))))
        return _apply_state(target, loaded)

    def latest_step(self):
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()
