"""paddle.utils.dlpack parity (reference: python/paddle/utils/dlpack.py):
zero-copy tensor interchange with other frameworks via the DLPack
protocol (torch, numpy, cupy...)."""
from __future__ import annotations

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor -> DLPack-protocol object (zero-copy where the backend
    allows).  Returned object implements ``__dlpack__``/
    ``__dlpack_device__`` — the modern protocol form every consumer
    (torch.from_dlpack, np.from_dlpack, jax) accepts; the reference's
    legacy PyCapsule form is produced by calling ``__dlpack__()`` on it."""
    from paddle_tpu.core.tensor import Tensor
    return x._value if isinstance(x, Tensor) else x


def from_dlpack(dlpack):
    """__dlpack__-bearing object (torch/numpy/jax array...) -> Tensor."""
    import jax

    from paddle_tpu.core.tensor import Tensor
    if not hasattr(dlpack, "__dlpack__"):
        raise TypeError(
            "from_dlpack needs an object implementing the DLPack protocol "
            "(__dlpack__/__dlpack_device__); legacy bare PyCapsules cannot "
            "be re-imported — pass the producing array itself")
    return Tensor(jax.dlpack.from_dlpack(dlpack))
