"""Misc utils. Reference: python/paddle/utils/__init__.py."""
from __future__ import annotations

from paddle_tpu.utils import dlpack  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(f"{name} is required: {e}") from e


def run_check():
    """paddle.utils.run_check parity: verify the TPU backend works."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as P
    x = P.ones([2, 2])
    y = (x @ x).numpy()
    assert y.shape == (2, 2)
    devs = jax.devices()
    print(f"paddle_tpu is installed successfully! backend={jax.default_backend()} "
          f"devices={devs}")
    return True


def unique_name(prefix="tmp"):
    from paddle_tpu.core.tensor import Tensor
    Tensor._tensor_id[0] += 1
    return f"{prefix}_{Tensor._tensor_id[0]}"


class deprecated:
    def __init__(self, update_to="", since="", reason=""):
        self.update_to = update_to

    def __call__(self, fn):
        return fn
