"""Misc utils. Reference: python/paddle/utils/__init__.py."""
from __future__ import annotations

from paddle_tpu.utils import cpp_extension, custom_op, dlpack  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(f"{name} is required: {e}") from e


def run_check():
    """paddle.utils.run_check parity: verify the TPU backend works."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as P
    x = P.ones([2, 2])
    y = (x @ x).numpy()
    assert y.shape == (2, 2)
    devs = jax.devices()
    print(f"paddle_tpu is installed successfully! backend={jax.default_backend()} "
          f"devices={devs}")
    return True


def unique_name(prefix="tmp"):
    from paddle_tpu.core.tensor import Tensor
    Tensor._tensor_id[0] += 1
    return f"{prefix}_{Tensor._tensor_id[0]}"


class deprecated:
    def __init__(self, update_to="", since="", reason=""):
        self.update_to = update_to

    def __call__(self, fn):
        return fn


# reference paddle.utils also surfaces these directly
from paddle_tpu.utils.dlpack import from_dlpack, to_dlpack  # noqa: E402,F401


def flops(net, input_size, custom_ops=None, print_detail=False):
    import paddle_tpu
    return paddle_tpu.flops(net, input_size, custom_ops, print_detail)


_flops_registry = {}


def register_flops(op_type):
    """Register a custom per-layer FLOPs counter (reference
    utils/flops.py registry)."""
    def deco(fn):
        _flops_registry[op_type] = fn
        return fn
    return deco


def generate(key=""):
    """paddle.utils.unique_name.generate parity: scoped by guard/switch
    (see the _NameScope machinery below)."""
    return _name_scope[0].generate(key or "tmp")


def require_version(min_version, max_version=None):
    """Version gate (reference utils/__init__.py require_version) against
    this framework's version string."""
    import paddle_tpu

    def parse(v):
        parts = []
        for p in str(v).split(".")[:3]:
            digits = "".join(c for c in p if c.isdigit())
            parts.append(int(digits) if digits else 0)
        return tuple(parts)

    def pad(t, n):
        return t + (0,) * (n - len(t))

    cur = parse(getattr(paddle_tpu, "__version__", "0.0.0"))
    mn = parse(min_version)
    if pad(mn, 3) > pad(cur, 3):
        raise Exception(
            f"installed version {cur} < required minimum {min_version}")
    if max_version is not None:
        mx = parse(max_version)
        # 'max 2.1' admits every 2.1.x: compare at the max's precision
        if mx < cur[:len(mx)]:
            raise Exception(
                f"installed version {cur} > required maximum {max_version}")


class ProfilerOptions:
    """Legacy fluid profiler options bag (reference utils/profiler.py)."""

    def __init__(self, options=None):
        self.options = dict(options or {})

    def get(self, key, default=None):
        return self.options.get(key, default)


class Profiler:
    """Legacy profiler facade routing to paddle_tpu.profiler.Profiler."""

    def __init__(self, enabled=True, options=None):
        from paddle_tpu.profiler import Profiler as _P
        self._p = _P()
        self._enabled = enabled

    def __enter__(self):
        if self._enabled:
            self._p.start()
        return self

    def __exit__(self, *exc):
        if self._enabled:
            self._p.stop()
        return False


_legacy_profiler = [None]


def get_profiler(options=None):
    if _legacy_profiler[0] is None:
        _legacy_profiler[0] = Profiler(options=options)
    return _legacy_profiler[0]


def start_profiler(state="All", tracer_option="Default"):
    get_profiler()._p.start()


def stop_profiler(sorted_key=None, profile_path=None):
    p = _legacy_profiler[0]
    if p is not None:
        p._p.stop()
        _legacy_profiler[0] = None


def reset_profiler():
    _legacy_profiler[0] = None


def cuda_profiler(*a, **kw):
    raise RuntimeError("cuda_profiler has no TPU analogue; use "
                       "paddle_tpu.profiler (jax.profiler traces)")


# ---- unique_name scoping (reference utils/unique_name.py: generate /
# guard / switch over a per-scope counter map) --------------------------
class _NameScope:
    def __init__(self):
        self.counters = {}

    def generate(self, key):
        n = self.counters.get(key, 0)
        self.counters[key] = n + 1
        return f"{key}_{n}"


_name_scope = [_NameScope()]


def switch(new_generator=None):
    """Swap the active unique-name scope, returning the previous one."""
    old = _name_scope[0]
    _name_scope[0] = new_generator if new_generator is not None \
        else _NameScope()
    return old


class guard:
    """Context manager: names generated inside restart from a fresh (or
    given) scope, restoring the outer scope on exit."""

    def __init__(self, new_generator=None):
        self._new = new_generator
        self._old = None

    def __enter__(self):
        self._old = switch(self._new)
        return self

    def __exit__(self, *exc):
        switch(self._old)
        return False


def get_weights_path_from_url(url, md5sum=None):
    """Reference utils/download.py:79: resolve a pretrained-weights URL to
    a local cache path.  This environment has zero egress, so only the
    cache-hit path works: the file must already be under WEIGHTS_HOME
    (~/.cache/paddle_tpu/hapi/weights or $WEIGHTS_HOME)."""
    import os
    home = os.environ.get(
        "WEIGHTS_HOME",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "hapi", "weights"))
    fname = os.path.basename(str(url).split("?")[0])
    path = os.path.join(home, fname)
    if os.path.exists(path):
        return path
    raise RuntimeError(
        f"weights for {url!r} not found at {path}; this build has no "
        "network egress — place the file there (or set WEIGHTS_HOME)")


from paddle_tpu.utils import profiler  # noqa: E402,F401
