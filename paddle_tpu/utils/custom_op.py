"""Public custom-op registration — the TPU-native cpp_extension story.

Reference: python/paddle/utils/cpp_extension/cpp_extension.py — the
reference's custom-op path compiles user C++/CUDA kernels and registers
them as framework ops with gradients. On a TPU system the compute-path
analogue is a Pallas (or plain jnp) kernel registered as a paddle_tpu op
with a VJP; host-side C++ remains available through
``paddle_tpu.utils.cpp_extension`` (ctypes + pure_callback).

Usage::

    import jax.numpy as jnp
    from paddle_tpu.utils.custom_op import register_custom_op

    def silu_fwd(x):                 # pure fn over jnp arrays —
        return x * jax.nn.sigmoid(x) # or a pl.pallas_call kernel

    def silu_bwd(saved, grads):
        (x,) = saved
        (g,) = grads
        s = jax.nn.sigmoid(x)
        return (g * (s + x * s * (1 - s)),)

    my_silu = register_custom_op("my_silu", silu_fwd, backward=silu_bwd)
    y = my_silu(tensor)              # eager: recorded on the tape
    # ... and inside @to_static it traces into the XLA program.

The op works in BOTH execution modes for free: eagerly each call runs
through core/dispatch.apply (tape-recorded, ``backward()`` uses the
custom VJP); under ``to_static`` the same function traces into the
single-program XLA compile. ``backward=None`` falls back to jax's
autodiff of the forward — register a backward only when autodiff can't
differentiate the kernel (e.g. a Pallas call without a built-in VJP) or
a custom gradient is wanted.
"""
from __future__ import annotations

import jax

from paddle_tpu.core.dispatch import apply

__all__ = ["register_custom_op", "get_custom_op", "custom_ops"]

custom_ops = {}


def register_custom_op(name, forward, backward=None, nondiff_args=()):
    """Register `forward` as a framework op; returns the Tensor-level
    callable (also retrievable via get_custom_op(name)).

    forward(*arrays) -> array | tuple of arrays — pure over jnp arrays
        (jnp ops, lax, or pl.pallas_call kernels).
    backward(saved_inputs, output_cotangents) -> input cotangent tuple,
        one entry per differentiable forward argument (None entries are
        allowed). When omitted, jax.vjp differentiates the forward.
    nondiff_args: indices of non-array / configuration arguments (static
        under jit, excluded from the VJP).
    """
    if name in custom_ops:
        raise ValueError(f"custom op {name!r} already registered")

    if backward is None:
        kernel = forward
    else:
        core = jax.custom_vjp(forward, nondiff_argnums=tuple(nondiff_args))
        nd = set(nondiff_args)

        def fwd(*args):
            out = forward(*args)
            # residuals: differentiable args only (static args reach bwd
            # as leading positionals via nondiff_argnums)
            return out, tuple(a for i, a in enumerate(args) if i not in nd)

        def bwd(*res_and_cot):
            *static, saved, cot = res_and_cot
            cots = cot if isinstance(cot, tuple) else (cot,)
            grads = backward(saved, cots)
            # None entries mean "no gradient": custom_vjp requires a
            # cotangent matching the primal, so materialize zeros
            return tuple(
                jax.numpy.zeros_like(s) if g is None else g
                for g, s in zip(grads, saved))

        core.defvjp(fwd, bwd)
        kernel = core

    def op(*tensors, **kwargs):
        return apply(kernel, *tensors, **kwargs)

    op.__name__ = name
    op._forward = forward
    op._backward = backward
    custom_ops[name] = op
    return op


def get_custom_op(name):
    try:
        return custom_ops[name]
    except KeyError:
        raise KeyError(
            f"no custom op {name!r}; registered: {sorted(custom_ops)}"
        ) from None
