"""Legacy `paddle.utils.profiler` module surface (reference:
python/paddle/utils/profiler.py) — routes to the modern
paddle_tpu.profiler jax-trace profiler via the facades in utils."""
from paddle_tpu.utils import (  # noqa: F401
    Profiler,
    ProfilerOptions,
    cuda_profiler,
    get_profiler,
    reset_profiler,
    start_profiler,
    stop_profiler,
)

__all__ = ["ProfilerOptions", "Profiler", "get_profiler", "start_profiler",
           "stop_profiler", "reset_profiler", "cuda_profiler"]
