"""C++ extension loading — host-side native custom ops.

Reference: python/paddle/utils/cpp_extension/cpp_extension.py (load /
setup / CppExtension / CUDAExtension: compile user C++ into framework
ops). TPU-native split: device compute belongs to Pallas/jnp custom ops
(utils/custom_op.py); what legitimately stays native is HOST-side work —
tokenizers, samplers, feature extraction, IO — and that is exactly what
this module compiles. ``load`` builds the sources with g++ into a shared
library (the same toolchain path as paddle_tpu/native/*.cc) and returns
a ctypes handle; ``as_host_op`` lifts an exported C function operating
on float32 buffers into a jit-safe framework op via
``jax.pure_callback``, so compiled C++ runs inside a traced program at
the host boundary.

Expected C signature for ``as_host_op``::

    extern "C" void my_op(const float* in, float* out, long n);

CUDAExtension has no meaning on a TPU system and raises.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

__all__ = ["load", "CppExtension", "CUDAExtension", "setup", "as_host_op",
           "get_build_directory"]

_DEFAULT_BUILD_DIR = os.path.join(tempfile.gettempdir(),
                                  "paddle_tpu_extensions")


def get_build_directory():
    os.makedirs(_DEFAULT_BUILD_DIR, exist_ok=True)
    return _DEFAULT_BUILD_DIR


class CppExtension:
    def __init__(self, sources, *args, **kwargs):
        self.sources = list(sources)
        self.extra_compile_args = kwargs.get("extra_compile_args") or []
        self.include_dirs = kwargs.get("include_dirs") or []


def CUDAExtension(*args, **kwargs):
    raise RuntimeError(
        "CUDAExtension has no TPU analogue — write device kernels as "
        "Pallas custom ops (paddle_tpu.utils.custom_op.register_custom_op)"
        " and keep C++ for host-side work via CppExtension/load")


def load(name, sources, extra_cxx_cflags=None, extra_include_paths=None,
         build_directory=None, verbose=False, **kwargs):
    """Compile C++ `sources` into <build_dir>/lib<name>.so and return the
    ctypes.CDLL handle. Caching: recompiles only when a source is newer
    than the built library."""
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    out = os.path.join(build_dir, f"lib{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    fresh = os.path.exists(out) and all(
        os.path.getmtime(out) >= os.path.getmtime(s) for s in srcs)
    if not fresh:
        cmd = (["g++", "-O2", "-fPIC", "-shared", "-std=c++17"]
               + [f"-I{d}" for d in (extra_include_paths or [])]
               + (extra_cxx_cflags or [])
               + srcs + ["-o", out])
        if verbose:
            print(" ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"cpp_extension build failed:\n{proc.stderr[-4000:]}")
    return ctypes.CDLL(out)


def setup(name=None, ext_modules=None, **kwargs):
    """setuptools-style entry: build every CppExtension immediately
    (the reference generates a python wheel; here the shared library in
    the build directory IS the artifact — import it with `load`)."""
    exts = ext_modules or []
    if not isinstance(exts, (list, tuple)):
        exts = [exts]
    libs = {}
    for i, ext in enumerate(exts):
        # `name` maps to the lib only when unambiguous; multiple
        # extensions get indexed names so none overwrites another
        ext_name = name if (name and len(exts) == 1) else \
            f"{name or 'ext'}_{i}"
        libs[ext_name] = load(ext_name, ext.sources,
                              extra_cxx_cflags=ext.extra_compile_args,
                              extra_include_paths=ext.include_dirs)
    return libs


def as_host_op(lib, symbol, out_shape_fn=None):
    """Lift `extern "C" void f(const float*, float*, long)` into a
    framework op usable eagerly AND inside jit (via jax.pure_callback —
    the op runs on host at a callback boundary; XLA overlaps transfers).

    out_shape_fn(in_shape) -> out_shape; defaults to same-shape.
    """
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.dispatch import apply

    cfun = getattr(lib, symbol)
    cfun.restype = None
    cfun.argtypes = [ctypes.POINTER(ctypes.c_float),
                     ctypes.POINTER(ctypes.c_float), ctypes.c_long]

    def host(x):
        x = np.ascontiguousarray(np.asarray(x, np.float32))
        shape = out_shape_fn(x.shape) if out_shape_fn else x.shape
        out = np.empty(shape, np.float32)
        cfun(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
             out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
             ctypes.c_long(x.size))
        return out

    def fn(xv):
        shape = out_shape_fn(xv.shape) if out_shape_fn else xv.shape
        return jax.pure_callback(
            host, jax.ShapeDtypeStruct(tuple(shape), jnp.float32), xv)

    def op(x):
        return apply(fn, x)

    op.__name__ = symbol
    return op
