"""paddle_tpu.fft — discrete Fourier transforms.

Reference: python/paddle/fft.py (~1300 lines over phi fft kernels/cuFFT).
TPU-native: jnp.fft (XLA FFT HLO). Norm conventions follow the reference:
"backward" (default), "ortho", "forward".
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply


def _norm(norm):
    if norm is None:
        return "backward"
    if norm not in ("backward", "ortho", "forward"):
        raise ValueError(f"invalid norm {norm!r}")
    return norm


def _wrap1(fn):
    def op(x, n=None, axis=-1, norm=None, name=None):
        return apply(lambda v: fn(v, n=n, axis=axis, norm=_norm(norm)), x)
    return op


def _wrap2(fn):
    def op(x, s=None, axes=(-2, -1), norm=None, name=None):
        return apply(lambda v: fn(v, s=s, axes=axes, norm=_norm(norm)), x)
    return op


def _wrapn(fn):
    def op(x, s=None, axes=None, norm=None, name=None):
        return apply(lambda v: fn(v, s=s, axes=axes, norm=_norm(norm)), x)
    return op


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)

fft2 = _wrap2(jnp.fft.fft2)
ifft2 = _wrap2(jnp.fft.ifft2)
rfft2 = _wrap2(jnp.fft.rfft2)
irfft2 = _wrap2(jnp.fft.irfft2)

fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def _swap_norm(norm):
    # hfft(x, norm) == irfft(conj(x), swapped norm) — the forward-style
    # Hermitian transform carries the inverse transform's scaling swapped
    return {"backward": "forward", "forward": "backward",
            "ortho": "ortho"}[_norm(norm)]


def hfft2(x, s=None, axes=(-2, -1), norm=None, name=None):
    """2-D FFT of Hermitian-symmetric input -> real output with last
    transformed dim 2*(m-1) (paddle/scipy semantics)."""
    return apply(lambda v: jnp.fft.irfft2(
        jnp.conj(v), s=s, axes=axes, norm=_swap_norm(norm)), x)


def ihfft2(x, s=None, axes=(-2, -1), norm=None, name=None):
    """Inverse of hfft2: real input -> Hermitian half-spectrum
    (last transformed dim m//2+1)."""
    return apply(lambda v: jnp.conj(
        jnp.fft.rfft2(v, s=s, axes=axes, norm=_swap_norm(norm))), x)


def hfftn(x, s=None, axes=None, norm=None, name=None):
    return apply(lambda v: jnp.fft.irfftn(
        jnp.conj(v), s=s, axes=axes, norm=_swap_norm(norm)), x)


def ihfftn(x, s=None, axes=None, norm=None, name=None):
    return apply(lambda v: jnp.conj(
        jnp.fft.rfftn(v, s=s, axes=axes, norm=_swap_norm(norm))), x)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from paddle_tpu.core.tensor import Tensor
    out = jnp.fft.fftfreq(n, d)
    return Tensor(out.astype(dtype) if dtype else out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from paddle_tpu.core.tensor import Tensor
    out = jnp.fft.rfftfreq(n, d)
    return Tensor(out.astype(dtype) if dtype else out)


def fftshift(x, axes=None, name=None):
    return apply(lambda v: jnp.fft.fftshift(v, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply(lambda v: jnp.fft.ifftshift(v, axes=axes), x)
