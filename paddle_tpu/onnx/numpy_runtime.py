"""Pure-numpy evaluator for the ONNX subset the exporter emits.

Lets exported models be executed and round-trip-verified with no
onnxruntime dependency (this image has none). Covers exactly the ops
export.py can produce; anything else raises.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.onnx import onnx_pb2 as pb

_NP_OF = {
    pb.TensorProto.FLOAT: np.float32,
    pb.TensorProto.DOUBLE: np.float64,
    pb.TensorProto.INT32: np.int32,
    pb.TensorProto.INT64: np.int64,
    pb.TensorProto.BOOL: np.bool_,
    pb.TensorProto.INT8: np.int8,
    pb.TensorProto.UINT8: np.uint8,
    pb.TensorProto.FLOAT16: np.float16,
}


def tensor_to_np(t):
    dt = _NP_OF[t.data_type]
    if t.raw_data:
        return np.frombuffer(t.raw_data, dt).reshape(list(t.dims)).copy()
    if t.float_data:
        return np.asarray(t.float_data, dt).reshape(list(t.dims))
    if t.int64_data:
        return np.asarray(t.int64_data, dt).reshape(list(t.dims))
    if t.int32_data:
        return np.asarray(t.int32_data, dt).reshape(list(t.dims))
    return np.zeros(list(t.dims), dt)


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == pb.AttributeProto.INT:
            out[a.name] = int(a.i)
        elif a.type == pb.AttributeProto.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == pb.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == pb.AttributeProto.INTS:
            out[a.name] = list(a.ints)
    return out


def _pool(x, ks, strides, pads, mode):
    n, c, h, w = x.shape
    ph0, pw0, ph1, pw1 = pads
    fill = -np.inf if mode == "max" else 0.0
    xp = np.full((n, c, h + ph0 + ph1, w + pw0 + pw1), fill, x.dtype)
    xp[:, :, ph0:ph0 + h, pw0:pw0 + w] = x
    kh, kw = ks
    sh, sw = strides
    oh = (xp.shape[2] - kh) // sh + 1
    ow = (xp.shape[3] - kw) // sw + 1
    out = np.empty((n, c, oh, ow), x.dtype)
    for i in range(oh):
        for j in range(ow):
            win = xp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
            out[:, :, i, j] = win.max((2, 3)) if mode == "max" \
                else win.mean((2, 3))
    return out


def _conv(x, w, b, attrs):
    group = attrs.get("group", 1)
    strides = attrs.get("strides", [1, 1])
    dil = attrs.get("dilations", [1, 1])
    pads = attrs.get("pads", [0, 0, 0, 0])
    n, cin, h, wdt = x.shape
    cout, cpg, kh, kw = w.shape
    xp = np.zeros((n, cin, h + pads[0] + pads[2], wdt + pads[1] + pads[3]),
                  x.dtype)
    xp[:, :, pads[0]:pads[0] + h, pads[1]:pads[1] + wdt] = x
    oh = (xp.shape[2] - ((kh - 1) * dil[0] + 1)) // strides[0] + 1
    ow = (xp.shape[3] - ((kw - 1) * dil[1] + 1)) // strides[1] + 1
    out = np.zeros((n, cout, oh, ow), np.float32)
    opg = cout // group
    for g in range(group):
        xg = xp[:, g * cpg:(g + 1) * cpg]
        wg = w[g * opg:(g + 1) * opg]
        for i in range(oh):
            for j in range(ow):
                hi = i * strides[0]
                wj = j * strides[1]
                win = xg[:, :, hi:hi + (kh - 1) * dil[0] + 1:dil[0],
                         wj:wj + (kw - 1) * dil[1] + 1:dil[1]]
                out[:, g * opg:(g + 1) * opg, i, j] = np.einsum(
                    "nchw,ochw->no", win, wg)
    if b is not None:
        out = out + b[None, :, None, None]
    return out.astype(x.dtype)


def run(model_bytes_or_path, inputs):
    """Execute an exported model. inputs: list of np arrays (positional,
    matching graph inputs). Returns list of outputs."""
    import os
    if isinstance(model_bytes_or_path, (str, os.PathLike)):
        data = open(model_bytes_or_path, "rb").read()
    else:
        data = model_bytes_or_path
    model = pb.ModelProto()
    model.ParseFromString(data)
    g = model.graph
    env = {t.name: tensor_to_np(t) for t in g.initializer}
    for vi, arr in zip(g.input, inputs):
        env[vi.name] = np.asarray(arr)

    for node in g.node:
        a = _attrs(node)
        x = [env[i] for i in node.input]
        op = node.op_type
        if op == "Identity":
            y = x[0]
        elif op == "Add":
            y = x[0] + x[1]
        elif op == "Sub":
            y = x[0] - x[1]
        elif op == "Mul":
            y = x[0] * x[1]
        elif op == "Div":
            if np.issubdtype(x[0].dtype, np.integer) and \
                    np.issubdtype(x[1].dtype, np.integer):
                # ONNX integer Div truncates toward zero (C semantics)
                y = np.trunc(x[0] / x[1]).astype(x[0].dtype)
            else:
                y = x[0] / x[1]
        elif op == "Pow":
            y = x[0] ** x[1]
        elif op == "Neg":
            y = -x[0]
        elif op == "Max":
            y = np.maximum(x[0], x[1])
        elif op == "Min":
            y = np.minimum(x[0], x[1])
        elif op == "Exp":
            y = np.exp(x[0])
        elif op == "Log":
            y = np.log(x[0])
        elif op == "Tanh":
            y = np.tanh(x[0])
        elif op == "Sin":
            y = np.sin(x[0])
        elif op == "Cos":
            y = np.cos(x[0])
        elif op == "Sqrt":
            y = np.sqrt(x[0])
        elif op == "Reciprocal":
            y = 1.0 / x[0]
        elif op == "Abs":
            y = np.abs(x[0])
        elif op == "Sign":
            y = np.sign(x[0])
        elif op == "Floor":
            y = np.floor(x[0])
        elif op == "Ceil":
            y = np.ceil(x[0])
        elif op == "Sigmoid":
            y = 1.0 / (1.0 + np.exp(-x[0]))
        elif op == "Erf":
            from math import erf
            y = np.vectorize(erf)(x[0]).astype(x[0].dtype)
        elif op == "Equal":
            y = x[0] == x[1]
        elif op == "Less":
            y = x[0] < x[1]
        elif op == "LessOrEqual":
            y = x[0] <= x[1]
        elif op == "Greater":
            y = x[0] > x[1]
        elif op == "GreaterOrEqual":
            y = x[0] >= x[1]
        elif op == "And":
            y = np.logical_and(x[0], x[1])
        elif op == "Or":
            y = np.logical_or(x[0], x[1])
        elif op == "Not":
            y = np.logical_not(x[0])
        elif op == "Where":
            y = np.where(x[0], x[1], x[2])
        elif op == "Einsum":
            y = np.einsum(a["equation"], *x)
        elif op == "Conv":
            y = _conv(x[0], x[1], x[2] if len(x) > 2 else None, a)
        elif op == "MaxPool":
            y = _pool(x[0], a["kernel_shape"], a["strides"],
                      a.get("pads", [0, 0, 0, 0]), "max")
        elif op == "AveragePool":
            y = _pool(x[0], a["kernel_shape"], a["strides"],
                      a.get("pads", [0, 0, 0, 0]), "avg")
        elif op == "ReduceSum":
            y = x[0].sum(tuple(a["axes"]),
                         keepdims=bool(a.get("keepdims", 1)))
        elif op == "ReduceMax":
            y = x[0].max(tuple(a["axes"]),
                         keepdims=bool(a.get("keepdims", 1)))
        elif op == "ReduceMin":
            y = x[0].min(tuple(a["axes"]),
                         keepdims=bool(a.get("keepdims", 1)))
        elif op == "Reshape":
            y = x[0].reshape([int(d) for d in x[1]])
        elif op == "Expand":
            y = np.broadcast_to(x[0], [int(d) for d in x[1]]).copy()
        elif op == "Transpose":
            y = np.transpose(x[0], a["perm"])
        elif op == "Concat":
            y = np.concatenate(x, axis=a["axis"])
        elif op == "Slice":
            starts, ends, axes, steps = (x[1], x[2], x[3], x[4])
            sl = [slice(None)] * x[0].ndim
            for s, e, ax, st in zip(starts, ends, axes, steps):
                sl[int(ax)] = slice(int(s), int(e), int(st))
            y = x[0][tuple(sl)]
        elif op == "Cast":
            y = x[0].astype(_NP_OF[a["to"]])
        elif op == "Gather":
            y = np.take(x[0], x[1].astype(np.int64), axis=a.get("axis", 0))
        elif op == "ArgMax":
            y = np.argmax(x[0], axis=a["axis"])
            if not a.get("keepdims", 1):
                pass
            else:
                y = np.expand_dims(y, a["axis"])
        elif op == "Pad":
            pads = x[1]
            nd = x[0].ndim
            widths = [(int(pads[i]), int(pads[i + nd])) for i in range(nd)]
            cval = x[2] if len(x) > 2 else 0
            y = np.pad(x[0], widths, constant_values=np.asarray(cval))
        else:
            raise NotImplementedError(f"numpy_runtime: op {op}")
        env[node.output[0]] = y

    return [env[vi.name] for vi in g.output]
