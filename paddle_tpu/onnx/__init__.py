"""paddle.onnx parity namespace (reference: python/paddle/onnx/export.py)."""
from paddle_tpu.onnx.export import export  # noqa: F401
from paddle_tpu.onnx import numpy_runtime  # noqa: F401
