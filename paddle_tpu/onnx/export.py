"""ONNX export: trace a Layer / function to an .onnx file.

Reference parity: python/paddle/onnx/export.py (paddle.onnx.export →
paddle2onnx over the static ProgramDesc). TPU-native redesign: the
source of truth is the JAXPR of the functionalized forward — the same
artifact to_static compiles — walked equation-by-equation into ONNX
nodes (opset 12). Model parameters become initializers; nested
pjit/custom-vjp calls are inlined. No onnx pip package is needed: the
serializer uses a protoc-generated binding of the public ONNX schema
subset (onnx.proto here, field numbers matching upstream so any ONNX
runtime loads the file), and paddle_tpu.onnx.numpy_runtime can execute
the emitted subset for verification without onnxruntime.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.onnx import onnx_pb2 as pb

__all__ = ["export"]

_DTYPE = {
    np.dtype(np.float32): pb.TensorProto.FLOAT,
    np.dtype(np.float64): pb.TensorProto.DOUBLE,
    np.dtype(np.int32): pb.TensorProto.INT32,
    np.dtype(np.int64): pb.TensorProto.INT64,
    np.dtype(np.bool_): pb.TensorProto.BOOL,
    np.dtype(np.int8): pb.TensorProto.INT8,
    np.dtype(np.uint8): pb.TensorProto.UINT8,
    np.dtype(np.float16): pb.TensorProto.FLOAT16,
}


def _np_dtype(aval_dtype):
    d = np.dtype(aval_dtype) if aval_dtype != jnp.bfloat16 else \
        np.dtype(np.float32)   # bf16 exported as f32 (ONNX rt coverage)
    return d


class _Graph:
    def __init__(self):
        self.g = pb.GraphProto(name="paddle_tpu")
        self.names = {}
        self.counter = [0]

    def fresh(self, hint="v"):
        self.counter[0] += 1
        return f"{hint}_{self.counter[0]}"

    def name_of(self, var):
        if var not in self.names:
            self.names[var] = self.fresh("t")
        return self.names[var]

    def tensor_proto(self, arr, name):
        arr = np.asarray(arr)
        if arr.dtype == jnp.bfloat16:
            arr = arr.astype(np.float32)
        t = pb.TensorProto(name=name, dims=list(arr.shape),
                           data_type=_DTYPE[np.dtype(arr.dtype)])
        t.raw_data = np.ascontiguousarray(arr).tobytes()
        return t

    def add_initializer(self, arr, hint="const"):
        name = self.fresh(hint)
        self.g.initializer.append(self.tensor_proto(arr, name))
        return name

    def node(self, op, inputs, **attrs):
        outs = [self.fresh(op.lower())]
        n = pb.NodeProto(op_type=op, input=list(inputs), output=outs,
                         name=self.fresh(op))
        for k, v in attrs.items():
            a = n.attribute.add()
            a.name = k
            if isinstance(v, int):
                a.type = pb.AttributeProto.INT
                a.i = v
            elif isinstance(v, float):
                a.type = pb.AttributeProto.FLOAT
                a.f = v
            elif isinstance(v, str):
                a.type = pb.AttributeProto.STRING
                a.s = v.encode()
            elif isinstance(v, (list, tuple)) and all(
                    isinstance(x, (int, np.integer)) for x in v):
                a.type = pb.AttributeProto.INTS
                a.ints.extend(int(x) for x in v)
            else:
                raise TypeError(f"attr {k}={v!r}")
        self.g.node.append(n)
        return outs[0]


def _value_info(name, aval):
    vi = pb.ValueInfoProto(name=name)
    tt = vi.type.tensor_type
    tt.elem_type = _DTYPE[_np_dtype(aval.dtype)]
    for s in aval.shape:
        tt.shape.dim.add().dim_value = int(s)
    return vi


# --------------------------------------------------------------- converters

def _conv(G, eqn, ins):
    p = eqn.params
    dn = p["dimension_numbers"]
    lhs_spec, rhs_spec, out_spec = dn.lhs_spec, dn.rhs_spec, dn.out_spec
    nd = len(lhs_spec)
    if (tuple(lhs_spec) != tuple(range(nd))
            or tuple(rhs_spec) != tuple(range(nd))
            or tuple(out_spec) != tuple(range(nd))):
        raise NotImplementedError(
            "onnx export supports NCHW/OIHW conv layouts only")
    if any(d != 1 for d in p["lhs_dilation"]):
        raise NotImplementedError("transposed conv export not supported")
    pads_lo = [pr[0] for pr in p["padding"]]
    pads_hi = [pr[1] for pr in p["padding"]]
    return G.node("Conv", ins,
                  strides=list(p["window_strides"]),
                  dilations=list(p["rhs_dilation"]),
                  pads=pads_lo + pads_hi,
                  group=int(p["feature_group_count"]))


def _dot_general(G, eqn, ins):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    ln, rn = len(eqn.invars[0].aval.shape), len(eqn.invars[1].aval.shape)
    letters = iter("abcdefghijklmnopqrstuvwxyz")
    l_sub = [None] * ln
    r_sub = [None] * rn
    for i, j in zip(lb, rb):
        c = next(letters)
        l_sub[i] = r_sub[j] = c
    for i, j in zip(lc, rc):
        c = next(letters)
        l_sub[i] = r_sub[j] = c
    for i in range(ln):
        if l_sub[i] is None:
            l_sub[i] = next(letters)
    for j in range(rn):
        if r_sub[j] is None:
            r_sub[j] = next(letters)
    out = [l_sub[i] for i in lb] + \
        [l_sub[i] for i in range(ln) if i not in lb and i not in lc] + \
        [r_sub[j] for j in range(rn) if j not in rb and j not in rc]
    eqn_str = f"{''.join(l_sub)},{''.join(r_sub)}->{''.join(out)}"
    return G.node("Einsum", ins, equation=eqn_str)


def _reduce_window(G, eqn, ins, kind):
    p = eqn.params
    wd = list(p["window_dimensions"])
    ws = list(p["window_strides"])
    pad = list(p["padding"])
    if len(wd) != 4 or wd[0] != 1 or wd[1] != 1:
        raise NotImplementedError("only NCHW spatial pooling exports")
    if any(d != 1 for d in p.get("base_dilation", (1,) * len(wd))) or \
            any(d != 1 for d in p.get("window_dilation", (1,) * len(wd))):
        raise NotImplementedError("dilated pooling export not supported")
    pads = [pad[2][0], pad[3][0], pad[2][1], pad[3][1]]
    if kind == "max":
        return G.node("MaxPool", ins, kernel_shape=wd[2:],
                      strides=ws[2:], pads=pads)
    # sum pool = AveragePool(count_include_pad) * window_size
    ap = G.node("AveragePool", ins, kernel_shape=wd[2:], strides=ws[2:],
                pads=pads, count_include_pad=1)
    scale = G.add_initializer(
        np.asarray(wd[2] * wd[3], _np_dtype(eqn.outvars[0].aval.dtype)))
    return G.node("Mul", [ap, scale])


_SIMPLE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow", "neg": "Neg",
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "sqrt": "Sqrt",
    "abs": "Abs", "sign": "Sign", "floor": "Floor", "ceil": "Ceil",
    "logistic": "Sigmoid", "erf": "Erf", "sin": "Sin", "cos": "Cos",
    "and": "And", "or": "Or", "not": "Not",
    "eq": "Equal", "lt": "Less", "le": "LessOrEqual", "gt": "Greater",
    "ge": "GreaterOrEqual",
}


def _emit(G, eqn, ins):
    prim = eqn.primitive.name
    aval = eqn.outvars[0].aval

    if prim in _SIMPLE:
        return G.node(_SIMPLE[prim], ins)
    if prim == "square":
        return G.node("Mul", [ins[0], ins[0]])
    if prim == "integer_pow":
        e = G.add_initializer(
            np.asarray(eqn.params["y"], _np_dtype(aval.dtype)))
        return G.node("Pow", [ins[0], e])
    if prim == "rsqrt":
        return G.node("Reciprocal", [G.node("Sqrt", ins)])
    if prim == "dot_general":
        return _dot_general(G, eqn, ins)
    if prim == "conv_general_dilated":
        return _conv(G, eqn, ins)
    if prim == "reduce_sum":
        return G.node("ReduceSum", ins, axes=list(eqn.params["axes"]),
                      keepdims=0)
    if prim == "reduce_max":
        return G.node("ReduceMax", ins, axes=list(eqn.params["axes"]),
                      keepdims=0)
    if prim == "reduce_min":
        return G.node("ReduceMin", ins, axes=list(eqn.params["axes"]),
                      keepdims=0)
    if prim == "reduce_window_max":
        return _reduce_window(G, eqn, ins, "max")
    if prim == "reduce_window_sum":
        return _reduce_window(G, eqn, ins, "sum")
    if prim == "reshape":
        shape = G.add_initializer(np.asarray(aval.shape, np.int64))
        return G.node("Reshape", [ins[0], shape])
    if prim == "squeeze":
        shape = G.add_initializer(np.asarray(aval.shape, np.int64))
        return G.node("Reshape", [ins[0], shape])
    if prim == "expand_dims":
        shape = G.add_initializer(np.asarray(aval.shape, np.int64))
        return G.node("Reshape", [ins[0], shape])
    if prim == "transpose":
        return G.node("Transpose", ins,
                      perm=list(eqn.params["permutation"]))
    if prim == "broadcast_in_dim":
        in_aval = eqn.invars[0].aval
        interm = [1] * len(aval.shape)
        for src, dst in enumerate(eqn.params["broadcast_dimensions"]):
            interm[dst] = in_aval.shape[src]
        rs = G.add_initializer(np.asarray(interm, np.int64))
        r = G.node("Reshape", [ins[0], rs])
        ex = G.add_initializer(np.asarray(aval.shape, np.int64))
        return G.node("Expand", [r, ex])
    if prim == "concatenate":
        return G.node("Concat", ins, axis=int(eqn.params["dimension"]))
    if prim == "slice":
        if eqn.params.get("strides") is None:
            strides = [1] * len(aval.shape)
        else:
            strides = list(eqn.params["strides"])
        starts = G.add_initializer(
            np.asarray(eqn.params["start_indices"], np.int64))
        ends = G.add_initializer(
            np.asarray(eqn.params["limit_indices"], np.int64))
        axes = G.add_initializer(
            np.asarray(range(len(aval.shape)), np.int64))
        steps = G.add_initializer(np.asarray(strides, np.int64))
        return G.node("Slice", [ins[0], starts, ends, axes, steps])
    if prim == "select_n":
        if len(ins) != 3:
            raise NotImplementedError("select_n with >2 cases")
        # select_n(pred, on_false, on_true) -> Where(pred, true, false)
        return G.node("Where", [ins[0], ins[2], ins[1]])
    if prim == "convert_element_type":
        return G.node("Cast", ins,
                      to=int(_DTYPE[_np_dtype(eqn.params["new_dtype"])]))
    if prim == "iota":
        p = eqn.params
        arr = np.asarray(
            jax.lax.broadcasted_iota(p["dtype"], p["shape"],
                                     p["dimension"]))
        return G.add_initializer(arr, "iota")
    if prim == "argmax":
        axes = eqn.params["axes"]
        out = G.node("ArgMax", ins, axis=int(axes[0]), keepdims=0)
        want = _DTYPE[_np_dtype(aval.dtype)]
        if want != pb.TensorProto.INT64:
            out = G.node("Cast", [out], to=int(want))
        return out
    if prim == "gather":
        return _gather(G, eqn, ins)
    if prim == "stop_gradient":
        return G.node("Identity", ins)
    if prim == "pad":
        lo_hi = eqn.params["padding_config"]
        if any(pc[2] != 0 for pc in lo_hi):
            raise NotImplementedError("interior pad export")
        pads = [pc[0] for pc in lo_hi] + [pc[1] for pc in lo_hi]
        pv = G.add_initializer(np.asarray(pads, np.int64))
        return G.node("Pad", [ins[0], pv, ins[1]], mode="constant")
    raise NotImplementedError(
        f"onnx export: no converter for primitive '{prim}'")


def _gather(G, eqn, ins):
    """Embedding-style gather only: take rows along axis 0."""
    p = eqn.params
    dn = p["dimension_numbers"]
    op_aval = eqn.invars[0].aval
    slice_sizes = tuple(p["slice_sizes"])
    if (tuple(dn.start_index_map) == (0,)
            and tuple(dn.collapsed_slice_dims) == (0,)
            and slice_sizes[0] == 1
            and slice_sizes[1:] == tuple(op_aval.shape[1:])):
        idx_aval = eqn.invars[1].aval
        idx = ins[1]
        if idx_aval.shape and idx_aval.shape[-1] == 1:
            shape = G.add_initializer(
                np.asarray(idx_aval.shape[:-1], np.int64))
            idx = G.node("Reshape", [idx, shape])
        return G.node("Gather", [ins[0], idx], axis=0)
    raise NotImplementedError("general lax.gather export")


_INLINE_CALLS = ("pjit", "closed_call", "custom_jvp_call",
                 "custom_vjp_call", "custom_vjp_call_jaxpr", "jit",
                 "remat", "checkpoint")


def _walk(G, jaxpr, env):
    def read(v):
        if isinstance(v, jax.extend.core.Literal) or type(v).__name__ == \
                "Literal":
            return G.add_initializer(np.asarray(v.val), "lit")
        return env[v]

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim in _INLINE_CALLS or "call" in prim:
            sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr") \
                or eqn.params.get("fun_jaxpr")
            if sub is None:
                raise NotImplementedError(f"call primitive {prim}")
            closed = sub if hasattr(sub, "jaxpr") else None
            inner = sub.jaxpr if closed is not None else sub
            sub_env = {}
            for cv, cval in zip(inner.constvars,
                                (sub.consts if closed is not None else [])):
                sub_env[cv] = G.add_initializer(np.asarray(cval), "const")
            for iv, outer in zip(inner.invars, eqn.invars):
                sub_env[iv] = read(outer)
            _walk(G, inner, sub_env)
            for ov, outer_ov in zip(inner.outvars, eqn.outvars):
                env[outer_ov] = sub_env[ov] if not isinstance(
                    ov, jax.extend.core.Literal) else G.add_initializer(
                        np.asarray(ov.val), "lit")
            continue
        ins = [read(v) for v in eqn.invars]
        out = _emit(G, eqn, ins)
        outs = out if isinstance(out, list) else [out]
        for ov, name in zip(eqn.outvars, outs):
            env[ov] = name


def export(layer, path, input_spec=None, opset_version=12, **configs):
    """Export a Layer (or pure fn over Tensors) to `path`.onnx.

    input_spec: list of example Tensors / np arrays / InputSpec-likes
    (anything with .shape and .dtype). The layer runs in eval mode;
    parameters are baked as initializers. Returns the output path.
    """
    from paddle_tpu.core import engine

    if input_spec is None:
        raise ValueError("input_spec is required")

    examples = []
    for s in input_spec:
        if isinstance(s, Tensor):
            examples.append(s._value)
        elif hasattr(s, "shape") and hasattr(s, "dtype"):
            dt = s.dtype
            dt = np.float32 if dt in (None, "float32") else dt
            examples.append(jnp.zeros(tuple(int(d) if d is not None else 1
                                            for d in s.shape), dt))
        else:
            examples.append(jnp.asarray(s))

    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    try:
        def fn(*xs):
            with engine.no_grad():
                out = layer(*[Tensor(x) for x in xs])
            if isinstance(out, (tuple, list)):
                return tuple(o._value if isinstance(o, Tensor) else o
                             for o in out)
            return out._value if isinstance(out, Tensor) else out

        closed = jax.make_jaxpr(fn)(*examples)
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()

    G = _Graph()
    env = {}
    for cv, cval in zip(closed.jaxpr.constvars, closed.consts):
        env[cv] = G.add_initializer(np.asarray(cval), "param")
    for i, iv in enumerate(closed.jaxpr.invars):
        name = f"input_{i}"
        env[iv] = name
        G.g.input.append(_value_info(name, iv.aval))
    _walk(G, closed.jaxpr, env)
    for i, ov in enumerate(closed.jaxpr.outvars):
        if isinstance(ov, jax.extend.core.Literal):
            name = G.add_initializer(np.asarray(ov.val), "out")
        else:
            name = env[ov]
        out_name = f"output_{i}"
        G.g.node.append(pb.NodeProto(op_type="Identity", input=[name],
                                     output=[out_name], name=out_name))
        G.g.output.append(_value_info(out_name, ov.aval))

    model = pb.ModelProto(ir_version=7, producer_name="paddle_tpu",
                          graph=G.g)
    ops = model.opset_import.add()
    ops.domain = ""
    ops.version = opset_version
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(model.SerializeToString())
    return out_path
