"""Global state-tensor registry + PRNG state.

The registry is the TPU-native replacement for the reference's global Scope /
persistable variables (paddle/fluid/framework/scope.h): every Parameter,
Layer buffer and optimizer accumulator registers here, so
``paddle_tpu.jit.to_static`` can lift ALL mutable framework state into pytree
arguments of one jitted function (whole-program functionalization).

PRNG: paddle's global seed (paddle.seed) maps to a threaded, splitting JAX
key — every random op consumes a fresh split, keeping eager semantics while
remaining trace-safe.
"""
from __future__ import annotations

import threading
import weakref

import jax
import jax.numpy as jnp

_state_tensors = weakref.WeakSet()
_registry_version = [0]
_serial = [0]


def register_state_tensor(t):
    _state_tensors.add(t)
    _registry_version[0] += 1
    _serial[0] += 1
    t.__dict__["_state_serial"] = _serial[0]


def state_tensors():
    return list(_state_tensors)


def registry_version():
    return _registry_version[0]


class _RNG(threading.local):
    def __init__(self):
        self.key_tensor = None
        self.seed_val = 0
        self.seeded = False  # True once the user called seed() explicitly


_rng = _RNG()


def _key_tensor():
    if _rng.key_tensor is None:
        from paddle_tpu.core.tensor import Tensor
        t = Tensor(jax.random.key_data(jax.random.key(0)), name="global_rng_key")
        t.persistable = True
        t.__dict__["_reinit"] = lambda: jax.random.key_data(
            jax.random.key(_rng.seed_val))
        register_state_tensor(t)
        _rng.key_tensor = t
    return _rng.key_tensor


def seed(s: int):
    t = _key_tensor()
    t._set_value(jax.random.key_data(jax.random.key(int(s))))
    _rng.seed_val = int(s)
    _rng.seeded = True
    return _rng


def get_rng_state():
    return _key_tensor()._value


def set_rng_state(key_data):
    _key_tensor()._set_value(key_data)


def next_key():
    """Split the global key. The key lives in a registered state Tensor, so
    under to_static the key is a lifted input/output of the compiled step —
    every compiled step sees fresh randomness (dropout works), no retrace."""
    t = _key_tensor()
    key = jax.random.wrap_key_data(t._value)
    new, sub = jax.random.split(key)
    t._set_value(jax.random.key_data(new))
    return sub


_flags = {}


def set_flags(d):
    _flags.update(d)


def get_flags(keys):
    if isinstance(keys, str):
        keys = [keys]
    return {k: _flags.get(k) for k in keys}
