"""paddle.save / paddle.load. Reference: python/paddle/framework/io.py.

Pickle-compatible state_dict persistence; Orbax-based async/multi-host
checkpointing lives in paddle_tpu.utils.checkpoint.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from paddle_tpu.core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._value)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return pickle.load(f)
