"""paddle.save / paddle.load. Reference: python/paddle/framework/io.py.

Pickle-compatible state_dict persistence; Orbax-based async/multi-host
checkpointing lives in paddle_tpu.utils.checkpoint, and crash-safe
manifested checkpointing (digests, retention, auto-resume) in
paddle_tpu.resilience.checkpoint — both write through
:func:`write_atomic` below, the repo's ONE durable-write choke point
(write to a temp file in the same directory, flush+fsync, then
``os.replace``), which is also the ``io.save`` fault-injection hook
site for the chaos suite.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from paddle_tpu.core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._value)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


def write_atomic(path, data, fsync=True, site="io.save"):
    """Durably write to `path`: temp file in the target directory,
    optional fsync, then an atomic ``os.replace`` — a reader never
    observes a half-written file; a crash mid-write leaves the previous
    file intact.  `data` is either bytes or a ``callable(file)`` that
    STREAMS the payload (so a multi-GB save never needs a full
    in-memory byte copy).

    Fault-injection site ``io.save`` (kind ``torn_write``): payload
    ``keep_fraction`` truncates the written payload BEFORE the rename
    (simulating a torn buffer that still got renamed — only a content
    digest catches it); ``abort_rename`` writes the temp file but skips
    the rename (simulating a crash between write and rename — atomicity
    itself is what recovers this one).
    """
    from paddle_tpu.resilience import faultinject
    spec = faultinject.fire(
        site, path=path,
        size=len(data) if isinstance(data, (bytes, bytearray)) else None)
    torn = spec is not None and spec.kind == "torn_write"
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        if callable(data):
            data(f)
        else:
            f.write(data)
        if torn:
            f.flush()
            keep = float(spec.payload.get("keep_fraction", 0.5))
            f.truncate(max(0, int(f.tell() * keep)))
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    if torn and spec.payload.get("abort_rename"):
        return  # the temp file is the debris a real crash would leave
    os.replace(tmp, path)


def save(obj, path, protocol=4, atomic=True, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    if atomic:
        # streamed through the temp file: atomic-by-default costs no
        # extra peak host memory over the historical direct pickle.dump
        write_atomic(path, lambda f: pickle.dump(_to_saveable(obj), f,
                                                 protocol=protocol))
    else:
        with open(path, "wb") as f:
            pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return pickle.load(f)
