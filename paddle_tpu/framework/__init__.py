"""Framework namespace. Reference: python/paddle/framework/__init__.py."""
from paddle_tpu.core.device import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    TPUPlace,
    XPUPlace,
    _default_place,
    get_device,
    set_device,
)
from paddle_tpu.core.dtype import (  # noqa: F401
    get_default_dtype,
    set_default_dtype,
)
from paddle_tpu.core.tensor import Parameter, Tensor  # noqa: F401
from paddle_tpu.framework.state import (  # noqa: F401
    get_flags,
    seed,
    set_flags,
)


def iinfo(dtype):
    """Integer dtype limits (reference framework/__init__.py iinfo)."""
    import paddle_tpu
    return paddle_tpu.iinfo(dtype)


def finfo(dtype):
    """Float dtype limits (reference framework/__init__.py finfo)."""
    import paddle_tpu
    return paddle_tpu.finfo(dtype)


def in_dynamic_mode():
    from paddle_tpu.jit.api import _in_to_static_trace
    return not _in_to_static_trace()


def in_dygraph_mode():
    return in_dynamic_mode()
