"""GPT-3-style causal LM — the flagship hybrid-parallel model.

Reference parity: PaddleNLP GPT-3 built on the reference framework's
fleet meta-parallel layers (reference:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py —
ColumnParallelLinear/RowParallelLinear/VocabParallelEmbedding; hybrid DP/MP/PP
topology from python/paddle/distributed/fleet/base/topology.py).

TPU-native design: one logical model over a `Mesh(("dp","pp","tp","sp"))`.
Weights carry PartitionSpecs (tp-sharded qkv/ffn columns, rows for the output
projections); activations are constrained to [batch→dp, seq→sp]; XLA's
sharding propagation inserts the AllReduce/AllGather collectives over ICI that
the reference expresses as explicit c_allreduce ops on NCCL. Attention runs
through F.scaled_dot_product_attention (Pallas flash-attention fast path).
"""
from __future__ import annotations

import paddle_tpu
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    _constrain,
)
from paddle_tpu.distributed.recompute import recompute
from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I


class GPTConfig:
    """Model hyperparameters (GPT-3 naming)."""

    def __init__(self, vocab_size=50304, hidden_size=2048, num_layers=24,
                 num_heads=16, ffn_hidden_size=None, max_seq_len=2048,
                 dropout=0.1, attention_dropout=0.1, initializer_range=0.02,
                 layer_norm_epsilon=1e-5, use_recompute=False,
                 tie_word_embeddings=True, fused_ln=False,
                 moe_num_experts=0, moe_top_k=2,
                 moe_every=2, moe_gate="gshard", moe_ep_axis="ep",
                 moe_capacity_factor=(2.0, 2.0)):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.attention_dropout = attention_dropout
        self.initializer_range = initializer_range
        self.layer_norm_epsilon = layer_norm_epsilon
        self.use_recompute = use_recompute
        self.tie_word_embeddings = tie_word_embeddings
        # fused_ln=True routes the block's norms through the Pallas
        # fused LN kernels (ops/pallas/norm.py): ln1/final_ln as plain
        # fused layernorm, ln2 as the fused residual-add+LN whose
        # custom VJP recomputes the normalized intermediate instead of
        # materializing it.  Pure-JAX numerics on CPU via interpret
        # mode; opt-in per model (docs/performance_guide.md).
        self.fused_ln = fused_ln
        # MoE (GShard-style; reference incubate.distributed.models.moe):
        # every `moe_every`-th decoder block swaps its dense FFN for
        # `moe_num_experts` experts sharded over the `moe_ep_axis` mesh axis
        self.moe_num_experts = moe_num_experts
        self.moe_top_k = moe_top_k
        self.moe_every = moe_every
        self.moe_gate = moe_gate
        self.moe_ep_axis = moe_ep_axis
        self.moe_capacity_factor = moe_capacity_factor


def gpt3_1p3b(**kw):
    """GPT-3 1.3B (the BASELINE.json Fleet hybrid-parallel config)."""
    cfg = dict(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
               max_seq_len=2048)
    cfg.update(kw)
    return GPTConfig(**cfg)


def gpt3_tiny(**kw):
    """Tiny config for tests / compile checks."""
    cfg = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
               max_seq_len=128, dropout=0.0, attention_dropout=0.0)
    cfg.update(kw)
    return GPTConfig(**cfg)


class GPTEmbeddings(nn.Layer):
    """Word (vocab-parallel) + learned position embeddings."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size,
            weight_attr=I.ParamAttr(initializer=I.Normal(
                0.0, config.initializer_range)))
        self.position_embeddings = nn.Embedding(
            config.max_seq_len, config.hidden_size,
            weight_attr=I.ParamAttr(initializer=I.Normal(
                0.0, config.initializer_range)))
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, input_ids, position_ids=None):
        if position_ids is None:
            seq_len = input_ids.shape[-1]
            position_ids = paddle_tpu.arange(seq_len, dtype="int64")
        h = self.word_embeddings(input_ids) + self.position_embeddings(
            position_ids)
        h = _constrain(h, "dp", "sp", None)
        return self.dropout(h)


class GPTAttention(nn.Layer):
    """Causal self-attention; fused qkv column-parallel, row-parallel output.

    qkv columns are laid out [head, 3*head_dim] so the tp shards own whole
    heads — attention then needs NO communication; the only tp collective in
    the block is the AllReduce after out_proj (XLA inserts it from the
    row-sharded weight spec).
    """

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        init = I.ParamAttr(initializer=I.Normal(0.0, config.initializer_range))
        self.qkv_proj = ColumnParallelLinear(
            config.hidden_size, 3 * config.hidden_size, weight_attr=init,
            gather_output=False)
        self.out_proj = RowParallelLinear(
            config.hidden_size, config.hidden_size, weight_attr=init,
            input_is_parallel=True)
        self.attn_dropout_p = config.attention_dropout

    def forward(self, hidden, kv_ctx=None):
        b, s = hidden.shape[0], hidden.shape[1]
        qkv = self.qkv_proj(hidden)
        qkv = qkv.reshape([b, s, self.num_heads, 3 * self.head_dim])
        qkv = _constrain(qkv, "dp", "sp", "tp", None)
        q, k, v = qkv.split(3, axis=-1)
        if kv_ctx is not None:
            # serving hook: the context owns KV residency (paged pools)
            # and attention over the cached history — see
            # paddle_tpu.serving.engine.PagedKVContext
            out = kv_ctx.attend(q, k, v)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True,
                dropout_p=self.attn_dropout_p if self.training else 0.0,
                training=self.training)
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        init = I.ParamAttr(initializer=I.Normal(0.0, config.initializer_range))
        self.fc1 = ColumnParallelLinear(
            config.hidden_size, config.ffn_hidden_size, weight_attr=init,
            gather_output=False)
        self.fc2 = RowParallelLinear(
            config.ffn_hidden_size, config.hidden_size, weight_attr=init,
            input_is_parallel=True)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTDecoderLayer(nn.Layer):
    """Pre-LN transformer decoder block. With `moe_num_experts` set and
    this block selected by `moe_every`, the dense FFN is replaced by a
    GShard MoE whose experts shard over the `ep` mesh axis (reference:
    GPT-MoE built on incubate.distributed.models.moe.MoELayer)."""

    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self._fused_ln = config.fused_ln
        self.ln1 = nn.LayerNorm(config.hidden_size,
                                epsilon=config.layer_norm_epsilon,
                                fused=config.fused_ln or None)
        self.attn = GPTAttention(config)
        self.ln2 = nn.LayerNorm(config.hidden_size,
                                epsilon=config.layer_norm_epsilon,
                                fused=config.fused_ln or None)
        use_moe = (config.moe_num_experts > 0
                   and (layer_idx + 1) % config.moe_every == 0)
        if use_moe:
            from paddle_tpu.distributed.moe import (MoELayer,
                                                    StackedExpertFFN)
            self.mlp = MoELayer(
                config.hidden_size,
                StackedExpertFFN(config.moe_num_experts, config.hidden_size,
                                 config.ffn_hidden_size,
                                 ep_axis=config.moe_ep_axis),
                gate={"type": config.moe_gate, "top_k": config.moe_top_k},
                ep_axis=config.moe_ep_axis,
                capacity_factor=config.moe_capacity_factor)
        else:
            self.mlp = GPTMLP(config)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x, kv_ctx=None):
        if self._fused_ln:
            # fused residual-add + ln2: the attn sublayer's residual add
            # and the second norm collapse into ONE kernel whose HBM
            # traffic is its call boundary (x, attn_out, w, b -> stream,
            # normed) — the normalized intermediate is recomputed by the
            # custom VJP, never materialized.  Run under an explicit
            # "ln2" scope so the roofline row keeps its pre-fusion name.
            from paddle_tpu.observability import profile as _prof
            a = self.dropout(self.attn(self.ln1(x), kv_ctx=kv_ctx))
            with _prof.scope("ln2"):
                x, h2 = F.fused_ln_residual(
                    a, x, self.ln2.weight, self.ln2.bias,
                    self.ln2._epsilon, fused=True)
            x = x + self.dropout(self.mlp(h2))
            return _constrain(x, "dp", "sp", None)
        x = x + self.dropout(self.attn(self.ln1(x), kv_ctx=kv_ctx))
        x = x + self.dropout(self.mlp(self.ln2(x)))
        return _constrain(x, "dp", "sp", None)


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = nn.LayerList(
            [GPTDecoderLayer(config, layer_idx=i)
             for i in range(config.num_layers)])
        self.final_ln = nn.LayerNorm(config.hidden_size,
                                     epsilon=config.layer_norm_epsilon,
                                     fused=config.fused_ln or None)

    def forward(self, input_ids, position_ids=None, kv_ctx=None):
        from paddle_tpu.amp.policy import remat_active
        h = self.embeddings(input_ids, position_ids)
        # the model's declared recompute units are its decoder blocks:
        # config.use_recompute turns them on statically, an ambient
        # to_static(remat=...) policy turns them on for that trace only
        use_rc = (self.config.use_recompute or bool(remat_active())) \
            and self.training
        if kv_ctx is not None and use_rc:
            # silently skipping the cache hook would leave the paged
            # pools unwritten and decode over garbage — fail loudly
            raise RuntimeError(
                "kv_ctx serving forward requires eval mode (recompute "
                "is active): call model.eval() before serving")
        for layer in self.layers:
            if use_rc:
                h = recompute(layer, h)
            elif kv_ctx is not None:
                h = layer(h, kv_ctx=kv_ctx)
            else:
                h = layer(h)
        return self.final_ln(h)

    def moe_aux_loss(self):
        """Sum of the MoE gates' load-balancing losses from the last
        forward (cleared on read); 0.0 when the model has no MoE blocks."""
        total = None
        for layer in self.layers:
            gate = getattr(layer.mlp, "gate", None)
            if gate is not None and hasattr(gate, "get_loss"):
                loss = gate.get_loss()
                if loss is not None:
                    total = loss if total is None else total + loss
        return total if total is not None else paddle_tpu.zeros([])


class GPTForCausalLM(nn.Layer):
    """LM head ties the (vocab-parallel) embedding table; logits are
    tp-sharded on the vocab dim — ParallelCrossEntropy consumes them without
    an AllGather of the [b, s, vocab] tensor."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head_weight = self.create_parameter(
                shape=[config.hidden_size, config.vocab_size],
                default_initializer=I.Normal(0.0, config.initializer_range))
            from paddle_tpu.distributed.mesh import shard_tensor
            shard_tensor(self.lm_head_weight, None, "tp")

    def forward(self, input_ids, position_ids=None, kv_ctx=None):
        h = self.gpt(input_ids, position_ids, kv_ctx=kv_ctx)
        if self.config.tie_word_embeddings:
            w = self.gpt.embeddings.word_embeddings.weight
            logits = paddle_tpu.matmul(h, w, transpose_y=True)
        else:
            logits = paddle_tpu.matmul(h, self.lm_head_weight)
        return _constrain(logits, "dp", "sp", "tp")

    def loss_with_fused_head(self, input_ids, labels, position_ids=None,
                             chunk_size=8192):
        """Single-chip memory path: head matmul + CE fused and chunked so
        the [b, s, vocab] logits never materialize (the tp analogue is
        ParallelCrossEntropy; see F.fused_linear_cross_entropy). A 350M
        model at batch 8/seq 2048 OOMs v5e HBM through the logits alone
        on the plain path; this one fits."""
        import paddle_tpu.nn.functional as F
        h = self.gpt(input_ids, position_ids)
        if self.config.tie_word_embeddings:
            w = self.gpt.embeddings.word_embeddings.weight.t()
        else:
            w = self.lm_head_weight
        return F.fused_linear_cross_entropy(h, w, labels,
                                            chunk_size=chunk_size)


class GPTPretrainingCriterion(nn.Layer):
    """Masked LM loss (reference: PaddleNLP GPTPretrainingCriterion —
    ParallelCrossEntropy when mp_degree>1; here the vocab-sharded logits make
    the same softmax tp-parallel via sharding propagation)."""

    def __init__(self):
        super().__init__()

    def forward(self, logits, labels, loss_mask=None):
        loss = F.cross_entropy(logits, labels, reduction="none")
        if loss_mask is not None:
            mask = loss_mask.reshape(loss.shape).astype(loss.dtype)
            return (loss * mask).sum() / mask.sum().clip(min=1.0)
        return loss.mean()
