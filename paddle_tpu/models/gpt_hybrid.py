"""GPT with EXPLICIT 4-D hybrid parallelism — dp × pp × tp × sp in one SPMD
program.

Reference parity: the reference's fleet hybrid-parallel GPT-3
(python/paddle/distributed/fleet/meta_parallel/: tensor parallel mp_layers
+ pipeline_parallel.py 1F1B over NCCL p2p + sharding/dp groups from
base/topology.py HybridCommunicateGroup).

TPU-native design: ONE jit-compiled shard_map over Mesh("dp","pp","tp","sp")
contains the whole train step —
  dp: batch dim sharded; gradient psum comes out of shard_map AD transpose
  pp: decoder trunk stages stacked on a leading dim sharded over pp;
      activations hop stages via the lax.scan+ppermute microbatch pipeline
      (distributed/pipeline.py pattern, inlined here with per-stage params)
  tp: Megatron layout — qkv/fc1 column-sharded, out-proj/fc2 row-sharded,
      ONE lax.psum("tp") after each row-parallel matmul; attention heads
      split over tp so attention itself needs no tp communication
  sp: sequence dim sharded; exact causal attention via ring_attention
      (ppermute k/v ring with online-softmax merge) over the "sp" axis
This composes paddle_tpu.distributed.pipeline's schedule with
context_parallel.ring_attention — the same building blocks exposed to
users — into the flagship configuration the driver dry-runs.

The nn.Layer GPT (models/gpt.py) remains the to_static/propagation path;
this module is the explicit-collectives path for peak control at scale.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.context_parallel import ring_attention
from paddle_tpu.distributed.fleet.mp_ops import (vocab_parallel_cross_entropy,
                                                 vocab_parallel_embedding)


# ---------------------------------------------------------------------------
# Parameter init / sharding specs
# ---------------------------------------------------------------------------

def init_hybrid_gpt_params(cfg, mesh, seed=0):
    """Whole-array params, device_put with their hybrid PartitionSpecs.

    cfg needs: vocab_size, hidden_size, num_layers, num_heads, ffn size via
    4*hidden, max_seq_len. num_layers must be divisible by the pp degree.
    """
    H = cfg.hidden_size
    F = getattr(cfg, "ffn_hidden_size", None) or 4 * H
    L = cfg.num_layers
    rng = np.random.default_rng(seed)
    std = 0.02

    def norm(*shape):
        return rng.normal(0.0, std, shape).astype(np.float32)

    stages = {
        "ln1_g": np.ones((L, H), np.float32),
        "ln1_b": np.zeros((L, H), np.float32),
        "w_qkv": norm(L, H, 3 * H),
        "b_qkv": np.zeros((L, 3 * H), np.float32),
        "w_o": norm(L, H, H),
        "b_o": np.zeros((L, H), np.float32),
        "ln2_g": np.ones((L, H), np.float32),
        "ln2_b": np.zeros((L, H), np.float32),
        "w1": norm(L, H, F),
        "b1": np.zeros((L, F), np.float32),
        "w2": norm(L, F, H),
        "b2": np.zeros((L, H), np.float32),
    }
    params = {
        "wte": norm(cfg.vocab_size, H),
        "wpe": norm(cfg.max_seq_len, H),
        "lnf_g": np.ones((H,), np.float32),
        "lnf_b": np.zeros((H,), np.float32),
        "stages": stages,
    }
    specs = hybrid_param_specs()
    return jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), params,
        specs)


def hybrid_param_specs():
    """PartitionSpecs: stage dim over pp; Megatron col/row layouts over tp."""
    return {
        "wte": P("tp", None),        # vocab-parallel table + tied head:
        "wpe": P(None, None),        # no full-vocab logits ever materialize
        "lnf_g": P(None),            # (fleet/mp_ops.py)
        "lnf_b": P(None),
        "stages": {
            "ln1_g": P("pp", None),
            "ln1_b": P("pp", None),
            "w_qkv": P("pp", None, "tp"),   # column-parallel
            "b_qkv": P("pp", "tp"),
            "w_o": P("pp", "tp", None),     # row-parallel
            "b_o": P("pp", None),
            "ln2_g": P("pp", None),
            "ln2_b": P("pp", None),
            "w1": P("pp", None, "tp"),      # column-parallel
            "b1": P("pp", "tp"),
            "w2": P("pp", "tp", None),      # row-parallel
            "b2": P("pp", None),
        },
    }


# ---------------------------------------------------------------------------
# Local (per-device) math inside shard_map
# ---------------------------------------------------------------------------

def _layer_norm(x, g, b, eps=1e-5):
    x = x.astype(jnp.float32)
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def _decoder_block(p, h, num_heads_local, sp_size):
    """One decoder layer on local shards: tp-split heads/ffn, sp-ring attn.
    h: [mb, s_loc, H]. p leaves are single-layer (no leading layer dim)."""
    mb, s_loc, H = h.shape
    # --- attention ---
    x = _layer_norm(h, p["ln1_g"], p["ln1_b"])
    qkv = x @ p["w_qkv"] + p["b_qkv"]          # [mb, s_loc, 3H/tp]
    head_dim = p["w_qkv"].shape[1] // 3 // num_heads_local
    qkv = qkv.reshape(mb, s_loc, num_heads_local, 3 * head_dim)
    qkv = jnp.moveaxis(qkv, 2, 1)              # [mb, h_loc, s_loc, 3hd]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    o = ring_attention(q, k, v, axis_name="sp", causal=True,
                       axis_size=sp_size)      # exact causal over sp ring
    o = jnp.moveaxis(o, 1, 2).reshape(mb, s_loc, -1)
    attn = o @ p["w_o"]                        # partial sums over tp shard
    attn = lax.psum(attn, "tp") + p["b_o"]     # row-parallel reduce
    h = h + attn
    # --- mlp ---
    x = _layer_norm(h, p["ln2_g"], p["ln2_b"])
    y = jax.nn.gelu(x @ p["w1"] + p["b1"], approximate=True)
    y = lax.psum(y @ p["w2"], "tp") + p["b2"]  # row-parallel reduce
    return h + y


def _pipeline_trunk(stage_params, h_mb, block_fn, pp_size):
    """GPipe microbatch schedule over pp (see distributed/pipeline.py).
    h_mb: [M, mb, s_loc, H]; stage_params leaves: [layers_local, ...]."""
    stage = lax.axis_index("pp")
    M = h_mb.shape[0]
    perm = [(i, (i + 1) % pp_size) for i in range(pp_size)]

    def apply_stage(prev_y, t):
        inp = jnp.where(stage == 0, h_mb[jnp.clip(t, 0, M - 1)], prev_y)

        def one(x, pl):   # scan over this stage's local layers
            return jax.checkpoint(block_fn)(pl, x), None
        out, _ = lax.scan(one, inp, stage_params)
        return out

    def tick(prev_y, t):
        inbound = lax.ppermute(prev_y, "pp", perm)
        y = apply_stage(inbound, t)
        return y, y

    y0 = apply_stage(jnp.zeros_like(h_mb[0]), 0)
    if pp_size == 1:
        rest = [apply_stage(h_mb[t], t) for t in range(1, M)]
        return jnp.stack([y0] + rest, 0)
    _, ys = lax.scan(tick, y0, jnp.arange(1, M + pp_size - 1))
    ys = jnp.concatenate([y0[None], ys], 0)
    outputs = jnp.where(stage == pp_size - 1, ys[pp_size - 1:], 0.0)
    return lax.psum(outputs, "pp")


def make_hybrid_loss_fn(cfg, mesh, num_microbatches=2):
    """Whole-array loss(params, ids, labels) -> scalar; jit/grad-able.

    ids/labels: [B, S] sharded (dp, sp). Composes the dp/pp/tp/sp program
    described in the module docstring inside one shard_map.
    """
    shape = dict(mesh.shape)
    tp, sp, pp = shape["tp"], shape["sp"], shape["pp"]
    if cfg.num_heads % tp:
        raise ValueError("num_heads must divide by tp degree")
    if cfg.num_layers % pp:
        raise ValueError("num_layers must divide by pp degree")
    if cfg.vocab_size % tp:
        raise ValueError("vocab_size must divide by tp degree")
    heads_local = cfg.num_heads // tp
    M = num_microbatches

    def local_loss(params, ids, labels):
        b_loc, s_loc = ids.shape
        sp_idx = lax.axis_index("sp")
        # embed: vocab-parallel table (wte sharded over tp on the vocab dim;
        # masked local lookup + psum), positions global via the sp shard idx
        pos = sp_idx * s_loc + jnp.arange(s_loc)
        h = vocab_parallel_embedding(params["wte"], ids, "tp") \
            + params["wpe"][pos][None, :, :]
        # microbatch the local batch for the pipeline
        h = h.reshape(M, b_loc // M, s_loc, -1)
        block = functools.partial(_decoder_block,
                                  num_heads_local=heads_local, sp_size=sp)
        h = _pipeline_trunk(params["stages"], h, block, pp)
        h = h.reshape(b_loc, s_loc, -1)
        h = _layer_norm(h, params["lnf_g"], params["lnf_b"])
        # tied head against the LOCAL vocab shard: [b, s, V/tp] is the
        # largest logits tensor that ever exists; CE runs sharded
        logits_local = h @ params["wte"].T
        nll = vocab_parallel_cross_entropy(logits_local, labels, "tp")
        total = lax.psum(jnp.sum(nll), ("dp", "sp"))
        count = lax.psum(jnp.asarray(nll.size, jnp.float32), ("dp", "sp"))
        return total / count

    specs = hybrid_param_specs()
    data_spec = P("dp", "sp")
    return jax.shard_map(local_loss, mesh=mesh,
                         in_specs=(specs, data_spec, data_spec),
                         out_specs=P(), check_vma=False)


def make_hybrid_train_step(cfg, mesh, lr=1e-3, num_microbatches=2):
    """SGD train step over the hybrid loss; returns jitted
    step(params, ids, labels) -> (params, loss). Update is elementwise, so
    every param keeps its hybrid sharding (dp grad-sync fell out of the
    shard_map transpose as psums over dp/sp)."""
    loss_fn = make_hybrid_loss_fn(cfg, mesh, num_microbatches)

    @jax.jit
    def step(params, ids, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids, labels)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params,
                                        grads)
        return params, loss

    return step
