"""GPT with EXPLICIT hybrid parallelism — up to 5 axes
(dp × pp × tp × sp × ep) in one SPMD program.

Reference parity: the reference's fleet hybrid-parallel GPT-3
(python/paddle/distributed/fleet/meta_parallel/: tensor parallel mp_layers
+ pipeline_parallel.py 1F1B over NCCL p2p + sharding/dp groups from
base/topology.py HybridCommunicateGroup).

TPU-native design: ONE jit-compiled shard_map over Mesh("dp","pp","tp","sp")
contains the whole train step —
  dp: batch dim sharded; gradient psum comes out of shard_map AD transpose
  pp: decoder trunk stages stacked on a leading dim sharded over pp;
      activations hop stages via the lax.scan+ppermute microbatch pipeline
      (distributed/pipeline.py pattern, inlined here with per-stage params)
  tp: Megatron layout — qkv/fc1 column-sharded, out-proj/fc2 row-sharded,
      ONE lax.psum("tp") after each row-parallel matmul; attention heads
      split over tp so attention itself needs no tp communication
  sp: sequence dim sharded; exact causal attention via ring_attention
      (ppermute k/v ring with online-softmax merge) over the "sp" axis
  ep: (cfg.moe_num_experts > 0) every FFN becomes a GShard expert bank
      sharded over "ep": per-ep-rank grouped dispatch, one all_to_all
      pair moves tokens to their experts and back (_moe_ffn)
This composes paddle_tpu.distributed.pipeline's schedule with
context_parallel.ring_attention — the same building blocks exposed to
users — into the flagship configuration the driver dry-runs.

The nn.Layer GPT (models/gpt.py) remains the to_static/propagation path;
this module is the explicit-collectives path for peak control at scale.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.context_parallel import ring_attention
from paddle_tpu.distributed.fleet.mp_ops import (copy_to_tp_region,
                                                 reduce_from_tp_region,
                                                 vocab_parallel_cross_entropy,
                                                 vocab_parallel_embedding)
from paddle_tpu.distributed.pipeline import (
    interleave_layer_permutation,
    pipeline_1f1b_body,
    pipeline_1f1b_interleaved_body,
    pipeline_interleaved_forward_fn,
)


# ---------------------------------------------------------------------------
# Parameter init / sharding specs
# ---------------------------------------------------------------------------

def init_hybrid_gpt_params(cfg, mesh, seed=0, virtual_chunks=1):
    """Whole-array params, device_put with their hybrid PartitionSpecs.

    cfg needs: vocab_size, hidden_size, num_layers, num_heads, ffn size via
    4*hidden, max_seq_len. num_layers must be divisible by the pp degree.

    virtual_chunks > 1 stores the stacked layers in the INTERLEAVED layout
    (device d's shard holds its V non-adjacent logical chunks — see
    interleave_layer_permutation); the logical model is identical, only
    row placement changes.
    """
    H = cfg.hidden_size
    F = getattr(cfg, "ffn_hidden_size", None) or 4 * H
    L = cfg.num_layers
    rng = np.random.default_rng(seed)
    std = 0.02

    def norm(*shape):
        return rng.normal(0.0, std, shape).astype(np.float32)

    stages = {
        "ln1_g": np.ones((L, H), np.float32),
        "ln1_b": np.zeros((L, H), np.float32),
        "w_qkv": norm(L, H, 3 * H),
        "b_qkv": np.zeros((L, 3 * H), np.float32),
        "w_o": norm(L, H, H),
        "b_o": np.zeros((L, H), np.float32),
        "ln2_g": np.ones((L, H), np.float32),
        "ln2_b": np.zeros((L, H), np.float32),
    }
    E = int(getattr(cfg, "moe_num_experts", 0) or 0)
    if E > 0:
        # MoE flagship variant: every layer's FFN becomes E experts
        # sharded over the `ep` mesh axis (GShard dispatch in-block)
        stages.update({
            "gate_w": norm(L, H, E),
            "moe_w1": norm(L, E, H, F),
            "moe_b1": np.zeros((L, E, F), np.float32),
            "moe_w2": norm(L, E, F, H),
            "moe_b2": np.zeros((L, E, H), np.float32),
        })
    else:
        stages.update({
            "w1": norm(L, H, F),
            "b1": np.zeros((L, F), np.float32),
            "w2": norm(L, F, H),
            "b2": np.zeros((L, H), np.float32),
        })
    if virtual_chunks > 1:
        pp = dict(mesh.shape)["pp"]
        perm = interleave_layer_permutation(L, pp, virtual_chunks)
        stages = {k: v[perm] for k, v in stages.items()}
    # record the storage layout on cfg so the schedule factories can
    # refuse a mismatched virtual_chunks (identical shapes would otherwise
    # silently train a layer-permuted model)
    cfg.pipeline_virtual_chunks = virtual_chunks
    params = {
        "wte": norm(cfg.vocab_size, H),
        "wpe": norm(cfg.max_seq_len, H),
        "lnf_g": np.ones((H,), np.float32),
        "lnf_b": np.zeros((H,), np.float32),
        "stages": stages,
    }
    specs = hybrid_param_specs(moe=E > 0)
    return jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), params,
        specs)


def hybrid_param_specs(moe=False):
    """PartitionSpecs: stage dim over pp; Megatron col/row layouts over
    tp; with `moe`, expert weights shard their E dim over ep (the dense
    FFN leaves disappear — every layer's FFN is the expert bank)."""
    stages = {
        "ln1_g": P("pp", None),
        "ln1_b": P("pp", None),
        "w_qkv": P("pp", None, "tp"),   # column-parallel
        "b_qkv": P("pp", "tp"),
        "w_o": P("pp", "tp", None),     # row-parallel
        "b_o": P("pp", None),
        "ln2_g": P("pp", None),
        "ln2_b": P("pp", None),
    }
    if moe:
        stages.update({
            "gate_w": P("pp", None, None),      # router replicated
            "moe_w1": P("pp", "ep", None, None),
            "moe_b1": P("pp", "ep", None),
            "moe_w2": P("pp", "ep", None, None),
            "moe_b2": P("pp", "ep", None),
        })
    else:
        stages.update({
            "w1": P("pp", None, "tp"),      # column-parallel
            "b1": P("pp", "tp"),
            "w2": P("pp", "tp", None),      # row-parallel
            "b2": P("pp", None),
        })
    return {
        "wte": P("tp", None),        # vocab-parallel table + tied head:
        "wpe": P(None, None),        # no full-vocab logits ever materialize
        "lnf_g": P(None),            # (fleet/mp_ops.py)
        "lnf_b": P(None),
        "stages": stages,
    }


# ---------------------------------------------------------------------------
# Local (per-device) math inside shard_map
# ---------------------------------------------------------------------------

def _layer_norm(x, g, b, eps=1e-5):
    x = x.astype(jnp.float32)
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def _make_ep_boundaries(ep_size):
    """Custom-VJP ep-region boundaries (the ep analogue of mp_ops'
    copy_to/reduce_from tp pair): activations REPLICATED over ep carry
    FULL per-rank cotangents in the explicit per-stage vjp, so the plain
    transposes of dynamic_slice (scatter) and all_gather
    (reduce-scatter) would double-count. The pair below implements the
    convention explicitly — split's bwd all-gathers the slice cotangents
    back to full; merge's bwd takes this rank's slice of the full
    cotangent — and is a no-op identity-pair semantics-wise.
    """

    @jax.custom_vjp
    def ep_split(x):
        n = x.shape[0] // ep_size
        r = lax.axis_index("ep")
        return lax.dynamic_slice_in_dim(x, r * n, n, axis=0)

    def split_fwd(x):
        return ep_split(x), None

    def split_bwd(_, d_slice):
        return (lax.all_gather(d_slice, "ep", axis=0, tiled=True),)

    ep_split.defvjp(split_fwd, split_bwd)

    @jax.custom_vjp
    def ep_merge(y_slice):
        return lax.all_gather(y_slice, "ep", axis=0, tiled=True)

    def merge_fwd(y_slice):
        return ep_merge(y_slice), None

    def merge_bwd(_, d_full):
        n = d_full.shape[0] // ep_size
        r = lax.axis_index("ep")
        return (lax.dynamic_slice_in_dim(d_full, r * n, n, axis=0),)

    ep_merge.defvjp(merge_fwd, merge_bwd)
    return ep_split, ep_merge


def _moe_ffn(p, x, top_k, capacity_factor, ep_size, explicit_bwd=False):
    """GShard expert FFN on local shards inside shard_map.

    x: [mb, s_loc, H] this device's tokens (its dp x sp group). Routing
    is per-group (the GShard formulation); the E global experts' weights
    shard E over `ep`, and the token exchange is ONE all_to_all pair
    over the ep axis (distributed/utils/moe_utils.py) — the explicit
    form of what the propagation path gets from a sharding constraint.
    """
    mb, s_loc, H = x.shape
    n_full = mb * s_loc
    flat = x.reshape(n_full, H)
    E = p["gate_w"].shape[-1]
    gate_w = p["gate_w"]
    if ep_size > 1 and explicit_bwd:
        # replicated router weight, per-GROUP tokens: its per-rank grad
        # covers only this rank's group — psum over ep in the backward
        # (the ep analogue of Megatron's copy_to_region boundary)
        gate_w = copy_to_tp_region(gate_w, "ep")
    if ep_size > 1:
        # tokens are REPLICATED across ep (data shards over dp/sp only):
        # each ep rank must dispatch a DISTINCT token group, or every
        # token reaches the experts ep times (ep-times compute and
        # ep-scaled expert grads). Slice this rank's group through the
        # custom-vjp boundary; outputs merge back through its pair.
        if n_full % ep_size:
            raise ValueError("local token count must divide by ep degree")
        n = n_full // ep_size
        if explicit_bwd:
            # per-stage jax.vjp (1F1B): replicated activations carry FULL
            # per-rank cotangents, so the plain slice/all_gather
            # transposes (scatter / reduce-scatter) would double-count —
            # route through the custom-vjp boundary pair instead
            ep_split, ep_merge = _make_ep_boundaries(ep_size)
            flat = ep_split(flat)
        else:
            r = lax.axis_index("ep")
            flat = lax.dynamic_slice_in_dim(flat, r * n, n, axis=0)
    else:
        n = n_full
    from paddle_tpu.distributed.moe import (_capacity,
                                            gshard_dispatch_combine)
    probs = jax.nn.softmax(flat @ gate_w, axis=-1)             # [n, E]
    capacity = _capacity(n, E, top_k, capacity_factor)
    combine, dispatch = gshard_dispatch_combine(probs, top_k, capacity)

    xin = jnp.einsum("nec,nd->ecd", dispatch, flat)            # [E, C, H]
    if ep_size > 1:
        xin = lax.all_to_all(xin, "ep", split_axis=0, concat_axis=1,
                             tiled=True)        # [E/ep, ep*C, H]
    h1 = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xin, p["moe_w1"])
                     + p["moe_b1"][:, None, :], approximate=True)
    out = jnp.einsum("ecf,efd->ecd", h1, p["moe_w2"]) \
        + p["moe_b2"][:, None, :]
    if ep_size > 1:
        out = lax.all_to_all(out, "ep", split_axis=1, concat_axis=0,
                             tiled=True)        # back to [E, C, H]
    y = jnp.einsum("nec,ecd->nd", combine, out)
    if ep_size > 1:
        # reassemble the full replicated token set from the ep groups
        if explicit_bwd:
            y = ep_merge(y)
        else:
            y = lax.all_gather(y, "ep", axis=0, tiled=True)
    return y.reshape(mb, s_loc, H)


def _decoder_block(p, h, num_heads_local, sp_size, explicit_tp_bwd=False,
                   moe_top_k=2, moe_capacity_factor=2.0, ep_size=1):
    """One decoder layer on local shards: tp-split heads/ffn, sp-ring attn.
    h: [mb, s_loc, H]. p leaves are single-layer (no leading layer dim).

    explicit_tp_bwd=True brackets the tp region with Megatron's
    identity/allreduce boundary pair (fleet/mp_ops.py) so an explicit
    per-stage jax.vjp (the 1F1B schedule) transposes the tp collectives
    correctly; the default bare-psum form is for whole-program outer AD."""
    if explicit_tp_bwd:
        def enter(x):
            return copy_to_tp_region(x, "tp")

        def reduce(x):
            return reduce_from_tp_region(x, "tp")
    else:
        def enter(x):
            return x

        def reduce(x):
            return lax.psum(x, "tp")

    mb, s_loc, H = h.shape
    # --- attention ---
    x = _layer_norm(h, p["ln1_g"], p["ln1_b"])
    qkv = enter(x) @ p["w_qkv"] + p["b_qkv"]   # [mb, s_loc, 3H/tp]
    head_dim = p["w_qkv"].shape[1] // 3 // num_heads_local
    qkv = qkv.reshape(mb, s_loc, num_heads_local, 3 * head_dim)
    qkv = jnp.moveaxis(qkv, 2, 1)              # [mb, h_loc, s_loc, 3hd]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    o = ring_attention(q, k, v, axis_name="sp", causal=True,
                       axis_size=sp_size)      # exact causal over sp ring
    o = jnp.moveaxis(o, 1, 2).reshape(mb, s_loc, -1)
    attn = o @ p["w_o"]                        # partial sums over tp shard
    attn = reduce(attn) + p["b_o"]             # row-parallel reduce
    h = h + attn
    # --- mlp / moe ---
    x = _layer_norm(h, p["ln2_g"], p["ln2_b"])
    if "gate_w" in p:
        # MoE branch: no tp collectives (experts shard over ep; the
        # router and dispatch replicate over tp)
        y = _moe_ffn(p, x.astype(h.dtype), moe_top_k,
                     moe_capacity_factor, ep_size,
                     explicit_bwd=explicit_tp_bwd)
    else:
        y = jax.nn.gelu(enter(x) @ p["w1"] + p["b1"], approximate=True)
        y = reduce(y @ p["w2"]) + p["b2"]      # row-parallel reduce
    return h + y


def _pipeline_trunk(stage_params, h_mb, block_fn, pp_size):
    """GPipe microbatch schedule over pp (see distributed/pipeline.py).
    h_mb: [M, mb, s_loc, H]; stage_params leaves: [layers_local, ...]."""
    stage = lax.axis_index("pp")
    M = h_mb.shape[0]
    perm = [(i, (i + 1) % pp_size) for i in range(pp_size)]

    def apply_stage(prev_y, t):
        inp = jnp.where(stage == 0, h_mb[jnp.clip(t, 0, M - 1)], prev_y)

        def one(x, pl):   # scan over this stage's local layers
            return jax.checkpoint(block_fn)(pl, x), None
        out, _ = lax.scan(one, inp, stage_params)
        return out

    def tick(prev_y, t):
        inbound = lax.ppermute(prev_y, "pp", perm)
        y = apply_stage(inbound, t)
        return y, y

    y0 = apply_stage(jnp.zeros_like(h_mb[0]), 0)
    if pp_size == 1:
        rest = [apply_stage(h_mb[t], t) for t in range(1, M)]
        return jnp.stack([y0] + rest, 0)
    _, ys = lax.scan(tick, y0, jnp.arange(1, M + pp_size - 1))
    ys = jnp.concatenate([y0[None], ys], 0)
    outputs = jnp.where(stage == pp_size - 1, ys[pp_size - 1:], 0.0)
    return lax.psum(outputs, "pp")


def _check_layout(cfg, virtual_chunks):
    stored = getattr(cfg, "pipeline_virtual_chunks", 1)
    if stored != virtual_chunks:
        raise ValueError(
            f"params were initialized with virtual_chunks={stored} but the "
            f"schedule was built with virtual_chunks={virtual_chunks}; "
            "layer placement would silently be wrong "
            "(init_hybrid_gpt_params and the schedule factory must agree)")


def _hybrid_degrees(cfg, mesh):
    """Validate cfg divisibility against the mesh; returns
    (tp, sp, pp, ep, heads_local) — shared by the schedule factories."""
    shape = dict(mesh.shape)
    tp, sp, pp = shape["tp"], shape["sp"], shape["pp"]
    ep = shape.get("ep", 1)
    if cfg.num_heads % tp:
        raise ValueError("num_heads must divide by tp degree")
    if cfg.num_layers % pp:
        raise ValueError("num_layers must divide by pp degree")
    if cfg.vocab_size % tp:
        raise ValueError("vocab_size must divide by tp degree")
    E = int(getattr(cfg, "moe_num_experts", 0) or 0)
    if E and E % ep:
        raise ValueError("moe_num_experts must divide by ep degree")
    if ep > 1 and not E:
        raise ValueError("mesh has ep > 1 but cfg.moe_num_experts is 0")
    return tp, sp, pp, ep, cfg.num_heads // tp


def _moe_knobs(cfg):
    """(top_k, train capacity factor) resolved once for both factories."""
    cf = getattr(cfg, "moe_capacity_factor", (2.0, 2.0)) or (2.0, 2.0)
    return getattr(cfg, "moe_top_k", 2), cf[0]


def _embed_fn(ids, num_microbatches, explicit_bwd):
    """Shared token+position embedding closure: vocab-parallel table
    (wte tp-sharded on the vocab dim; masked local lookup + psum), global
    positions via the sp shard index, reshaped into the [M, mb, s_loc, H]
    microbatch stream the pipeline consumes."""
    b_loc, s_loc = ids.shape
    if b_loc % num_microbatches:
        raise ValueError(
            f"per-dp-shard batch {b_loc} must divide by num_microbatches "
            f"{num_microbatches} (a zero-sized microbatch otherwise "
            "surfaces as an opaque reshape error)")
    pos = lax.axis_index("sp") * s_loc + jnp.arange(s_loc)

    def embed(wte, wpe):
        h = vocab_parallel_embedding(wte, ids, "tp",
                                     explicit_bwd=explicit_bwd) \
            + wpe[pos][None, :, :]
        return h.reshape(num_microbatches, b_loc // num_microbatches,
                         s_loc, -1)

    return embed


def make_hybrid_loss_fn(cfg, mesh, num_microbatches=2, pipeline="gpipe",
                        virtual_chunks=1):
    """Whole-array loss(params, ids, labels) -> scalar; jit/grad-able.

    ids/labels: [B, S] sharded (dp, sp). Composes the dp/pp/tp/sp program
    described in the module docstring inside one shard_map.

    pipeline: "gpipe" (scan+ppermute trunk) or "interleave"
    (virtual-stage folded ring, `virtual_chunks` chunks per device —
    params must come from init_hybrid_gpt_params(virtual_chunks=V)).
    Both differentiate via outer AD; the explicit 1F1B schedule lives in
    make_hybrid_grad_fn.
    """
    tp, sp, pp, ep, heads_local = _hybrid_degrees(cfg, mesh)
    _check_layout(cfg, virtual_chunks if pipeline == "interleave" else 1)
    M = num_microbatches
    moe = bool(getattr(cfg, "moe_num_experts", 0))

    def local_loss(params, ids, labels):
        b_loc, s_loc = ids.shape
        h = _embed_fn(ids, M, False)(params["wte"], params["wpe"])
        moe_top_k, moe_cf = _moe_knobs(cfg)
        block = functools.partial(
            _decoder_block, num_heads_local=heads_local, sp_size=sp,
            moe_top_k=moe_top_k, moe_capacity_factor=moe_cf, ep_size=ep)
        if pipeline == "interleave":
            v = virtual_chunks

            def chunk_fn(chunk_params, xmb):
                def one(xc, pl):
                    return jax.checkpoint(block)(pl, xc), None
                out, _ = lax.scan(one, xmb, chunk_params)
                return out

            chunked = jax.tree_util.tree_map(
                lambda p: p.reshape((v, p.shape[0] // v) + p.shape[1:]),
                params["stages"])
            body = pipeline_interleaved_forward_fn(
                chunk_fn, "pp", axis_size=pp, num_chunks=v)
            h = body(chunked, h)
        else:
            h = _pipeline_trunk(params["stages"], h, block, pp)
        h = h.reshape(b_loc, s_loc, -1)
        h = _layer_norm(h, params["lnf_g"], params["lnf_b"])
        # tied head against the LOCAL vocab shard: [b, s, V/tp] is the
        # largest logits tensor that ever exists; CE runs sharded
        logits_local = h @ params["wte"].T
        nll = vocab_parallel_cross_entropy(logits_local, labels, "tp")
        total = lax.psum(jnp.sum(nll), ("dp", "sp"))
        count = lax.psum(jnp.asarray(nll.size, jnp.float32), ("dp", "sp"))
        return total / count

    specs = hybrid_param_specs(moe=moe)
    data_spec = P("dp", "sp")
    return jax.shard_map(local_loss, mesh=mesh,
                         in_specs=(specs, data_spec, data_spec),
                         out_specs=P(), check_vma=False)


def make_hybrid_grad_fn(cfg, mesh, num_microbatches=2, virtual_chunks=1):
    """Explicit 1F1B loss+grad for the flagship (r3, VERDICT #3).

    Reference: fleet/meta_parallel/pipeline_parallel.py:117
    (`forward_backward_pipeline`, "the 1f1b scheduling strategy"). Unlike
    make_hybrid_loss_fn (whose GPipe trunk differentiates via outer AD),
    this composes distributed/pipeline.py's explicit 1F1B schedule — the
    per-tick interleaved forward/backward with an O(pp) activation ring
    buffer — with the same tp psums and sp ring attention, so the schedule
    that shrinks pipeline memory actually runs under the flagship's 4-D
    sharding. The embedding and tied head sit outside the schedule: the
    embed's VJP is applied to the dx_mb the pipeline returns, and the head
    grads ride the schedule's loss_params slot.

    virtual_chunks > 1 (r4, VERDICT #5) switches to the INTERLEAVED 1F1B
    schedule (pipeline_1f1b_interleaved_body): V virtual stages per
    device composed WITH the explicit per-tick fwd/bwd — bubble/V and the
    O(pp·V-chunk-input) activation bound together, which is the actual
    semantics of the reference's PipelineParallelWithInterleave
    (pipeline_parallel.py:461). Params must come from
    init_hybrid_gpt_params(virtual_chunks=V).

    Returns fn(params, ids, labels) -> (loss, grads) for the whole mesh.
    """
    tp, sp, pp, ep, heads_local = _hybrid_degrees(cfg, mesh)
    _check_layout(cfg, virtual_chunks)
    M = num_microbatches
    moe = bool(getattr(cfg, "moe_num_experts", 0))

    def local_step(params, ids, labels):
        b_loc, s_loc = ids.shape
        embed = _embed_fn(ids, M, True)
        h_mb, embed_vjp = jax.vjp(embed, params["wte"], params["wpe"])
        labels_mb = labels.reshape(M, b_loc // M, s_loc)
        moe_top_k, moe_cf = _moe_knobs(cfg)
        block = functools.partial(
            _decoder_block, num_heads_local=heads_local, sp_size=sp,
            explicit_tp_bwd=True,
            moe_top_k=moe_top_k, moe_capacity_factor=moe_cf, ep_size=ep)

        def stage_fn(stage_params, x):
            def one(xc, pl):
                return jax.checkpoint(block)(pl, xc), None
            out, _ = lax.scan(one, x, stage_params)
            return out

        def loss_fn(lp, y, lab):
            h = _layer_norm(y, lp["lnf_g"], lp["lnf_b"])
            # copy_to_tp_region: the head consumes the replicated h on
            # every tp rank — its vjp must psum the cotangent back
            logits_local = copy_to_tp_region(h, "tp") @ lp["wte"].T
            nll = vocab_parallel_cross_entropy(logits_local, lab, "tp",
                                               explicit_bwd=True)
            return jnp.sum(nll)

        loss_params = {"lnf_g": params["lnf_g"], "lnf_b": params["lnf_b"],
                       "wte": params["wte"]}
        if virtual_chunks > 1:
            v = virtual_chunks
            chunked = jax.tree_util.tree_map(
                lambda p: p.reshape((v, p.shape[0] // v) + p.shape[1:]),
                params["stages"])
            loss_sum, g_chunks, gloss, dx_mb = \
                pipeline_1f1b_interleaved_body(
                    stage_fn, loss_fn, chunked, loss_params,
                    h_mb, labels_mb, axis_name="pp", axis_size=pp,
                    num_chunks=v)
            g_stages = jax.tree_util.tree_map(
                lambda g: g.reshape((g.shape[0] * g.shape[1],)
                                    + g.shape[2:]), g_chunks)
        else:
            loss_sum, g_stages, gloss, dx_mb = pipeline_1f1b_body(
                stage_fn, loss_fn, params["stages"], loss_params,
                h_mb, labels_mb, axis_name="pp", axis_size=pp)
        d_wte_e, d_wpe = embed_vjp(dx_mb)

        total = lax.psum(loss_sum, ("dp", "sp"))
        count = lax.psum(jnp.asarray(b_loc * s_loc, jnp.float32),
                         ("dp", "sp"))
        inv = 1.0 / count
        grads = {
            "wte": gloss["wte"] + d_wte_e,
            "wpe": d_wpe,
            "lnf_g": gloss["lnf_g"],
            "lnf_b": gloss["lnf_b"],
            "stages": g_stages,
        }
        grads = jax.tree_util.tree_map(
            lambda g: lax.psum(g, ("dp", "sp")) * inv, grads)
        return total * inv, grads

    specs = hybrid_param_specs(moe=moe)
    data_spec = P("dp", "sp")
    return jax.shard_map(local_step, mesh=mesh,
                         in_specs=(specs, data_spec, data_spec),
                         out_specs=(P(), specs), check_vma=False)


def make_hybrid_train_step(cfg, mesh, lr=1e-3, num_microbatches=2,
                           schedule="1f1b", virtual_chunks=1):
    """SGD train step over the hybrid program; returns jitted
    step(params, ids, labels) -> (params, loss). Update is elementwise, so
    every param keeps its hybrid sharding (dp grad-sync fell out of the
    shard_map transpose — or, on the 1F1B path, explicit dp/sp psums).

    schedule: "1f1b" (explicit interleaved fwd/bwd pipeline, the flagship
    default), "interleave" (virtual-stage 1F1B — V chunks per device
    composed with the explicit per-tick fwd/bwd schedule, keeping BOTH
    the bubble/V and the 1F1B activation-memory win; init params with the
    matching virtual_chunks layout), or "gpipe" (scan+ppermute forward
    trunk, outer AD backward). "interleave-fwd" keeps r3's forward-only
    folded ring with outer AD, for comparison.
    """
    if schedule in ("1f1b", "interleave"):
        grad_fn = make_hybrid_grad_fn(
            cfg, mesh, num_microbatches,
            virtual_chunks=virtual_chunks if schedule == "interleave"
            else 1)

        @jax.jit
        def step(params, ids, labels):
            loss, grads = grad_fn(params, ids, labels)
            params = jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                            params, grads)
            return params, loss
    elif schedule in ("gpipe", "interleave-fwd"):
        loss_fn = make_hybrid_loss_fn(
            cfg, mesh, num_microbatches,
            pipeline="interleave" if schedule == "interleave-fwd"
            else "gpipe",
            virtual_chunks=virtual_chunks)

        @jax.jit
        def step(params, ids, labels):
            loss, grads = jax.value_and_grad(loss_fn)(params, ids, labels)
            params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params,
                                            grads)
            return params, loss
    else:
        raise ValueError(f"unknown pipeline schedule: {schedule!r}")

    step.schedule = schedule
    return step
