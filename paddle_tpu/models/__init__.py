"""Flagship model families (parity targets from BASELINE.json configs).

Reference counterparts live in PaddleNLP/PaddleClas model zoos built on the
reference framework's fleet meta-parallel layers
(python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py);
here each model is built TPU-first on paddle_tpu's mesh-sharded layers.
"""
from paddle_tpu.models import gpt  # noqa: F401
from paddle_tpu.models.gpt import (  # noqa: F401
    GPTConfig,
    GPTForCausalLM,
    GPTModel,
    GPTPretrainingCriterion,
    gpt3_1p3b,
    gpt3_tiny,
)
