"""Flagship model families (parity targets from BASELINE.json configs).

Reference counterparts live in PaddleNLP/PaddleClas model zoos built on the
reference framework's fleet meta-parallel layers
(python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py);
here each model is built TPU-first on paddle_tpu's mesh-sharded layers.
"""
from paddle_tpu.models import bert, ernie, gpt, vit  # noqa: F401
from paddle_tpu.models.bert import (  # noqa: F401
    BertConfig,
    BertForPretraining,
    BertForSequenceClassification,
    BertModel,
    BertPretrainingCriterion,
    bert_base,
    bert_large,
    bert_tiny,
)
from paddle_tpu.models.ernie import (  # noqa: F401
    ErnieConfig,
    ErnieForPretraining,
    ErnieForSequenceClassification,
    ErnieModel,
    ernie_3_0_base,
    ernie_3_0_medium,
    ernie_tiny,
)
from paddle_tpu.models.gpt import (  # noqa: F401
    GPTConfig,
    GPTForCausalLM,
    GPTModel,
    GPTPretrainingCriterion,
    gpt3_1p3b,
    gpt3_tiny,
)
from paddle_tpu.models.vit import (  # noqa: F401
    ViT,
    ViTConfig,
    VisionTransformer,
    vit_b_16,
    vit_l_16,
    vit_tiny,
)
from paddle_tpu.models.deepfm import DeepFM, DeepFMCriterion, SparseEmbeddingBag  # noqa: F401
