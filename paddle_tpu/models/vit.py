"""Vision Transformer (ViT-B parity target from BASELINE.json configs).

Reference parity: PaddleClas ViT (ppcls/arch/backbone/model_zoo/
vision_transformer.py in the PaddleClas zoo) built on the reference
framework. TPU-native: patchify as a single conv (MXU), encoder blocks share
the tp/sp-sharded attention+ffn design, class-token pooling.
"""
from __future__ import annotations

import paddle_tpu
from paddle_tpu.distributed.fleet.meta_parallel import _constrain
from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I


class ViTConfig:
    def __init__(self, image_size=224, patch_size=16, in_channels=3,
                 hidden_size=768, num_layers=12, num_heads=12,
                 ffn_hidden_size=None, num_classes=1000, dropout=0.0,
                 attention_dropout=0.0, drop_path=0.0,
                 layer_norm_epsilon=1e-6, representation_size=None):
        self.image_size = image_size
        self.patch_size = patch_size
        self.in_channels = in_channels
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.num_classes = num_classes
        self.dropout = dropout
        self.attention_dropout = attention_dropout
        self.drop_path = drop_path
        self.layer_norm_epsilon = layer_norm_epsilon
        self.representation_size = representation_size
        self.num_patches = (image_size // patch_size) ** 2


def vit_b_16(**kw):
    return ViTConfig(**kw)


def vit_l_16(**kw):
    cfg = dict(hidden_size=1024, num_layers=24, num_heads=16)
    cfg.update(kw)
    return ViTConfig(**cfg)


def vit_tiny(**kw):
    cfg = dict(image_size=32, patch_size=8, hidden_size=64, num_layers=2,
               num_heads=4, num_classes=10)
    cfg.update(kw)
    return ViTConfig(**cfg)


class PatchEmbed(nn.Layer):
    def __init__(self, config: ViTConfig):
        super().__init__()
        self.proj = nn.Conv2D(config.in_channels, config.hidden_size,
                              kernel_size=config.patch_size,
                              stride=config.patch_size)

    def forward(self, x):
        x = self.proj(x)                       # [b, hid, gh, gw]
        b, c = x.shape[0], x.shape[1]
        return x.reshape([b, c, -1]).transpose([0, 2, 1])   # [b, n, hid]


class ViTBlock(nn.Layer):
    """Pre-LN encoder block (same residual form as GPT, bidirectional)."""

    def __init__(self, config: ViTConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        self.ln1 = nn.LayerNorm(config.hidden_size,
                                epsilon=config.layer_norm_epsilon)
        self.qkv = nn.Linear(config.hidden_size, 3 * config.hidden_size)
        self.proj = nn.Linear(config.hidden_size, config.hidden_size)
        self.ln2 = nn.LayerNorm(config.hidden_size,
                                epsilon=config.layer_norm_epsilon)
        self.fc1 = nn.Linear(config.hidden_size, config.ffn_hidden_size)
        self.fc2 = nn.Linear(config.ffn_hidden_size, config.hidden_size)
        self.dropout = nn.Dropout(config.dropout)
        self.attn_dropout_p = config.attention_dropout

    def forward(self, x):
        b, n = x.shape[0], x.shape[1]
        h = self.ln1(x)
        qkv = self.qkv(h).reshape([b, n, self.num_heads, 3 * self.head_dim])
        q, k, v = qkv.split(3, axis=-1)
        attn = F.scaled_dot_product_attention(
            q, k, v,
            dropout_p=self.attn_dropout_p if self.training else 0.0,
            training=self.training)
        attn = attn.reshape([b, n, self.num_heads * self.head_dim])
        x = x + self.dropout(self.proj(attn))
        x = x + self.dropout(self.fc2(F.gelu(self.fc1(self.ln2(x)),
                                             approximate=True)))
        return _constrain(x, "dp", None, None)


class VisionTransformer(nn.Layer):
    def __init__(self, config: ViTConfig):
        super().__init__()
        self.config = config
        self.patch_embed = PatchEmbed(config)
        self.cls_token = self.create_parameter(
            shape=[1, 1, config.hidden_size],
            default_initializer=I.TruncatedNormal(std=0.02))
        self.pos_embed = self.create_parameter(
            shape=[1, config.num_patches + 1, config.hidden_size],
            default_initializer=I.TruncatedNormal(std=0.02))
        self.pos_drop = nn.Dropout(config.dropout)
        self.blocks = nn.LayerList(
            [ViTBlock(config) for _ in range(config.num_layers)])
        self.norm = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)
        self.head = nn.Linear(config.hidden_size, config.num_classes) \
            if config.num_classes > 0 else None

    def forward(self, x):
        x = self.patch_embed(x)
        b = x.shape[0]
        cls = self.cls_token.expand([b, 1, self.config.hidden_size])
        x = paddle_tpu.concat([cls, x], axis=1) + self.pos_embed
        x = self.pos_drop(x)
        for blk in self.blocks:
            x = blk(x)
        x = self.norm(x)
        if self.head is None:
            return x
        return self.head(x[:, 0])


ViT = VisionTransformer
