"""BERT-base encoder family (and the ERNIE-3.0 variant in ernie.py).

Reference parity: PaddleNLP BertModel/BertForPretraining built on the
reference framework (nn.TransformerEncoder — reference:
python/paddle/nn/layer/transformer.py:900+). TPU-native: mesh-sharded
attention/ffn (tp), batch→dp / seq→sp activation shardings, flash-attention
fast path, bf16-friendly (fp32 layernorm accumulators inside the fused
kernel).
"""
from __future__ import annotations

import paddle_tpu
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    _constrain,
)
from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768, num_layers=12,
                 num_heads=12, ffn_hidden_size=None, max_position=512,
                 type_vocab_size=2, dropout=0.1, attention_dropout=0.1,
                 initializer_range=0.02, layer_norm_epsilon=1e-12,
                 pad_token_id=0):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.dropout = dropout
        self.attention_dropout = attention_dropout
        self.initializer_range = initializer_range
        self.layer_norm_epsilon = layer_norm_epsilon
        self.pad_token_id = pad_token_id


def bert_base(**kw):
    return BertConfig(**kw)


def bert_large(**kw):
    cfg = dict(hidden_size=1024, num_layers=24, num_heads=16)
    cfg.update(kw)
    return BertConfig(**cfg)


def bert_tiny(**kw):
    cfg = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
               max_position=128, dropout=0.0, attention_dropout=0.0)
    cfg.update(kw)
    return BertConfig(**cfg)


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        init = I.ParamAttr(initializer=I.Normal(0.0, config.initializer_range))
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size, weight_attr=init)
        self.position_embeddings = nn.Embedding(
            config.max_position, config.hidden_size, weight_attr=init)
        self.token_type_embeddings = nn.Embedding(
            config.type_vocab_size, config.hidden_size, weight_attr=init)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_epsilon)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[-1]
        if position_ids is None:
            position_ids = paddle_tpu.arange(s, dtype="int64")
        if token_type_ids is None:
            token_type_ids = paddle_tpu.zeros_like(input_ids)
        h = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        h = _constrain(h, "dp", "sp", None)
        return self.dropout(self.layer_norm(h))


class BertSelfAttention(nn.Layer):
    """Bidirectional attention; same tp head-sharded layout as GPTAttention."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        init = I.ParamAttr(initializer=I.Normal(0.0, config.initializer_range))
        self.qkv_proj = ColumnParallelLinear(
            config.hidden_size, 3 * config.hidden_size, weight_attr=init,
            gather_output=False)
        self.out_proj = RowParallelLinear(
            config.hidden_size, config.hidden_size, weight_attr=init,
            input_is_parallel=True)
        self.attn_dropout_p = config.attention_dropout

    def forward(self, hidden, attn_mask=None):
        b, s = hidden.shape[0], hidden.shape[1]
        qkv = self.qkv_proj(hidden)
        qkv = qkv.reshape([b, s, self.num_heads, 3 * self.head_dim])
        qkv = _constrain(qkv, "dp", "sp", "tp", None)
        q, k, v = qkv.split(3, axis=-1)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_p if self.training else 0.0,
            training=self.training)
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        return self.out_proj(out)


class BertLayer(nn.Layer):
    """Post-LN encoder block (original BERT residual placement)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        init = I.ParamAttr(initializer=I.Normal(0.0, config.initializer_range))
        self.attention = BertSelfAttention(config)
        self.ln1 = nn.LayerNorm(config.hidden_size,
                                epsilon=config.layer_norm_epsilon)
        self.fc1 = ColumnParallelLinear(
            config.hidden_size, config.ffn_hidden_size, weight_attr=init,
            gather_output=False)
        self.fc2 = RowParallelLinear(
            config.ffn_hidden_size, config.hidden_size, weight_attr=init,
            input_is_parallel=True)
        self.ln2 = nn.LayerNorm(config.hidden_size,
                                epsilon=config.layer_norm_epsilon)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x, attn_mask=None):
        x = self.ln1(x + self.dropout(self.attention(x, attn_mask)))
        x = self.ln2(x + self.dropout(self.fc2(F.gelu(self.fc1(x)))))
        return _constrain(x, "dp", "sp", None)


class BertPooler(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, hidden):
        return paddle_tpu.tanh(self.dense(hidden[:, 0]))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList(
            [BertLayer(config) for _ in range(config.num_layers)])
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is not None and len(attention_mask.shape) == 2:
            # [b, s] padding mask -> additive [b, 1, 1, s] logits bias
            m = (1.0 - attention_mask.astype("float32")) * -1e4
            attention_mask = m.unsqueeze(1).unsqueeze(2)
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        for layer in self.encoder:
            h = layer(h, attention_mask)
        return h, self.pooler(h)


class BertLMHead(nn.Layer):
    def __init__(self, config: BertConfig, embedding_weight):
        super().__init__()
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_epsilon)
        self.decoder_weight = embedding_weight  # tied [vocab, hidden]
        self.decoder_bias = self.create_parameter(
            shape=[config.vocab_size], is_bias=True)

    def forward(self, h):
        h = self.layer_norm(F.gelu(self.transform(h)))
        return paddle_tpu.matmul(h, self.decoder_weight,
                                 transpose_y=True) + self.decoder_bias


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (reference: PaddleNLP BertForPretraining)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.cls = BertLMHead(
            config, self.bert.embeddings.word_embeddings.weight)
        self.nsp = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        h, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        return self.cls(h), self.nsp(pooled)


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.dropout)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        return self.classifier(self.dropout(pooled))


class BertPretrainingCriterion(nn.Layer):
    def __init__(self, vocab_size):
        super().__init__()
        self.vocab_size = vocab_size

    def forward(self, prediction_scores, seq_relationship_score,
                masked_lm_labels, next_sentence_labels=None,
                masked_lm_weights=None):
        mlm = F.cross_entropy(prediction_scores, masked_lm_labels,
                              reduction="none", ignore_index=-100)
        if masked_lm_weights is not None:
            w = masked_lm_weights.reshape(mlm.shape).astype(mlm.dtype)
            mlm = (mlm * w).sum() / w.sum().clip(min=1.0)
        else:
            mlm = mlm.mean()
        if next_sentence_labels is None:
            return mlm
        nsp = F.cross_entropy(seq_relationship_score, next_sentence_labels)
        return mlm + nsp
