"""DeepFM — sparse CTR model (the reference's sparse/PS parity target).

Reference parity: PaddleRec DeepFM on the reference framework: huge
embedding tables live on parameter servers, workers pull rows per batch
(distributed/fleet PS mode, paddle.static.nn.sparse_embedding).

TPU-native design: no parameter server — the embedding table is a dense
array SHARDED over the mesh (vocab dim on the `mp` axis, falling back to
replicated on smaller meshes); lookups are XLA gathers and sharding
propagation turns the per-shard partial lookups into one ICI all-gather of
just the touched rows' embeddings. The FM + deep tower are standard MXU
matmuls. This trades the PS's sparse pull RPCs for collectives that ride
ICI — the idiomatic TPU recipe for embedding-heavy models.
"""
from __future__ import annotations

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu.distributed.mesh import shard_tensor
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I


class SparseEmbeddingBag(nn.Layer):
    """Vocab-sharded embedding table for categorical id features.

    weight: [vocab, dim] with the vocab dim annotated over the `mp` mesh
    axis (reference analogue: sparse_embedding on a PS table)."""

    def __init__(self, vocab_size, embedding_dim, mesh_axis="mp",
                 init_std=0.01):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[vocab_size, embedding_dim],
            default_initializer=I.Normal(0.0, init_std))
        shard_tensor(self.weight, mesh_axis, None)

    def forward(self, ids):
        return F.embedding(ids, self.weight)


class DeepFM(nn.Layer):
    """DeepFM: first-order + FM second-order + deep MLP over shared
    per-field embeddings.

    Inputs: sparse_ids [batch, num_fields] int feature ids (already hashed
    into [0, vocab)), dense [batch, dense_dim] float features.
    Output: CTR logit [batch, 1].
    """

    def __init__(self, vocab_size=1000000, num_fields=26, embedding_dim=16,
                 dense_dim=13, mlp_sizes=(400, 400, 400), mesh_axis="mp"):
        super().__init__()
        self.num_fields = num_fields
        self.embedding_dim = embedding_dim
        # first order: per-id scalar weight + linear over dense feats
        self.fo_embedding = SparseEmbeddingBag(vocab_size, 1, mesh_axis)
        self.fo_dense = nn.Linear(dense_dim, 1)
        # second order + deep share one table (standard DeepFM)
        self.embedding = SparseEmbeddingBag(vocab_size, embedding_dim,
                                            mesh_axis)
        self.dense_proj = nn.Linear(dense_dim, embedding_dim)
        layers = []
        in_dim = (num_fields + 1) * embedding_dim
        for h in mlp_sizes:
            layers += [nn.Linear(in_dim, h), nn.ReLU()]
            in_dim = h
        layers.append(nn.Linear(in_dim, 1))
        self.mlp = nn.Sequential(*layers)

    def forward(self, sparse_ids, dense):
        b = sparse_ids.shape[0]
        # ---- first order ----
        fo = self.fo_embedding(sparse_ids).reshape([b, self.num_fields])
        first = fo.sum(axis=1, keepdim=True) + self.fo_dense(dense)
        # ---- second order (FM): 0.5 * ((Σe)² − Σe²) ----
        emb = self.embedding(sparse_ids)          # [b, fields, k]
        dense_emb = self.dense_proj(dense).unsqueeze(1)   # [b, 1, k]
        feats = paddle_tpu.concat([emb, dense_emb], axis=1)
        sum_sq = feats.sum(axis=1).pow(2)
        sq_sum = feats.pow(2).sum(axis=1)
        second = (0.5 * (sum_sq - sq_sum)).sum(axis=1, keepdim=True)
        # ---- deep ----
        deep = self.mlp(feats.reshape([b, -1]))
        return first + second + deep


class DeepFMCriterion(nn.Layer):
    """Pointwise CTR loss: BCE with logits."""

    def forward(self, logits, labels):
        return F.binary_cross_entropy_with_logits(
            logits, labels.astype(logits.dtype).reshape(logits.shape))


class DeepFMPS(nn.Layer):
    """DeepFM with BEYOND-HBM embedding tables (r3, VERDICT #6).

    Reference parity: the trillion-parameter PS configuration
    (distributed/ps/the_one_ps.py + sparse_embedding): embedding rows
    live in host RAM (distributed/ps.py SparseTable), each step pulls
    only the touched [batch, fields, dim] slice to the device and pushes
    sparse gradients back to the host optimizer. The dense tower (FM +
    MLP) remains an ordinary device model trained by a normal optimizer;
    the tables never enter parameters()/HBM, so capacity is bounded by
    host RAM — the scale story the mesh-sharded DeepFM (above) cannot
    reach past aggregate HBM.
    """

    def __init__(self, vocab_size=1000000, num_fields=26, embedding_dim=16,
                 dense_dim=13, mlp_sizes=(400, 400, 400), ps_optimizer=
                 "adagrad", ps_learning_rate=0.05, seed=0):
        super().__init__()
        from paddle_tpu.distributed.ps import PSEmbedding

        self.num_fields = num_fields
        self.embedding_dim = embedding_dim
        self.fo_embedding = PSEmbedding(
            vocab_size, 1, optimizer=ps_optimizer,
            learning_rate=ps_learning_rate, seed=seed)
        self.embedding = PSEmbedding(
            vocab_size, embedding_dim, optimizer=ps_optimizer,
            learning_rate=ps_learning_rate, seed=seed + 1)
        self.fo_dense = nn.Linear(dense_dim, 1)
        self.dense_proj = nn.Linear(dense_dim, embedding_dim)
        layers = []
        in_dim = (num_fields + 1) * embedding_dim
        for h in mlp_sizes:
            layers += [nn.Linear(in_dim, h), nn.ReLU()]
            in_dim = h
        layers.append(nn.Linear(in_dim, 1))
        self.mlp = nn.Sequential(*layers)

    def forward(self, sparse_ids, dense):
        b = sparse_ids.shape[0]
        fo = self.fo_embedding(sparse_ids).reshape([b, self.num_fields])
        first = fo.sum(axis=1, keepdim=True) + self.fo_dense(dense)
        emb = self.embedding(sparse_ids)                  # [b, fields, k]
        dense_emb = self.dense_proj(dense).unsqueeze(1)
        feats = paddle_tpu.concat([emb, dense_emb], axis=1)
        sum_sq = feats.sum(axis=1).pow(2)
        sq_sum = feats.pow(2).sum(axis=1)
        second = (0.5 * (sum_sq - sq_sum)).sum(axis=1, keepdim=True)
        deep = self.mlp(feats.reshape([b, -1]))
        return first + second + deep
