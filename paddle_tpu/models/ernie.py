"""ERNIE-3.0 (BASELINE.json parity target: ERNIE-3.0 pretraining tokens/s).

Reference parity: PaddleNLP ErnieModel — architecturally a BERT-style
encoder with task/type embeddings and shared underlying layers; the
framework-level machinery (fleet DP allreduce → XLA dp-psum, AMP, to_static)
is identical to bert.py, so ERNIE shares the Bert building blocks here, same
as PaddleNLP shares its TransformerEncoder.
"""
from __future__ import annotations

from paddle_tpu import nn
from paddle_tpu.models.bert import (
    BertConfig,
    BertLayer,
    BertLMHead,
    BertModel,
    BertPooler,
    BertPretrainingCriterion,
)
from paddle_tpu.nn import functional as F


class ErnieConfig(BertConfig):
    def __init__(self, task_type_vocab_size=3, use_task_id=True, **kw):
        kw.setdefault("vocab_size", 40000)
        kw.setdefault("layer_norm_epsilon", 1e-5)
        super().__init__(**kw)
        self.task_type_vocab_size = task_type_vocab_size
        self.use_task_id = use_task_id


def ernie_3_0_base(**kw):
    cfg = dict(hidden_size=768, num_layers=12, num_heads=12)
    cfg.update(kw)
    return ErnieConfig(**cfg)


def ernie_3_0_medium(**kw):
    cfg = dict(hidden_size=768, num_layers=6, num_heads=12)
    cfg.update(kw)
    return ErnieConfig(**cfg)


def ernie_tiny(**kw):
    cfg = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
               max_position=128, dropout=0.0, attention_dropout=0.0)
    cfg.update(kw)
    return ErnieConfig(**cfg)


class ErnieModel(BertModel):
    """BERT encoder + task-type embedding (ERNIE-3.0 universal
    representation)."""

    def __init__(self, config: ErnieConfig):
        super().__init__(config)
        if config.use_task_id:
            self.task_type_embeddings = nn.Embedding(
                config.task_type_vocab_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        if attention_mask is not None and len(attention_mask.shape) == 2:
            m = (1.0 - attention_mask.astype("float32")) * -1e4
            attention_mask = m.unsqueeze(1).unsqueeze(2)
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        if self.config.use_task_id and task_type_ids is not None:
            h = h + self.task_type_embeddings(task_type_ids)
        for layer in self.encoder:
            h = layer(h, attention_mask)
        return h, self.pooler(h)


class ErnieForPretraining(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.cls = BertLMHead(
            config, self.ernie.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                task_type_ids=None):
        h, _ = self.ernie(input_ids, token_type_ids,
                          attention_mask=attention_mask,
                          task_type_ids=task_type_ids)
        return self.cls(h)


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, config: ErnieConfig, num_classes=2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = nn.Dropout(config.dropout)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.ernie(input_ids, token_type_ids,
                               attention_mask=attention_mask)
        return self.classifier(self.dropout(pooled))


ErniePretrainingCriterion = BertPretrainingCriterion
