"""Weight initializers. Reference: python/paddle/nn/initializer/*."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework.state import next_key


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity not in gains:
        raise ValueError(f"Unsupported nonlinearity {nonlinearity}")
    return gains[nonlinearity]


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle Linear weight is [in, out]
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, param, block=None):
        v = self._generate(tuple(param._value.shape), param._value.dtype)
        param._set_value(v.astype(param._value.dtype))
        return param

    def _generate(self, shape, dtype):
        raise NotImplementedError


def _host_rng():
    """Numpy Generator fed from the global PRNG key stream.

    Init-time randomness runs on HOST: a jax.random call per parameter
    costs one XLA mini-compile per distinct (shape, dtype), which
    dominates model-construction time (~50-100 ms each on CPU; a ResNet
    has hundreds).  Returns None when the key is abstract (initializer
    invoked inside a trace) — callers then use the traced jax.random
    path with the returned subkey.
    """
    sub = next_key()
    data = jax.random.key_data(sub)
    if isinstance(data, jax.core.Tracer):
        return None, sub
    bits = np.asarray(data).astype(np.uint64).ravel()
    return np.random.Generator(np.random.Philox(key=bits)), sub


def _randn(shape, compute):
    rng, sub = _host_rng()
    if rng is None:
        return jax.random.normal(sub, shape, compute)
    return jnp.asarray(rng.standard_normal(shape), compute)


def _randu(shape, compute, low, high):
    rng, sub = _host_rng()
    if rng is None:
        return jax.random.uniform(sub, shape, compute, low, high)
    return jnp.asarray(rng.uniform(low, high, shape), compute)


def _randtrunc(shape, compute, a, b):
    rng, sub = _host_rng()
    if rng is None:
        return jax.random.truncated_normal(sub, a, b, shape, compute)
    out = rng.standard_normal(shape)
    bad = (out < a) | (out > b)
    while bad.any():
        out[bad] = rng.standard_normal(int(bad.sum()))
        bad = (out < a) | (out > b)
    return jnp.asarray(out, compute)


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype):
        compute = jnp.float32 if dtype == jnp.bfloat16.dtype else dtype
        return (self.mean + self.std * _randn(shape, compute)).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def _generate(self, shape, dtype):
        compute = jnp.float32 if dtype == jnp.bfloat16.dtype else dtype
        z = _randtrunc(shape, compute, self.a, self.b)
        return (self.mean + self.std * z).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def _generate(self, shape, dtype):
        compute = jnp.float32 if dtype == jnp.bfloat16.dtype else dtype
        return _randu(shape, compute, self.low, self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        compute = jnp.float32 if dtype == jnp.bfloat16.dtype else dtype
        return (std * _randn(shape, compute)).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        compute = jnp.float32 if dtype == jnp.bfloat16.dtype else dtype
        return _randu(shape, compute, -limit, limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        compute = jnp.float32 if dtype == jnp.bfloat16.dtype else dtype
        return (std * _randn(shape, compute)).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        compute = jnp.float32 if dtype == jnp.bfloat16.dtype else dtype
        return _randu(shape, compute, -limit, limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def _generate(self, shape, dtype):
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        return jnp.asarray(np.asarray(v)).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def _generate(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        min_dim = min(oc // self.groups, ic)
        centers = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for d in range(min_dim):
                out[(g * (oc // self.groups) + d, d) + centers] = 1.0
        return jnp.asarray(out, dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def _generate(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = _randn((max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


# paddle.ParamAttr
class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


class Bilinear(Initializer):
    """Bilinear-interpolation kernel init for transposed-conv upsampling
    (reference fluid/initializer.py:1034 BilinearInitializer): every
    channel of a (C, 1|Cin, K, K) filter gets the same (K, K) separable
    triangle kernel, so a stride-f Conv2DTranspose performs bilinear
    x f upsampling."""

    def _generate(self, shape, dtype):
        if len(shape) != 4:
            raise ValueError("Bilinear expects a 4-D conv filter shape")
        k = shape[3]
        f = int(np.ceil(k / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        ax = np.arange(k)
        tri = (1 - np.abs(ax / f - c))
        kern = np.outer(tri, tri)
        out = np.zeros(shape, np.float64)
        out[...] = kern  # broadcast over the leading channel dims
        return jnp.asarray(out).astype(dtype)


# global default initializers (reference fluid/initializer.py:1346
# set_global_initializer): consulted by Layer.create_parameter when no
# per-param initializer was given
_global_weight_init = [None]
_global_bias_init = [None]


def set_global_initializer(weight_init, bias_init=None):
    for v, nm in ((weight_init, "weight_init"), (bias_init, "bias_init")):
        if v is not None and not isinstance(v, Initializer):
            raise TypeError(f"{nm} must be an Initializer or None, "
                            f"got {type(v)}")
    _global_weight_init[0] = weight_init
    _global_bias_init[0] = bias_init


def _global_default(is_bias):
    return _global_bias_init[0] if is_bias else _global_weight_init[0]
