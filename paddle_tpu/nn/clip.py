"""Gradient clipping. Reference: python/paddle/fluid/clip.py (paddle.nn.Clip*)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.engine import no_grad


class ClipGradBase:
    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _clip(self, params_grads):
        out = []
        with no_grad():
            for p, g in params_grads:
                if g is None:
                    out.append((p, g))
                    continue
                g._set_value(jnp.clip(g._value, self.min, self.max))
                out.append((p, g))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip(self, params_grads):
        out = []
        with no_grad():
            for p, g in params_grads:
                if g is None:
                    out.append((p, g))
                    continue
                norm = jnp.sqrt(jnp.sum(jnp.square(g._value)))
                scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
                g._set_value(g._value * scale)
                out.append((p, g))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def _clip(self, params_grads):
        with no_grad():
            grads = [g for p, g in params_grads
                     if g is not None and getattr(p, "need_clip", True)]
            if not grads:
                return params_grads
            gn = jnp.sqrt(sum(jnp.sum(jnp.square(g._value.astype(jnp.float32)))
                              for g in grads))
            scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
            for p, g in params_grads:
                if g is not None and getattr(p, "need_clip", True):
                    g._set_value((g._value.astype(jnp.float32) * scale).astype(
                        g._value.dtype))
        return params_grads


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    from paddle_tpu.core.tensor import Tensor
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return None
    with no_grad():
        if norm_type == float("inf"):
            total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value)) for g in grads]))
        else:
            total = jnp.sum(jnp.stack(
                [jnp.sum(jnp.abs(g._value) ** norm_type) for g in grads])) ** (
                1.0 / norm_type)
        scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
        for g in grads:
            g._set_value(g._value * scale)
    from paddle_tpu.core.tensor import Tensor as _T
    return _T(total)


def clip_grad_value_(parameters, clip_value):
    from paddle_tpu.core.tensor import Tensor
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    with no_grad():
        for p in parameters:
            if p.grad is not None:
                p.grad._set_value(jnp.clip(p.grad._value, -clip_value, clip_value))
