"""nn.utils. Reference: python/paddle/nn/utils/*."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.engine import no_grad
from paddle_tpu.core.tensor import Tensor


def parameters_to_vector(parameters, name=None):
    from paddle_tpu.tensor.manipulation import concat, reshape
    return concat([reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    with no_grad():
        for p in parameters:
            n = int(np.prod(p._value.shape))
            p._set_value(vec._value[offset:offset + n].reshape(p._value.shape))
            offset += n


class _WeightNorm:
    def __init__(self, name, dim):
        self.name = name
        self.dim = dim

    @staticmethod
    def apply(layer, name, dim):
        w = getattr(layer, name)
        wn = _WeightNorm(name, dim)
        dims = tuple(i for i in range(w._value.ndim) if i != (dim if dim is not None else 0))
        if dim is None:
            g0 = jnp.sqrt(jnp.sum(jnp.square(w._value)))
        else:
            g0 = jnp.sqrt(jnp.sum(jnp.square(w._value), axis=dims, keepdims=False))
        from paddle_tpu.core.tensor import Parameter
        layer.add_parameter(name + "_g", Parameter(g0))
        layer.add_parameter(name + "_v", Parameter(w._value))
        del layer._parameters[name]
        hook = layer.register_forward_pre_hook(
            lambda l, inp: wn._recompute(l) or None)
        layer.__dict__.setdefault("_weight_norm_hooks", {})[name] = (wn, hook)
        wn._recompute(layer)
        return wn

    def _recompute(self, layer):
        from paddle_tpu.core.dispatch import apply
        g = layer._parameters[self.name + "_g"]
        v = layer._parameters[self.name + "_v"]
        dim = self.dim

        def fn(gv, vv):
            if dim is None:
                norm = jnp.sqrt(jnp.sum(jnp.square(vv)))
                return vv * (gv / norm)
            dims = tuple(i for i in range(vv.ndim) if i != dim)
            norm = jnp.sqrt(jnp.sum(jnp.square(vv), axis=dims, keepdims=True))
            shape = [1] * vv.ndim
            shape[dim] = -1
            return vv / norm * gv.reshape(shape)
        w = apply(fn, g, v)
        object.__setattr__(layer, self.name, w)


def weight_norm(layer, name="weight", dim=0):
    _WeightNorm.apply(layer, name, dim)
    return layer


def remove_weight_norm(layer, name="weight"):
    hooks = layer.__dict__.get("_weight_norm_hooks", {})
    if name in hooks:
        wn, hook = hooks.pop(name)
        g = layer._parameters.pop(name + "_g")
        v = layer._parameters.pop(name + "_v")
        hook.remove()
        from paddle_tpu.core.tensor import Parameter
        dim = wn.dim
        if dim is None:
            norm = jnp.sqrt(jnp.sum(jnp.square(v._value)))
            w = v._value * (g._value / norm)
        else:
            dims = tuple(i for i in range(v._value.ndim) if i != dim)
            norm = jnp.sqrt(jnp.sum(jnp.square(v._value), axis=dims, keepdims=True))
            shape = [1] * v._value.ndim
            shape[dim] = -1
            w = v._value / norm * g._value.reshape(shape)
        if name in layer.__dict__:
            del layer.__dict__[name]
        layer.add_parameter(name, Parameter(w))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    """Wrap a layer's weight with spectral normalization (paddle.nn.utils)."""
    from paddle_tpu.nn.layer.norm import SpectralNorm as _SN
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = _SN(tuple(w._value.shape), dim=dim, power_iters=n_power_iterations,
             epsilon=eps)
    layer.add_sublayer(name + "_spectral_norm", sn)
    orig = layer._parameters[name]
    layer._parameters[name + "_orig"] = orig
    del layer._parameters[name]

    def pre_hook(l, inp):
        object.__setattr__(l, name, sn(l._parameters[name + "_orig"]))
    layer.register_forward_pre_hook(pre_hook)
    pre_hook(layer, None)
    return layer
