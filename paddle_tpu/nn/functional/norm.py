"""Normalization functionals. Reference: python/paddle/nn/functional/norm.py.

batch_norm follows paddle semantics: in training mode it normalizes with
batch statistics and updates running stats in-place (value rebind — captured
functionally under to_static); in eval mode it uses running stats.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.engine import no_grad


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(v):
        norm = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(norm, epsilon)
    return apply(fn, x)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    channel_axis = 1 if data_format.startswith("NC") else -1
    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        # compute batch stats and update running stats (paddle: r = m*r + (1-m)*b)
        def fn(v, rm, rv, w, b):
            axes = tuple(i for i in range(v.ndim) if i != channel_axis % v.ndim)
            # centered two-pass variance: E[(x-m)²], NOT E[x²]-E[x]² — the
            # one-pass form catastrophically cancels in fp32 when |mean| >>
            # std (e.g. un-centered raw features), and the corrupted var
            # would poison running_var for eval. fp32 accumulation
            # regardless of activation dtype; output keeps v.dtype.
            vf = v.astype(jnp.float32)
            mean = jnp.mean(vf, axis=axes)
            var = jnp.var(vf, axis=axes)
            shape = [1] * v.ndim
            shape[channel_axis % v.ndim] = -1
            # subtract the mean BEFORE scaling (fold only the affine into
            # the per-channel scale): vf*scale - mean*scale would cancel
            # catastrophically when |mean| >> std; (vf - mean) keeps the
            # bits and still fuses into one elementwise pass
            inv = jax.lax.rsqrt(var + epsilon)
            scale = inv if w is None else inv * w.astype(jnp.float32)
            out = (vf - mean.reshape(shape)) * scale.reshape(shape)
            if b is not None:
                out = out + b.astype(jnp.float32).reshape(shape)
            return out.astype(v.dtype), mean, var
        out, mean_t, var_t = apply(fn, x, running_mean, running_var, weight, bias)
        with no_grad():
            n = int(np.prod([s for i, s in enumerate(x.shape)
                             if i != channel_axis % x.ndim]))
            unbias = n / max(n - 1, 1)
            # update in fp32, then cast BACK to the buffer dtype — the fp32
            # stats must not silently promote bf16 (O2) running buffers
            rm_dt = running_mean._value.dtype
            rv_dt = running_var._value.dtype
            running_mean._set_value(
                (momentum * running_mean._value.astype(jnp.float32) +
                 (1 - momentum) * mean_t._value).astype(rm_dt))
            running_var._set_value(
                (momentum * running_var._value.astype(jnp.float32) +
                 (1 - momentum) * var_t._value * unbias).astype(rv_dt))
        return out

    def fn_eval(v, rm, rv, w, b):
        shape = [1] * v.ndim
        shape[channel_axis % v.ndim] = -1
        # per-channel scale computed on (C,) vectors in fp32 (stats/affine
        # may be bf16 under O2 decorate); mean subtracted before scaling
        # (see training path: the folded form cancels for |mean| >> std)
        inv = jax.lax.rsqrt(rv.astype(jnp.float32) + epsilon)
        scale = inv if w is None else inv * w.astype(jnp.float32)
        out = (v.astype(jnp.float32) - rm.astype(jnp.float32)
               .reshape(shape)) * scale.reshape(shape)
        if b is not None:
            out = out + b.astype(jnp.float32).reshape(shape)
        return out.astype(v.dtype)
    return apply(fn_eval, x, running_mean, running_var, weight, bias)


# opt-in global flag for the Pallas fused-norm paths off-TPU (CPU runs
# them in interpret mode — same numerics, and whole-program cost models
# see the fused call boundary instead of the op-by-op composition).
# On TPU the fused path is the default regardless.  Per-call `fused=`
# (and nn.LayerNorm(fused=...)) overrides in either direction.
_FUSED_NORM = [False]


def set_fused_norm(flag=True):
    """Globally enable/disable the Pallas fused LN/RMS-norm paths off
    TPU; returns the previous value (docs/performance_guide.md,
    "Cutting bytes/step")."""
    prev = _FUSED_NORM[0]
    _FUSED_NORM[0] = bool(flag)
    return prev


def fused_norm_enabled():
    return _FUSED_NORM[0]


def _use_fused(fused):
    if fused is not None:
        return bool(fused)
    if _FUSED_NORM[0]:
        return True
    try:
        from paddle_tpu.ops.pallas import on_tpu
        return on_tpu()
    except Exception:
        return False


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None, fused=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    nd = len(tuple(normalized_shape))

    if nd == 1 and _use_fused(fused):
        # last-axis layernorm: fused Pallas kernel (custom VJP whose
        # backward recomputes the stats; interpret mode off-TPU)
        try:
            from paddle_tpu.ops.pallas.norm import fused_layer_norm
            return apply(lambda v, w, b: fused_layer_norm(
                v, w, b, epsilon), x, weight, bias)
        except Exception:
            pass

    def fn(v, w, b):
        from paddle_tpu.amp.auto_cast import downcast_inputs
        from paddle_tpu.amp.policy import residency_dtype
        orig_dtype = v.dtype
        (v,) = downcast_inputs(v, opname="layer_norm")
        axes = tuple(range(v.ndim - nd, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) / jnp.sqrt(var + epsilon)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        # bf16 activation residency: the blacklist upcast computed the
        # norm in f32 for stability, but STORING the result f32 is what
        # shardlint SL303 flags — under a policy the output returns to
        # the residency-dtype stream
        if residency_dtype() is not None and out.dtype != orig_dtype:
            out = out.astype(orig_dtype)
        return out
    return apply(fn, x, weight, bias)


def fused_ln_residual(x, residual, weight=None, bias=None, epsilon=1e-5,
                      act=None, name=None, fused=None):
    """``h = x + residual; y = act(LN(h))`` in one pass, returning
    ``(h, y)`` — the residual-stream update and the next sublayer's
    normalized input.  On the fused path (Pallas kernel, interpret mode
    off-TPU) the custom VJP recomputes the normalized intermediate in
    backward instead of materializing it; the pure-JAX composition is
    the fallback (weight-free norms always use it).  ``act`` is None or
    ``"gelu"`` (tanh approximation)."""
    if _use_fused(fused) and weight is not None:
        try:
            from paddle_tpu.ops.pallas.norm import (
                fused_ln_residual as _pallas_ln_res)
            return apply(lambda a, r, w, b: _pallas_ln_res(
                a, r, w, b, epsilon, act), x, residual, weight, bias)
        except Exception:
            pass

    def fn(a, r, w, b):
        h = a + r
        hf = h.astype(jnp.float32)
        mean = jnp.mean(hf, axis=-1, keepdims=True)
        var = jnp.var(hf, axis=-1, keepdims=True)
        out = (hf - mean) / jnp.sqrt(var + epsilon)
        if w is not None:
            out = out * w.astype(jnp.float32)
        if b is not None:
            out = out + b.astype(jnp.float32)
        if act == "gelu":
            out = jax.nn.gelu(out, approximate=True)
        return h, out.astype(h.dtype)
    return apply(fn, x, residual, weight, bias)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    def fn(v, w, b):
        axes = tuple(range(2, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) / jnp.sqrt(var + eps)
        if w is not None:
            shape = [1, -1] + [1] * (v.ndim - 2)
            out = out * w.reshape(shape)
        if b is not None:
            shape = [1, -1] + [1] * (v.ndim - 2)
            out = out + b.reshape(shape)
        return out
    return apply(fn, x, weight, bias)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    def fn(v, w, b):
        cl = not data_format.startswith("NC")
        if cl:
            v = jnp.moveaxis(v, -1, 1)
        n, c = v.shape[:2]
        g = num_groups
        vv = v.reshape((n, g, c // g) + v.shape[2:])
        axes = tuple(range(2, vv.ndim))
        mean = jnp.mean(vv, axis=axes, keepdims=True)
        var = jnp.var(vv, axis=axes, keepdims=True)
        out = ((vv - mean) / jnp.sqrt(var + epsilon)).reshape(v.shape)
        shape = [1, -1] + [1] * (v.ndim - 2)
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        if cl:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply(fn, x, weight, bias)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def fn(v):
        cl = not data_format.startswith("NC")
        if cl:
            v = jnp.moveaxis(v, -1, 1)
        sq = jnp.square(v)
        c = v.shape[1]
        half = size // 2
        pad_lo, pad_hi = half, size - half - 1
        sqp = jnp.pad(sq, [(0, 0), (pad_lo, pad_hi)] + [(0, 0)] * (v.ndim - 2))
        acc = jnp.zeros_like(v)
        for i in range(size):
            acc = acc + sqp[:, i:i + c]
        out = v / (k + alpha * acc) ** beta
        if cl:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply(fn, x)


def rms_norm(x, weight=None, epsilon=1e-6, name=None, fused=None):
    """RMSNorm (TPU-friendly LLM building block; also via pallas kernel)."""
    if _use_fused(fused):
        try:
            from paddle_tpu.ops.pallas.norm import fused_rms_norm
            return apply(lambda v, w: fused_rms_norm(v, w, epsilon),
                         x, weight)
        except Exception:
            pass

    def fn(v, w):
        ms = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) / jnp.sqrt(ms + epsilon)).astype(v.dtype)
        if w is not None:
            out = out * w
        return out
    return apply(fn, x, weight)


def spectral_norm(weight, weight_u, weight_v, dim=0, power_iters=1, eps=1e-12,
                  name=None):
    def fn(w, u, v):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        for _ in range(power_iters):
            v = wm.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = wm @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ wm @ v
        return w / sigma, u, v
    out, u_new, v_new = apply(fn, weight, weight_u, weight_v)
    # persist the power iteration so u/v converge across steps
    with no_grad():
        weight_u._set_value(u_new._value)
        weight_v._set_value(v_new._value)
    return out
