"""Activation functions. Reference: python/paddle/nn/functional/activation.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply


def relu(x, name=None):
    return apply(jax.nn.relu, x)


def relu_(x, name=None):
    return x._inplace_assign(relu(x))


def relu6(x, name=None):
    return apply(lambda v: jnp.clip(v, 0.0, 6.0), x)


def elu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.elu(v, alpha), x)


def elu_(x, alpha=1.0, name=None):
    return x._inplace_assign(elu(x, alpha))


def celu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.celu(v, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), x)


def gelu(x, approximate=False, name=None):
    return apply(lambda v: jax.nn.gelu(v, approximate=approximate), x)


def sigmoid(x, name=None):
    return apply(jax.nn.sigmoid, x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda v: jnp.clip(slope * v + offset, 0.0, 1.0), x)


def hardswish(x, name=None):
    return apply(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda v: jnp.clip(v, min, max), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), x)


def softshrink(x, threshold=0.5, name=None):
    return apply(
        lambda v: jnp.where(v > threshold, v - threshold,
                            jnp.where(v < -threshold, v + threshold, 0.0)), x)


def tanhshrink(x, name=None):
    return apply(lambda v: v - jnp.tanh(v), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda v: jax.nn.leaky_relu(v, negative_slope), x)


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(v, w):
        if w.size == 1:
            return jnp.where(v >= 0, v, w.reshape(()) * v)
        shape = [1] * v.ndim
        ch_axis = 1 if data_format[1] == "C" else v.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(v >= 0, v, w.reshape(shape) * v)
    return apply(fn, x, weight)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    from paddle_tpu.framework.state import next_key
    def fn(v):
        if training:
            a = jax.random.uniform(next_key(), v.shape, jnp.float32, lower, upper).astype(v.dtype)
        else:
            a = (lower + upper) / 2.0
        return jnp.where(v >= 0, v, a * v)
    return apply(fn, x)


def log_sigmoid(x, name=None):
    return apply(jax.nn.log_sigmoid, x)


def maxout(x, groups, axis=1, name=None):
    def fn(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (groups, c // groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax)
    return apply(fn, x)


def softmax(x, axis=-1, dtype=None, name=None):
    from paddle_tpu.core.dtype import convert_dtype
    dt = convert_dtype(dtype)
    def fn(v):
        if dt is not None:
            # explicit dtype request wins over the amp black-list upcast
            v = v.astype(dt)
        else:
            from paddle_tpu.amp.auto_cast import downcast_inputs
            (v,) = downcast_inputs(v, opname="softmax")
        return jax.nn.softmax(v, axis=axis)
    return apply(fn, x)


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._inplace_assign(softmax(x, axis, dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    from paddle_tpu.core.dtype import convert_dtype
    dt = convert_dtype(dtype)
    def fn(v):
        if dt is not None:
            # explicit dtype request wins over the amp black-list upcast
            v = v.astype(dt)
        else:
            from paddle_tpu.amp.auto_cast import downcast_inputs
            (v,) = downcast_inputs(v, opname="log_softmax")
        return jax.nn.log_softmax(v, axis=axis)
    return apply(fn, x)


def softplus(x, beta=1, threshold=20, name=None):
    return apply(
        lambda v: jnp.where(beta * v > threshold, v,
                            jnp.log1p(jnp.exp(beta * jnp.minimum(v, threshold / beta))) / beta), x)


def softsign(x, name=None):
    return apply(jax.nn.soft_sign, x)


def swish(x, name=None):
    return apply(jax.nn.silu, x)


silu = swish


def mish(x, name=None):
    return apply(lambda v: v * jnp.tanh(jax.nn.softplus(v)), x)


def tanh(x, name=None):
    return apply(jnp.tanh, x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply(lambda v: jnp.where(v > threshold, v, value), x)


def glu(x, axis=-1, name=None):
    return apply(lambda v: jax.nn.glu(v, axis=axis), x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from paddle_tpu.framework.state import next_key

    def fn(v):
        g = jax.random.gumbel(next_key(), v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:  # straight-through estimator
            oh = jax.nn.one_hot(jnp.argmax(y, axis=axis), v.shape[axis],
                                axis=axis, dtype=y.dtype)
            y = y + jax.lax.stop_gradient(oh - y)
        return y
    return apply(fn, x)


def tanh_(x, name=None):
    """In-place tanh (reference activation.py tanh_)."""
    out = tanh(x)
    x._inplace_assign(out)
    return x
