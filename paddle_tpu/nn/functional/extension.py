"""Extension functionals. Reference: python/paddle/nn/functional/extension.py."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply, unwrap
from paddle_tpu.core.tensor import Tensor


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from paddle_tpu.core.dtype import convert_dtype
    import numpy as np
    ml = maxlen
    if ml is None:
        ml = int(np.asarray(unwrap(x)).max())
    elif isinstance(ml, Tensor):
        ml = int(ml._value)
    def fn(v):
        ar = jnp.arange(ml)
        return (ar < v[..., None]).astype(convert_dtype(dtype))
    return apply(fn, x)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def fn(v):
        cl = data_format == "NHWC"
        if cl:
            v = jnp.transpose(v, (0, 3, 1, 2))
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                                 v[:, :-1, fold:2 * fold]], axis=1)
        mid = v[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, mid], axis=2).reshape(nt, c, h, w)
        if cl:
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out
    return apply(fn, x)


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    from paddle_tpu.tensor.creation import diag_embed as de
    return de(input, offset, dim1, dim2)
