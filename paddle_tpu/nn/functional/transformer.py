"""Attention functionals.

Reference: python/paddle/nn/functional/ (scaled_dot_product_attention appears
in later paddle; incubate flash_attention). TPU-first: the hot path calls the
Pallas flash-attention kernel (paddle_tpu/ops/pallas/flash_attention.py) when
shapes allow; otherwise an XLA einsum softmax fallback (still MXU-bound).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.framework.state import next_key


def _sdpa_ref(q, k, v, mask, dropout_p, causal, scale, dropout_key=None):
    # q, k, v: [batch, seq, heads, head_dim] (paddle layout)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * jnp.asarray(s, q.dtype)
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(cm, logits, jnp.asarray(-1e30, logits.dtype))
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, jnp.asarray(-1e30, logits.dtype))
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_key is not None and dropout_p > 0.0:
        keep = jax.random.bernoulli(
            dropout_key, 1.0 - dropout_p, probs.shape).astype(probs.dtype)
        probs = probs * keep / (1.0 - dropout_p)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 scale=None, name=None):
    """query/key/value: [batch, seq, num_heads, head_dim] (paddle convention)."""
    apply_dropout = dropout_p > 0.0 and training
    use_flash = attn_mask is None and not apply_dropout
    if use_flash:
        try:
            from paddle_tpu.ops.pallas.flash_attention import flash_attention_bshd
            return apply(lambda q, k, v: flash_attention_bshd(q, k, v, causal=is_causal,
                                                              scale=scale),
                         query, key, value)
        except Exception:
            pass
    def fn(q, k, v, m):
        key_ = next_key() if apply_dropout else None
        return _sdpa_ref(q, k, v, m, dropout_p if apply_dropout else 0.0,
                         is_causal, scale, dropout_key=key_)
    return apply(fn, query, key, value, attn_mask)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention. Reference: nn/functional/sparse_attention.py.
    TPU note: implemented as dense attention with a sparsity mask built from
    the CSR pattern (XLA handles masked softmax efficiently); a pallas
    block-sparse kernel is the planned fast path."""
    def fn(q, k, v, offs, cols):
        b, h, ql, d = q.shape
        kl = k.shape[2]
        mask = jnp.zeros((b, h, ql, kl), bool)
        # CSR rows -> dense mask (static pattern assumed)
        import numpy as np
        offs_np = np.asarray(offs)
        cols_np = np.asarray(cols)
        m = np.zeros((b, h, ql, kl), dtype=bool)
        for bi in range(b):
            for hi in range(h):
                o = offs_np[bi, hi]
                c = cols_np[bi, hi]
                for r in range(ql):
                    m[bi, hi, r, c[o[r]:o[r + 1]]] = True
        mask = jnp.asarray(m)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(d, jnp.float32)).astype(q.dtype)
        logits = jnp.where(mask, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return apply(fn, query, key, value, sparse_csr_offset, sparse_csr_columns)
