"""Attention functionals.

Reference: python/paddle/nn/functional/ (scaled_dot_product_attention appears
in later paddle; incubate flash_attention). TPU-first: the hot path calls the
Pallas flash-attention kernel (paddle_tpu/ops/pallas/flash_attention.py) when
shapes allow; otherwise an XLA einsum softmax fallback (still MXU-bound).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.framework.state import next_key


def _sdpa_ref(q, k, v, mask, dropout_p, causal, scale, dropout_key=None):
    # q, k, v: [batch, seq, heads, head_dim] (paddle layout)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    # narrow (bf16/fp16) q/k: accumulate the score contraction WIDE
    # (numlint NL101) — the pre-fix chain (bf16-accumulated logits, one
    # rounding, then the softmax's f32 upcast) was also a double
    # rounding (NL102); f32 inputs take the identical old path
    narrow = q.dtype in (jnp.bfloat16, jnp.float16)
    pet = {"preferred_element_type": jnp.float32} if narrow else {}
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, **pet) \
        * jnp.asarray(s, jnp.float32 if narrow else q.dtype)
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(cm, logits, jnp.asarray(-1e30, logits.dtype))
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, jnp.asarray(-1e30, logits.dtype))
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_key is not None and dropout_p > 0.0:
        keep = jax.random.bernoulli(
            dropout_key, 1.0 - dropout_p, probs.shape).astype(probs.dtype)
        probs = probs * keep / (1.0 - dropout_p)
    # probs @ v contracts over the WHOLE key length — the deepest
    # reduction in the model; accumulate wide, round once at the output
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v, **pet).astype(q.dtype)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 scale=None, name=None):
    """query/key/value: [batch, seq, num_heads, head_dim] (paddle convention)."""
    apply_dropout = dropout_p > 0.0 and training
    use_flash = attn_mask is None and not apply_dropout
    if use_flash:
        try:
            from paddle_tpu.ops.pallas.flash_attention import flash_attention_bshd
            return apply(lambda q, k, v: flash_attention_bshd(q, k, v, causal=is_causal,
                                                              scale=scale),
                         query, key, value)
        except Exception:
            pass
    def fn(q, k, v, m):
        key_ = next_key() if apply_dropout else None
        return _sdpa_ref(q, k, v, m, dropout_p if apply_dropout else 0.0,
                         is_causal, scale, dropout_key=key_)
    return apply(fn, query, key, value, attn_mask)


_block_mask_cache = {}          # digest key -> (block_mask, block) | None
_BLOCK_MASK_CACHE_CAP = 64
_pattern_identity_memo = {}     # (id(offs), id(cols), ql, kl) -> digest key
_PATTERN_MEMO_CAP = 256


def _cache_put(cache, cap, key, value):
    if len(cache) >= cap:
        cache.pop(next(iter(cache)))   # FIFO eviction
    cache[key] = value


def _to_np(x):
    import numpy as np
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


def _csr_shared_mask(offs_np, cols_np, ql, kl):
    """The single [ql, kl] token mask all (b, h) share, or None. Built
    ONCE per pattern (the per-block-size alignment checks below reuse
    it)."""
    import numpy as np
    b, h = offs_np.shape[:2]
    base = None
    for bi in range(b):
        for hi in range(h):
            m = np.zeros((ql, kl), bool)
            o, c = offs_np[bi, hi], cols_np[bi, hi]
            for r in range(ql):
                m[r, c[o[r]:o[r + 1]]] = True
            if base is None:
                base = m
            elif not np.array_equal(base, m):
                return None
    return base


def _mask_block_aligned(base, ql, kl, block):
    """[nq, nk] block mask if `base` is exactly block-aligned, else None."""
    import numpy as np
    if ql % block or kl % block:
        return None
    blocks = base.reshape(ql // block, block, kl // block, block)
    frac = blocks.mean(axis=(1, 3))
    if not np.all((frac == 0.0) | (frac == 1.0)):
        return None
    return frac.astype(bool)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention. Reference: nn/functional/sparse_attention.py.

    TPU note: when the CSR pattern is shared across (batch, head) and
    exactly block-aligned (the practical patterns — sliding window,
    global tokens, blocked causal), this routes to the Pallas
    block-sparse flash kernel
    (ops/pallas/block_sparse_attention.py): work and K/V DMA scale with
    the ACTIVE block count, not seq². Other patterns fall back to dense
    attention with the CSR mask (XLA fuses the masked softmax)."""
    hit = None
    if key_padding_mask is None and attn_mask is None:
        import hashlib

        import numpy as np
        try:
            # host-side pattern analysis only — a failure here (traced
            # offsets, exotic inputs) falls back to dense; a failure in
            # the KERNEL below must surface, not be swallowed
            ql = query.shape[2]
            kl = key.shape[2]
            # serving loops pass the SAME offset/column objects each
            # step: an identity memo skips the device->host copy + hash
            # on the hot path
            import weakref
            ident = (id(sparse_csr_offset), id(sparse_csr_columns),
                     ql, kl)
            def _ver(t):
                return getattr(t, "_version", None)

            memo = _pattern_identity_memo.get(ident)
            key_ = None
            if memo is not None:
                # id() can be reused after GC, and in-place mutation
                # (set_value/__setitem__) keeps id but bumps _version:
                # the memo only counts for the same LIVE objects at the
                # same versions
                k, r1, r2, v1, v2 = memo
                if r1() is sparse_csr_offset and \
                        r2() is sparse_csr_columns and \
                        v1 == _ver(sparse_csr_offset) and \
                        v2 == _ver(sparse_csr_columns):
                    key_ = k
            if key_ is None:
                offs_np = _to_np(sparse_csr_offset)
                cols_np = _to_np(sparse_csr_columns)
                dig = hashlib.sha256()
                dig.update(offs_np.tobytes())
                dig.update(cols_np.tobytes())
                key_ = (dig.hexdigest(), ql, kl)
                try:
                    _cache_put(
                        _pattern_identity_memo, _PATTERN_MEMO_CAP, ident,
                        (key_, weakref.ref(sparse_csr_offset),
                         weakref.ref(sparse_csr_columns),
                         _ver(sparse_csr_offset),
                         _ver(sparse_csr_columns)))
                except TypeError:
                    pass  # plain ndarrays/lists may not be weakref-able
            else:
                offs_np = cols_np = None
            if key_ in _block_mask_cache:
                hit = _block_mask_cache[key_]
            else:
                if offs_np is None:
                    offs_np = _to_np(sparse_csr_offset)
                    cols_np = _to_np(sparse_csr_columns)
                hit = None
                base = _csr_shared_mask(offs_np, cols_np, ql, kl)
                if base is not None:
                    for block in (512, 256, 128, 64):
                        bm = _mask_block_aligned(base, ql, kl, block)
                        if bm is not None and bm.any():
                            # all-empty patterns stay on the dense path
                            # (defined zero output, no kernel tables)
                            hit = (bm, block)
                            break
                _cache_put(_block_mask_cache, _BLOCK_MASK_CACHE_CAP,
                           key_, hit)
        except Exception:
            hit = None
    if hit is not None:
        bm, block = hit
        from paddle_tpu.ops.pallas.block_sparse_attention import (
            block_sparse_attention,
        )
        return apply(
            lambda q, k, v: block_sparse_attention(
                q, k, v, bm, block_q=block, block_k=block),
            query, key, value)

    def fn(q, k, v, offs, cols):
        b, h, ql, d = q.shape
        kl = k.shape[2]
        # CSR rows -> dense mask (static pattern assumed)
        import numpy as np
        offs_np = np.asarray(offs)
        cols_np = np.asarray(cols)
        m = np.zeros((b, h, ql, kl), dtype=bool)
        for bi in range(b):
            for hi in range(h):
                o = offs_np[bi, hi]
                c = cols_np[bi, hi]
                for r in range(ql):
                    m[bi, hi, r, c[o[r]:o[r + 1]]] = True
        mask = jnp.asarray(m)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(d, jnp.float32)).astype(q.dtype)
        logits = jnp.where(mask, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        # a row with NO stored entries attends nothing: zero output (the
        # softmax over the all -1e30 row would fabricate a uniform
        # average of V) — same convention as the block-sparse kernel and
        # sparse.nn.functional.attention
        p = jnp.where(mask.any(-1, keepdims=True), p, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return apply(fn, query, key, value, sparse_csr_offset, sparse_csr_columns)
