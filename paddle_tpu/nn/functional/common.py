"""Common NN functionals. Reference: python/paddle/nn/functional/common.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply
from paddle_tpu.framework.state import next_key


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with W: [in, out] (paddle layout -> MXU matmul)."""
    def fn(v, w, b):
        from paddle_tpu.amp.auto_cast import downcast_inputs
        v2, w2 = downcast_inputs(v, w, opname="linear")
        if _is_master_downcast(v2, w2, w):
            # master-weight mixed precision (the amp-policy flagship):
            # grads for w/b accumulate WIDE and land f32 directly
            if b is not None:
                return _linear_master(v2, w, b)
            return _mm_master(False, v2, w)
        y = jnp.matmul(v2, w2)
        if b is not None:
            y = y + b.astype(y.dtype)
        return y
    return apply(fn, x, weight, bias)


def _is_master_downcast(a2, w2, w):
    """True when `downcast_inputs` narrowed an f32 master weight for a
    narrow-float matmul — the ONE predicate gating the wide-grad
    custom_vjp path for F.linear and paddle.matmul/mm.  Requires a
    genuine DOWNcast (a black-list upcast of a narrow-stored weight
    must keep stock AD so grad dtype == param dtype) AND a matching
    narrow-float lhs (an integer/other lhs must keep jnp.matmul's
    stock promotion — the master path would truncate the weights to
    the lhs dtype)."""
    return (w2.dtype != w.dtype
            and w2.dtype in (jnp.bfloat16, jnp.float16)
            and a2.dtype == w2.dtype
            and w.ndim == 2 and a2.ndim >= 2)


# ---- wide-accumulating gradients for master-weight matmul/bias ----
# numlint NL101 (the flagship self-audit's finding at this site): under
# bf16 activation residency the weight- and bias-grad reductions
# contract over EVERY token in the batch — a bf16 serial sum whose
# running total absorbs small addends once it is ~256x larger than
# them, silently corrupting exactly the grads that feed the f32 master
# weights step after step.  The fix moves the master downcast INSIDE a
# custom_vjp: the forward math is unchanged eqn-for-eqn (cast, matmul,
# bias add — same values as before), but the backward contracts dw/db
# with an f32 accumulator (preferred_element_type, the MXU's native
# wide accumulation) and hands them to the f32 masters WITHOUT ever
# rounding through bf16 — strictly better than the pre-fix chain
# (bf16-serial sum, then an upcast of the already-rounded result).
# The activation cotangent da stays the stock narrow dot: it lives for
# one backward step in residency dtype by design, matching the forward
# (docs/numlint.md documents this split and the baseline entries for
# the forward dots).  The f32 path never enters these wrappers: its
# jaxpr is byte-identical to before.

@jax.custom_vjp
def _linear_master(a, w, b):
    wc = w.astype(a.dtype)
    return jnp.matmul(a, wc) + b.astype(a.dtype)


def _linear_master_fwd(a, w, b):
    wc = w.astype(a.dtype)
    return jnp.matmul(a, wc) + b.astype(a.dtype), (a, wc)


def _linear_master_bwd(res, g):
    a, wc = res
    lead = tuple(range(a.ndim - 1))
    da = jax.lax.dot_general(
        g, wc, (((g.ndim - 1,), (1,)), ((), ())))
    dw = jax.lax.dot_general(
        a, g, ((lead, lead), ((), ())),
        preferred_element_type=jnp.float32)
    # db via a ones-dot: wide accumulation over the lead dims without
    # materializing an f32 copy of the cotangent
    ones = jnp.ones(g.shape[:-1], g.dtype)
    db = jax.lax.dot_general(
        ones, g, ((lead, lead), ((), ())),
        preferred_element_type=jnp.float32)
    return da.astype(a.dtype), dw, db


_linear_master.defvjp(_linear_master_fwd, _linear_master_bwd)


from functools import partial as _partial  # noqa: E402


@_partial(jax.custom_vjp, nondiff_argnums=(0,))
def _mm_master(trans_y, a, w):
    wc = w.astype(a.dtype)
    return jnp.matmul(a, jnp.swapaxes(wc, -1, -2) if trans_y else wc)


def _mm_master_fwd(trans_y, a, w):
    wc = w.astype(a.dtype)
    y = jnp.matmul(a, jnp.swapaxes(wc, -1, -2) if trans_y else wc)
    return y, (a, wc)


def _mm_master_bwd(trans_y, res, g):
    a, wc = res
    lead = tuple(range(a.ndim - 1))
    if trans_y:
        # y = a @ w^T with w: [n, k]
        da = jax.lax.dot_general(
            g, wc, (((g.ndim - 1,), (0,)), ((), ())))
        dw = jax.lax.dot_general(
            g, a, ((lead, lead), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        da = jax.lax.dot_general(
            g, wc, (((g.ndim - 1,), (1,)), ((), ())))
        dw = jax.lax.dot_general(
            a, g, ((lead, lead), ((), ())),
            preferred_element_type=jnp.float32)
    return da.astype(a.dtype), dw


_mm_master.defvjp(_mm_master_fwd, _mm_master_bwd)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if mode == "upscale_in_train" else apply(lambda v: v * (1.0 - p), x)
    def fn(v):
        if axis is None:
            shape = v.shape
        else:
            axes = (axis,) if isinstance(axis, int) else tuple(axis)
            shape = tuple(v.shape[i] if i in axes else 1 for i in range(v.ndim))
        keep = jax.random.bernoulli(next_key(), 1.0 - p, shape).astype(v.dtype)
        if mode == "upscale_in_train":
            return v * keep / (1.0 - p)
        return v * keep
    return apply(fn, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    a = ((1 - p) * (1 + p * alpha_p ** 2)) ** -0.5
    b = -a * alpha_p * p
    def fn(v):
        keep = jax.random.bernoulli(next_key(), 1.0 - p, v.shape)
        return a * jnp.where(keep, v, alpha_p) + b
    return apply(fn, x)


def _pad_nd(v, pad, mode, value, data_format):
    nd = v.ndim
    if len(pad) == 2 * nd:
        # paddle "all-dims" format: [(before,after) per dim] flattened, dim0 first
        widths = [(int(pad[2 * i]), int(pad[2 * i + 1])) for i in range(nd)]
    else:
        # spatial-only pairs, reversed (last spatial dim first), like torch
        n_spatial = len(pad) // 2
        widths = [(0, 0)] * nd
        if data_format.startswith("NC"):
            spatial = list(range(2, nd))
        else:
            spatial = list(range(1, nd - 1))
        for i in range(n_spatial):
            d = spatial[len(spatial) - 1 - i]
            widths[d] = (int(pad[2 * i]), int(pad[2 * i + 1]))
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(v, widths, mode="constant", constant_values=value)
    return jnp.pad(v, widths, mode=jmode)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from paddle_tpu.core.tensor import Tensor
    if isinstance(pad, Tensor):
        pad = [int(p) for p in np.asarray(pad._value).reshape(-1)]
    pad = [int(p) for p in pad]
    return apply(lambda v: _pad_nd(v, pad, mode, value, data_format), x)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply(fn, x1, x2)


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, bi):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bi is not None:
            out = out + bi
        return out
    return apply(fn, x1, x2, weight, bias)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(lab, prior):
        k = lab.shape[-1]
        if prior is None:
            return (1.0 - epsilon) * lab + epsilon / k
        return (1.0 - epsilon) * lab + epsilon * prior
    return apply(fn, label, prior_dist)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = (kernel_sizes,) * 2 if isinstance(kernel_sizes, int) else tuple(kernel_sizes)
    st = (strides,) * 2 if isinstance(strides, int) else tuple(strides)
    dl = (dilations,) * 2 if isinstance(dilations, int) else tuple(dilations)
    pd = (paddings,) * 4 if isinstance(paddings, int) else tuple(paddings)
    if len(pd) == 2:
        pd = (pd[0], pd[0], pd[1], pd[1])

    def fn(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])])
        oh = (v.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (v.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                sl = v[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                       j * dl[1]: j * dl[1] + ow * st[1]: st[1]]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # [N, C, kh*kw, oh, ow]
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)
    return apply(fn, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    os_ = (output_sizes,) * 2 if isinstance(output_sizes, int) else tuple(output_sizes)
    ks = (kernel_sizes,) * 2 if isinstance(kernel_sizes, int) else tuple(kernel_sizes)
    st = (strides,) * 2 if isinstance(strides, int) else tuple(strides)
    dl = (dilations,) * 2 if isinstance(dilations, int) else tuple(dilations)
    pd = (paddings,) * 4 if isinstance(paddings, int) else tuple(paddings)
    if len(pd) == 2:
        pd = (pd[0], pd[0], pd[1], pd[1])

    def fn(v):
        n, ckk, L = v.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = os_[0] + pd[0] + pd[2], os_[1] + pd[1] + pd[3]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        v = v.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), v.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                             j * dl[1]: j * dl[1] + ow * st[1]: st[1]].add(v[:, :, i, j])
        return out[:, :, pd[0]: ph - pd[2], pd[1]: pw - pd[3]]
    return apply(fn, x)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    from paddle_tpu.core.tensor import Tensor
    if isinstance(size, Tensor):
        size = [int(s) for s in np.asarray(size._value)]
    elif size is not None and not isinstance(size, (list, tuple)):
        size = [int(size)]
    if isinstance(scale_factor, Tensor):
        scale_factor = [float(s) for s in np.asarray(scale_factor._value).reshape(-1)]

    def fn(v):
        chan_last = not data_format.startswith("NC")
        nd = v.ndim - 2
        spatial = v.shape[1:-1] if chan_last else v.shape[2:]
        if size is not None:
            out_spatial = tuple(int(s) for s in size)
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * nd
            out_spatial = tuple(int(np.floor(s * f)) for s, f in zip(spatial, sf))
        jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
                 "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode.lower()]
        if chan_last:
            out_shape = (v.shape[0],) + out_spatial + (v.shape[-1],)
            axes = tuple(range(1, 1 + nd))
        else:
            out_shape = v.shape[:2] + out_spatial
            axes = tuple(range(2, 2 + nd))
        if jmode == "nearest":
            # paddle nearest (align_corners=False): floor(i * scale)
            idx = []
            for a, (si, so) in zip(axes, zip(spatial, out_spatial)):
                scale = si / so
                ind = jnp.floor(jnp.arange(so) * scale).astype(jnp.int32)
                idx.append((a, jnp.clip(ind, 0, si - 1)))
            out = v
            for a, ind in idx:
                out = jnp.take(out, ind, axis=a)
            return out
        if mode.lower() in ("bilinear", "linear", "trilinear", "bicubic") and align_corners:
            # jax.image.resize has no align_corners; emulate via coordinate map
            out = v
            for a, (si, so) in zip(axes, zip(spatial, out_spatial)):
                pos = jnp.linspace(0.0, si - 1.0, so)
                lo = jnp.floor(pos).astype(jnp.int32)
                hi = jnp.clip(lo + 1, 0, si - 1)
                wgt = (pos - lo).astype(v.dtype)
                shape = [1] * out.ndim
                shape[a] = so
                wgt = wgt.reshape(shape)
                out = jnp.take(out, lo, axis=a) * (1 - wgt) + jnp.take(out, hi, axis=a) * wgt
            return out
        return jax.image.resize(v, out_shape, method=jmode)
    return apply(fn, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def class_center_sample(label, num_classes, num_samples, group=None,
                        rank=None, nranks=None, seed=None):
    """PartialFC class-center sampling (reference nn/functional/common.py
    class_center_sample; phi kernel
    paddle/phi/kernels/cpu/class_center_sample_kernel.cc): keep every
    positive class center (sorted ascending), top up with uniformly
    sampled negative centers until ``max(num_samples, num_positives)``,
    and remap labels to indices into the sampled list.

    This is host-side label preparation (data-dependent output size), so
    it runs in numpy — the TPU work is the subsequent margin loss over the
    sampled centers, which stays static-shaped at ``num_samples``.

    Model parallel (class-sharded fc over the tp axis): pass
    ``rank``/``nranks`` (or a group object carrying them). ``num_classes``
    is the LOCAL class count of every shard; labels are GLOBAL. Each
    rank's sample is computed deterministically from the shared seed, so
    the remapped labels index the CONCATENATED per-rank sampled space —
    the layout vocab-sharded weights use. Returns this rank's
    (remapped_label, sampled_local_class_center).
    """
    from paddle_tpu.core.tensor import Tensor

    if num_samples > num_classes:
        # same contract as the phi kernel's PADDLE_ENFORCE_LE — without it
        # the negative-sampling loop below could never terminate
        raise ValueError(
            f"num_samples ({num_samples}) must be <= num_classes "
            f"({num_classes})")
    y = np.asarray(label.numpy() if isinstance(label, Tensor) else label)
    y = y.reshape(-1).astype(np.int64)
    if group is False:
        nranks, rank = 1, 0
    if nranks is None:
        nranks = getattr(group, "nranks", 1) if group is not None else 1
    if rank is None:
        rank = getattr(group, "rank", 0) if group is not None else 0
    if seed is None:
        from paddle_tpu.framework.state import _rng
        seed = _rng.seed_val

    total_classes = nranks * num_classes
    if y.size and (y.min() < 0 or y.max() >= total_classes):
        raise ValueError(
            f"class_center_sample: labels must lie in [0, "
            f"{total_classes}) (nranks*num_classes); got range "
            f"[{int(y.min())}, {int(y.max())}]")
    sampled_per_rank = []
    remap_base = {}
    base = 0
    for r in range(nranks):
        lo, hi = r * num_classes, (r + 1) * num_classes
        pos = np.unique(y[(y >= lo) & (y < hi)]) - lo       # local ids, sorted
        rng = np.random.default_rng(np.uint64(seed) + np.uint64(r) * 7919)
        chosen = set(pos.tolist())
        sampled = list(pos)
        while len(chosen) < num_samples:
            neg = int(rng.integers(0, num_classes))
            if neg not in chosen:
                chosen.add(neg)
                sampled.append(neg)                          # negatives unordered
        for local_idx, cls in enumerate(sampled):
            remap_base[cls + lo] = base + local_idx
        sampled_per_rank.append(np.asarray(sampled, dtype=np.int64))
        base += len(sampled)

    remapped = np.asarray([remap_base[int(v)] for v in y], dtype=np.int64)
    return (Tensor(jnp.asarray(remapped)),
            Tensor(jnp.asarray(sampled_per_rank[rank])))
