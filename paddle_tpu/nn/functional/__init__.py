"""nn.functional namespace. Reference: python/paddle/nn/functional/__init__.py."""
from paddle_tpu.nn.functional.activation import *  # noqa: F401,F403
from paddle_tpu.nn.functional.common import *  # noqa: F401,F403
from paddle_tpu.nn.functional.conv import (  # noqa: F401
    conv1d,
    conv1d_transpose,
    conv2d,
    conv2d_transpose,
    conv3d,
    conv3d_transpose,
)
from paddle_tpu.nn.functional.distance import cdist, pairwise_distance, pdist  # noqa: F401
from paddle_tpu.nn.functional.extension import (  # noqa: F401
    diag_embed,
    sequence_mask,
    temporal_shift,
)
from paddle_tpu.nn.functional.input import embedding, gather_tree, one_hot  # noqa: F401
from paddle_tpu.nn.functional.loss import *  # noqa: F401,F403
from paddle_tpu.nn.functional.norm import (  # noqa: F401
    batch_norm,
    fused_ln_residual,
    fused_norm_enabled,
    group_norm,
    instance_norm,
    layer_norm,
    local_response_norm,
    normalize,
    rms_norm,
    set_fused_norm,
    spectral_norm,
)
from paddle_tpu.nn.functional.pooling import *  # noqa: F401,F403
from paddle_tpu.nn.functional.transformer import (  # noqa: F401
    scaled_dot_product_attention,
    sparse_attention,
)
from paddle_tpu.nn.functional.vision import (  # noqa: F401
    affine_grid,
    channel_shuffle,
    grid_sample,
    pixel_shuffle,
    pixel_unshuffle,
)
