"""Loss functionals. Reference: python/paddle/nn/functional/loss.py."""
from __future__ import annotations

import functools
import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    def fn(logits, lab, w):
        from paddle_tpu.amp.auto_cast import downcast_inputs
        (logits,) = downcast_inputs(logits, opname="cross_entropy")
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(
            jnp.maximum(logits, 1e-30))
        c = logits.shape[axis]
        if soft_label or (lab.ndim == logits.ndim and lab.shape == logits.shape):
            tgt = lab
            if label_smoothing > 0:
                tgt = (1 - label_smoothing) * tgt + label_smoothing / c
            per = -jnp.sum(tgt * logp, axis=axis)
            return _reduce(per, reduction)
        lab_int = lab
        if lab_int.ndim == logits.ndim:  # trailing 1 dim
            lab_int = jnp.squeeze(lab_int, axis=axis)
        valid = lab_int != ignore_index
        safe = jnp.where(valid, lab_int, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, axis).astype(jnp.int32), axis=axis)
        per = -jnp.squeeze(picked, axis=axis)
        if label_smoothing > 0:
            smooth = -jnp.mean(logp, axis=axis)
            per = (1 - label_smoothing) * per + label_smoothing * smooth
        if w is not None:
            per = per * jnp.take(w, safe)
        per = jnp.where(valid, per, 0.0)
        if reduction == "mean":
            if w is not None:
                denom = jnp.sum(jnp.where(valid, jnp.take(w, safe), 0.0))
            else:
                denom = jnp.sum(valid.astype(per.dtype))
            return jnp.sum(per) / jnp.maximum(denom, 1e-12)
        if reduction == "sum":
            return jnp.sum(per)
        return per
    return apply(fn, input, label, weight)


softmax_with_cross_entropy = None  # defined below


def _softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                                numeric_stable_mode=True, return_softmax=False,
                                axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    from paddle_tpu.nn.functional.activation import softmax as _softmax
    from paddle_tpu.tensor.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


softmax_with_cross_entropy = _softmax_with_cross_entropy


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def fn(logp, lab, w):
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        if logp.ndim > 2:  # [N, C, d1...] -> [N, d1..., C]
            logp2 = jnp.moveaxis(logp, 1, -1)
        else:
            logp2 = logp
        picked = jnp.take_along_axis(logp2, safe[..., None].astype(jnp.int32), axis=-1)[..., 0]
        per = -picked
        if w is not None:
            per = per * jnp.take(w, safe)
        per = jnp.where(valid, per, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.where(valid, jnp.take(w, safe) if w is not None
                                      else jnp.ones_like(per), 0.0))
            return jnp.sum(per) / jnp.maximum(denom, 1e-12)
        if reduction == "sum":
            return jnp.sum(per)
        return per
    return apply(fn, input, label, weight)


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction), input, label)


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        per = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # paddle smooth_l1_loss uses huber with delta scaling
        return _reduce(per * delta, reduction)
    return apply(fn, input, label)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        per = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(per, reduction)
    return apply(fn, input, label)


def bce_loss(input, label, weight=None, reduction="mean", name=None):
    def fn(p, y, w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-7)
        per = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        if w is not None:
            per = per * w
        return _reduce(per, reduction)
    return apply(fn, input, label, weight)


binary_cross_entropy = bce_loss


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def fn(z, y, w, pw):
        log_sig = jax.nn.log_sigmoid(z)
        log_sig_neg = jax.nn.log_sigmoid(-z)
        if pw is not None:
            per = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        else:
            per = -(y * log_sig + (1 - y) * log_sig_neg)
        if w is not None:
            per = per * w
        return _reduce(per, reduction)
    return apply(fn, logit, label, weight, pos_weight)


def kl_div(input, label, reduction="mean", name=None):
    def fn(logp, y):
        per = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(per) / logp.shape[0]
        return _reduce(per, reduction)
    return apply(fn, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, y):
        per = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(per, reduction)
    return apply(fn, input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def fn(a, y):
        per = jnp.where(y == 1.0, a, jnp.maximum(0.0, margin - a))
        return _reduce(per, reduction)
    return apply(fn, input, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        per = jnp.where(y == 1, 1.0 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(per, reduction)
    return apply(fn, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p, axis=-1) ** (1.0 / p)
        dp = dist(a, pos)
        dn = dist(a, neg)
        if swap:
            dn = jnp.minimum(dn, dist(pos, neg))
        per = jnp.maximum(dp - dn + margin, 0.0)
        return _reduce(per, reduction)
    return apply(fn, input, positive, negative)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        from paddle_tpu.tensor.math import minimum
        dn = minimum(dn, distance_function(positive, negative))
    return apply(lambda a, b: _reduce(jnp.maximum(a - b + margin, 0.0), reduction), dp, dn)


def soft_margin_loss(input, label, reduction="mean", name=None):
    def fn(a, y):
        per = jnp.log1p(jnp.exp(-y * a))
        return _reduce(per, reduction)
    return apply(fn, input, label)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    def fn(a, y, w):
        per = -(y * jax.nn.log_sigmoid(a) + (1 - y) * jax.nn.log_sigmoid(-a))
        if w is not None:
            per = per * w
        per = jnp.mean(per, axis=-1)
        return _reduce(per, reduction)
    return apply(fn, input, label, weight)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def fn(a, y):
        if log_input:
            per = jnp.exp(a) - y * a
        else:
            per = a - y * jnp.log(a + epsilon)
        if full:
            stirling = y * jnp.log(y) - y + 0.5 * jnp.log(2 * jnp.pi * y)
            per = per + jnp.where(y > 1, stirling, 0.0)
        return _reduce(per, reduction)
    return apply(fn, input, label)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def fn(mu, y, var):
        var = jnp.maximum(var, epsilon)
        per = 0.5 * (jnp.log(var) + jnp.square(y - mu) / var)
        if full:
            per = per + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(per, reduction)
    return apply(fn, input, label, variance)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard log-alpha forward recursion as a `lax.scan`
    (TPU-friendly: static shapes, no host loop).
    Reference: paddle warpctc op (paddle/fluid/operators/warpctc_op.*)."""
    def fn(lp, lab, in_len, lab_len):
        # lp: [T, N, C] logits (paddle passes logits; take log_softmax)
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, N, C = lp.shape
        S = lab.shape[1]
        ext = 2 * S + 1
        # extended label seq: blank l1 blank l2 ... blank
        ext_labels = jnp.full((N, ext), blank, dtype=lab.dtype)
        ext_labels = ext_labels.at[:, 1::2].set(lab)
        neg_inf = jnp.asarray(-1e30, lp.dtype)
        alpha0 = jnp.full((N, ext), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lab = jnp.take_along_axis(lp[0], ext_labels[:, 1:2].astype(jnp.int32), axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(first_lab)

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((N, 2), dtype=bool),
             ext_labels[:, 2:] == ext_labels[:, :-2]], axis=1)
        is_blank = ext_labels == blank

        def step(alpha, t):
            lp_t = lp[t]
            emit = jnp.take_along_axis(lp_t, ext_labels.astype(jnp.int32), axis=1)
            a_prev = alpha
            a_shift1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            allow_skip = (~is_blank) & (~same_as_prev2)
            cand = jnp.logaddexp(a_prev, a_shift1)
            cand = jnp.where(allow_skip, jnp.logaddexp(cand, a_shift2), cand)
            new_alpha = cand + emit
            # mask steps beyond input length: keep old alpha
            active = (t < in_len)[:, None]
            return jnp.where(active, new_alpha, alpha), None

        alpha_T, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        end_idx = (2 * lab_len).astype(jnp.int32)
        a_last = jnp.take_along_axis(alpha_T, end_idx[:, None], axis=1)[:, 0]
        a_prev_last = jnp.take_along_axis(
            alpha_T, jnp.maximum(end_idx - 1, 0)[:, None], axis=1)[:, 0]
        ll = jnp.logaddexp(a_last, a_prev_last)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1.0))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply(fn, log_probs, labels, input_lengths, label_lengths)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, y, norm):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        pt = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        per = a_t * ((1 - pt) ** gamma) * ce
        if norm is not None:
            per = per / norm
        return _reduce(per, reduction)
    return apply(fn, logit, label, normalizer)


def dice_loss(input, label, epsilon=1e-5, name=None):
    def fn(p, y):
        y1 = jax.nn.one_hot(y[..., 0], p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y1, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(y1, axis=red)
        return jnp.mean(1.0 - (2 * inter + epsilon) / (union + epsilon))
    return apply(fn, input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    def fn(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return apply(fn, input, label)


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def fn(a, p, y):
        sim = a @ p.T
        n = a.shape[0]
        tgt = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1)) + jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return ce + reg
    return apply(fn, anchor, positive, labels)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Multi-class margin (hinge) loss (reference nn/functional/loss.py
    multi_margin_loss): mean over classes of
    max(0, margin - x_label + x_j)^p for j != label."""

    def fn(x, y, *rest):
        n, c = x.shape
        yi = y.reshape(-1).astype(jnp.int32)
        x_label = jnp.take_along_axis(x, yi[:, None], axis=1)
        m = jnp.maximum(0.0, margin - x_label + x)
        if p != 1:
            m = m ** p
        if rest:
            m = m * rest[0][None, yi].reshape(n, 1) if rest[0].ndim == 1 \
                else m * rest[0]
        mask = 1.0 - jax.nn.one_hot(yi, c, dtype=x.dtype)
        loss = (m * mask).sum(axis=1) / c
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply(fn, *args)


_hsigmoid_path_cache = {}


def _default_hsigmoid_paths(n_cls):
    if n_cls not in _hsigmoid_path_cache:
        depth = int(np.ceil(np.log2(max(n_cls, 2))))
        tables, codes = [], []
        for lab in range(n_cls):
            node = lab + n_cls  # leaf position in the heap
            tab, code = [], []
            while node > 1:
                code.append(node & 1)
                node //= 2
                tab.append(node - 1)  # non-leaf ids 0-based
            tab = tab[::-1]
            code = code[::-1]
            pad = depth + 1 - len(tab)
            tables.append(tab + [-1] * pad)
            codes.append(code + [-1] * pad)
        # cache DEVICE arrays: re-uploading [num_classes, depth+1]
        # tables every step would defeat the cache at hsigmoid's
        # intended (large-vocab) scale
        _hsigmoid_path_cache[n_cls] = (
            jnp.asarray(np.asarray(tables, np.int64)),
            jnp.asarray(np.asarray(codes, np.int64)))
    return _hsigmoid_path_cache[n_cls]


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference nn/functional/loss.py:926).

    Default tree: the complete binary tree over num_classes leaves the
    reference builds — node ids trace the path root->leaf of
    (label + num_classes) in the implicit heap layout; code bits are the
    left/right turns. Custom trees pass path_table/path_code
    [N, path_len] (pad with -1).
    """

    if path_table is None or path_code is None:
        # the default-tree paths depend only on (class id, num_classes):
        # build the [num_classes, L] tables ONCE per num_classes and
        # gather rows by label on device (no per-step host sync)
        t_all, c_all = _default_hsigmoid_paths(num_classes)

        def gather_paths(y, tbl):
            yi = y.reshape(-1).astype(jnp.int32)
            return tbl[yi]

        path_table = apply(lambda y: gather_paths(y, t_all), label)
        path_code = apply(lambda y: gather_paths(y, c_all), label)

    def fn(x, tab, code, w, *rest):
        valid = (tab >= 0)
        tab_c = jnp.maximum(tab, 0)
        # scores along the path: [N, L]
        wsel = w[tab_c]                       # [N, L, D]
        s = jnp.einsum("nd,nld->nl", x, wsel)
        if rest:
            s = s + rest[0][tab_c]
        target = code.astype(jnp.float32)
        # BCE-with-logits per path node, masked by validity
        bce = jnp.maximum(s, 0) - s * target + jnp.log1p(
            jnp.exp(-jnp.abs(s)))
        return (bce * valid).sum(axis=1, keepdims=True)

    args = [input, path_table, path_code, weight] + (
        [bias] if bias is not None else [])
    return apply(fn, *args)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-style margin softmax (reference nn/functional/loss.py:1837):
    the target logit's angle theta becomes
    cos(margin1*theta + margin2) - margin3, everything scaled by `scale`.

    Model parallel: pass ``group`` as a mesh AXIS NAME (e.g. "tp") from
    inside a shard_map whose logits are class-sharded — the loss then runs
    the two-allreduce sharded logsumexp with the margin applied only by
    the shard owning the target class (the reference's group-parallel
    c_margin_cross_entropy), and no [N, C] global tensor forms. See
    distributed/fleet/mp_ops.py:parallel_margin_cross_entropy."""
    if isinstance(group, str):
        from paddle_tpu.distributed.fleet.mp_ops import (
            parallel_margin_cross_entropy,
        )

        def sharded(lg, y):
            out = parallel_margin_cross_entropy(
                lg, y, margin1=margin1, margin2=margin2, margin3=margin3,
                scale=scale, axis_name=group, return_softmax=return_softmax)
            if return_softmax:
                nll, sm = out
                return _reduce(nll[:, None], reduction), sm
            return _reduce(out[:, None], reduction)

        return apply(sharded, logits, label)

    def fn(lg, y):
        n, c = lg.shape
        yi = y.reshape(-1).astype(jnp.int32)
        # stay strictly inside arccos' differentiable domain: cos==1.0
        # gives d(arccos)/dx = -inf and one such sample poisons the step
        cos = jnp.clip(lg, -1.0 + 1e-6, 1.0 - 1e-6)
        theta = jnp.arccos(jnp.take_along_axis(cos, yi[:, None], 1))
        target = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(yi, c, dtype=lg.dtype)
        adjusted = cos * (1 - onehot) + target * onehot
        z = adjusted * scale
        logp = jax.nn.log_softmax(z, -1)
        loss = -jnp.take_along_axis(logp, yi[:, None], 1)
        loss_out = _reduce(loss, reduction)
        return (loss_out, jax.nn.softmax(z, -1)) if return_softmax \
            else loss_out

    return apply(fn, logits, label)


# ---- fused LM-head + cross entropy (chunked, logits never materialize)
def _flce_chunk_stats(xs, w, ys):
    """Per-chunk pieces: logsumexp over the vocab + the label logit."""
    logits = jax.lax.dot_general(
        xs, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [c, V] f32
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    lab = jnp.take_along_axis(
        logits, ys[:, None].astype(jnp.int32), axis=1)[:, 0]
    return lse, lab


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flce(x, w, y, valid, chunk):
    """valid: float [n] mask (padding rows = 0); loss = masked mean."""
    n = x.shape[0]
    xs = x.reshape(n // chunk, chunk, x.shape[1])
    ys = y.reshape(n // chunk, chunk)

    def body(_, c):
        lse, lab = _flce_chunk_stats(c[0], w, c[1])
        return None, lse - lab

    _, losses = jax.lax.scan(body, None, (xs, ys))
    # max(1): an all-ignored batch must yield loss 0, not nan
    return (jnp.sum(losses.reshape(-1) * valid)
            / jnp.maximum(jnp.sum(valid), 1.0))


def _flce_fwd(x, w, y, valid, chunk):
    # residuals: only the INPUTS — the whole point is that no [n, V]
    # tensor survives the forward
    return _flce(x, w, y, valid, chunk), (x, w, y, valid)


def _flce_bwd(chunk, res, ct):
    x, w, y, valid = res
    n = x.shape[0]
    xs = x.reshape(n // chunk, chunk, x.shape[1])
    ys = y.reshape(n // chunk, chunk)
    per_tok = (ct / jnp.maximum(jnp.sum(valid), 1.0)) * valid    # [n]
    scales = per_tok.reshape(n // chunk, chunk)

    def body(dw, c):
        xc, yc, sc = c
        logits = jax.lax.dot_general(
            xc, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        p = jax.nn.softmax(logits, axis=-1)
        onehot = jax.nn.one_hot(yc.astype(jnp.int32), w.shape[1],
                                dtype=p.dtype)
        dlogits = (p - onehot) * sc[:, None]         # [c, V]
        dxc = jax.lax.dot_general(
            dlogits, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)
        dw = dw + jax.lax.dot_general(
            xc, dlogits, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dw, dxc

    dw, dx = jax.lax.scan(body, jnp.zeros(w.shape, jnp.float32),
                          (xs, ys, scales))
    return (dx.reshape(x.shape), dw.astype(w.dtype), None, None)


_flce.defvjp(_flce_fwd, _flce_bwd)


def fused_linear_cross_entropy(hidden, weight, label, chunk_size=8192,
                               ignore_index=-100, name=None):
    """LM-head matmul + softmax cross entropy WITHOUT materializing the
    [tokens, vocab] logits: tokens stream through lax.scan in
    `chunk_size` slices and the backward rematerializes each chunk's
    softmax (custom VJP saves only the inputs).

    This is the single-chip counterpart of the tp vocab-parallel
    ParallelCrossEntropy (reference fleet ParallelCrossEntropy /
    incubate fused_linear role): the reference avoids the full-vocab
    tensor by sharding it over mp ranks; on one chip we avoid it by
    chunking time. hidden: [..., H] (flattened to tokens), weight:
    [H, vocab], label: int ids matching hidden's leading dims.
    Labels equal to `ignore_index` (padding tokens, reference
    softmax_with_cross_entropy convention) are excluded from the mean
    and clamped before the vocab gather. Returns the mean loss.
    """
    def fn(h, w, y):
        hf = h.reshape(-1, h.shape[-1])
        yf = y.reshape(-1)
        n = hf.shape[0]
        c = min(chunk_size, n)
        pad = (-n) % c   # pad to a chunk multiple; a divisor fallback
        # would degrade to chunk=1 for prime n (thousands of [1, V] steps)
        valid = (yf != ignore_index).astype(jnp.float32)
        yf = jnp.where(yf == ignore_index, 0, yf)
        if pad:
            hf = jnp.pad(hf, ((0, pad), (0, 0)))
            yf = jnp.pad(yf, (0, pad))
            valid = jnp.pad(valid, (0, pad))
        return _flce(hf, w, yf, valid, c)

    return apply(fn, hidden, weight, label)
