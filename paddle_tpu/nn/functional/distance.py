"""Distance functionals. Reference: python/paddle/nn/functional/distance.py."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def fn(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)
    return apply(fn, x, y)


def pdist(x, p=2.0, name=None):
    def fn(v):
        n = v.shape[0]
        diff = v[:, None, :] - v[None, :, :]
        dm = jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)
        iu = jnp.triu_indices(n, k=1)
        return dm[iu]
    return apply(fn, x)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    def fn(a, b):
        if p == 2.0 and "use_mm" in compute_mode:
            a2 = jnp.sum(a * a, axis=-1, keepdims=True)
            b2 = jnp.sum(b * b, axis=-1, keepdims=True)
            d2 = a2 + jnp.swapaxes(b2, -1, -2) - 2 * (a @ jnp.swapaxes(b, -1, -2))
            return jnp.sqrt(jnp.maximum(d2, 0.0))
        diff = a[..., :, None, :] - b[..., None, :, :]
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)
    return apply(fn, x, y)
