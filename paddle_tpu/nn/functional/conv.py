"""Convolutions. Reference: python/paddle/nn/functional/conv.py.

TPU-first: all convs lower to a single `lax.conv_general_dilated`, which XLA
tiles onto the MXU (the conv is where ResNet's FLOPs live). We keep paddle's
NCHW default at the API level and let XLA's layout assignment pick the
TPU-optimal internal layout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply


def _tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    t = tuple(int(x) for x in v)
    return t * n if len(t) == 1 else t


def _padding(padding, n, data_format):
    """Normalize paddle padding spec -> lax [(lo, hi)] per spatial dim or str."""
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    # nested [[lo,hi],...] possibly including batch/channel dims
    if all(isinstance(p, (list, tuple)) for p in padding):
        pads = [tuple(int(q) for q in p) for p in padding]
        if len(pads) == n:
            return pads
        # strip N, C dims according to data_format
        if data_format.startswith("NC"):
            return pads[2:]
        return pads[1:-1]
    raise ValueError(f"bad padding {padding!r}")


def _dim_numbers(nd, channel_last):
    if nd == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if nd == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, nd, data_format):
    channel_last = not data_format.startswith("NC")
    stride = _tuple(stride, nd)
    dilation = _tuple(dilation, nd)
    pad = _padding(padding, nd, data_format)
    lhs_dn, rhs_dn, out_dn = _dim_numbers(nd, channel_last)

    def fn(v, w, b):
        from paddle_tpu.amp.auto_cast import downcast_inputs
        v, w = downcast_inputs(v, w, opname=f"conv{nd}d")
        # paddle weight layout is [out_c, in_c/groups, *k] == OIHW
        if channel_last:
            perm = tuple(range(2, 2 + nd)) + (1, 0)  # OIHW->HWIO
            w = jnp.transpose(w, perm)
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=(lhs_dn, rhs_dn, out_dn),
            preferred_element_type=None)
        if b is not None:
            shape = [1] * out.ndim
            shape[out_dn.index("C")] = b.shape[0]
            out = out + b.reshape(shape).astype(out.dtype)
        return out
    return apply(fn, x, weight, bias)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, groups,
                    dilation, nd, data_format, output_size=None):
    channel_last = not data_format.startswith("NC")
    stride = _tuple(stride, nd)
    dilation = _tuple(dilation, nd)
    opad = _tuple(output_padding, nd) if output_padding is not None else (0,) * nd
    pad = _padding(padding, nd, data_format)
    lhs_dn, rhs_dn, out_dn = _dim_numbers(nd, channel_last)

    def fn(v, w, b):
        from paddle_tpu.amp.auto_cast import downcast_inputs
        v, w = downcast_inputs(v, w, opname=f"conv{nd}d_transpose")
        # paddle transpose-conv weight layout: [in_c, out_c/groups, *k]
        # grad-of-conv formulation: conv with transposed spatial dilation
        if isinstance(pad, str):
            pads = None
        else:
            pads = pad
        k = w.shape[2:]
        eff_k = tuple(dilation[i] * (k[i] - 1) + 1 for i in range(nd))
        if pads is None:
            lo_hi = [(0, 0)] * nd if pad == "VALID" else [
                ((eff_k[i] - 1) // 2, eff_k[i] // 2) for i in range(nd)]
        else:
            lo_hi = pads
        tpad = [
            (eff_k[i] - 1 - lo_hi[i][0], eff_k[i] - 1 - lo_hi[i][1] + opad[i])
            for i in range(nd)
        ]
        # flip spatial dims, swap I/O: [in, out/g, *k] -> [out, in/g, *k]
        wf = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
        if groups > 1:
            ic = wf.shape[0]
            ocg = wf.shape[1]
            wf = wf.reshape((groups, ic // groups, ocg) + k)
            wf = jnp.swapaxes(wf, 1, 2)
            wf = wf.reshape((groups * ocg, ic // groups) + k)
        else:
            wf = jnp.swapaxes(wf, 0, 1)
        if channel_last:
            perm = tuple(range(2, 2 + nd)) + (1, 0)
            wf = jnp.transpose(wf, perm)
        out = jax.lax.conv_general_dilated(
            v, wf, window_strides=(1,) * nd, padding=tpad,
            lhs_dilation=stride, rhs_dilation=dilation,
            feature_group_count=groups,
            dimension_numbers=(lhs_dn, rhs_dn, out_dn))
        if output_size is not None:
            tgt = _tuple(output_size, nd)
            sl = [slice(None)] * out.ndim
            for i in range(nd):
                ax = (1 + i) if channel_last else (2 + i)
                cur = out.shape[ax]
                if cur > tgt[i]:
                    sl[ax] = slice(0, tgt[i])
            out = out[tuple(sl)]
        if b is not None:
            shape = [1] * out.ndim
            shape[out_dn.index("C")] = b.shape[0]
            out = out + b.reshape(shape).astype(out.dtype)
        return out
    return apply(fn, x, weight, bias)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, 1, df, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, 3, data_format, output_size)
