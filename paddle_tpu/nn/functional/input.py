"""Input encodings. Reference: python/paddle/nn/functional/input.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply


def one_hot(x, num_classes, name=None):
    return apply(lambda v: jax.nn.one_hot(v, num_classes, dtype=jnp.float32), x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Gather rows of `weight`. On TPU this lowers to a dynamic-gather that
    XLA vectorizes; `sparse` is accepted for API parity (gradient is dense —
    the TPU-native equivalent of the reference's selected-rows grad)."""
    def fn(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return out
    return apply(fn, x, weight)


def gather_tree(ids, parents):
    """Beam-search backtrace (reference nn/functional/extension.py:253);
    implementation in paddle_tpu.nn.decode."""
    from paddle_tpu.nn.decode import gather_tree as _gt
    return _gt(ids, parents)
