"""Vision functionals. Reference: python/paddle/nn/functional/vision.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = jnp.transpose(v, (0, 1, 4, 2, 5, 3))
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = jnp.transpose(v, (0, 1, 3, 2, 4, 5))
        return v.reshape(n, h * r, w * r, c // (r * r))
    return apply(fn, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor
    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = jnp.transpose(v, (0, 1, 3, 5, 2, 4))
            return v.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        v = jnp.transpose(v, (0, 1, 3, 2, 4, 5))
        return v.reshape(n, h // r, w // r, c * r * r)
    return apply(fn, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, groups, c // groups, h, w)
            v = jnp.swapaxes(v, 1, 2)
            return v.reshape(n, c, h, w)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, groups, c // groups)
        v = jnp.swapaxes(v, 3, 4)
        return v.reshape(n, h, w, c)
    return apply(fn, x)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def fn(th):
        n, _, h, w = [int(s) for s in out_shape] if len(out_shape) == 4 else (
            int(out_shape[0]), 0, int(out_shape[1]), int(out_shape[2]))
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # [h, w, 3]
        return jnp.einsum("hwk,nck->nhwc", base, th)
    return apply(fn, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    def fn(v, g):
        n, c, h, w = v.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def sample(ix, iy):
            if padding_mode == "border":
                ix = jnp.clip(ix, 0, w - 1)
                iy = jnp.clip(iy, 0, h - 1)
                valid = jnp.ones_like(ix, dtype=bool)
            elif padding_mode == "reflection":
                ix = jnp.abs(jnp.mod(ix, 2 * (w - 1)) - (w - 1)) if w > 1 else ix * 0
                iy = jnp.abs(jnp.mod(iy, 2 * (h - 1)) - (h - 1)) if h > 1 else iy * 0
                valid = jnp.ones_like(ix, dtype=bool)
            else:
                valid = (ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1)
                ix = jnp.clip(ix, 0, w - 1)
                iy = jnp.clip(iy, 0, h - 1)
            vals = v[jnp.arange(n)[:, None, None], :, iy.astype(jnp.int32),
                     ix.astype(jnp.int32)]  # [n, gh, gw, c]
            return jnp.where(valid[..., None], vals, 0.0)

        if mode == "nearest":
            out = sample(jnp.round(fx), jnp.round(fy))
        else:
            x0, y0 = jnp.floor(fx), jnp.floor(fy)
            x1, y1 = x0 + 1, y0 + 1
            wa = (x1 - fx) * (y1 - fy)
            wb = (x1 - fx) * (fy - y0)
            wc = (fx - x0) * (y1 - fy)
            wd = (fx - x0) * (fy - y0)
            out = (sample(x0, y0) * wa[..., None] + sample(x0, y1) * wb[..., None]
                   + sample(x1, y0) * wc[..., None] + sample(x1, y1) * wd[..., None])
        return jnp.transpose(out, (0, 3, 1, 2))
    return apply(fn, x, grid)
