"""Pooling. Reference: python/paddle/nn/functional/pooling.py.

All pools lower to `lax.reduce_window` (XLA fuses these well on TPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import apply
from paddle_tpu.nn.functional.conv import _tuple


def _pool_nd(v, nd, kernel, stride, padding, ceil_mode, kind, exclusive,
             channel_last):
    kernel = _tuple(kernel, nd)
    stride = _tuple(stride if stride is not None else kernel, nd)
    if isinstance(padding, str):
        pad_str = padding.upper()
        pads = None
    else:
        pad_str = None
        p = _tuple(padding, nd) if not (
            isinstance(padding, (list, tuple)) and len(padding) == 2 * nd
        ) else tuple(int(x) for x in padding)
        if len(p) == nd:
            pads = [(p[i], p[i]) for i in range(nd)]
        else:
            pads = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        full_pads = [(0, 0)] + (pads or []) + [(0, 0)] if pads is not None else pad_str
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        full_pads = [(0, 0), (0, 0)] + pads if pads is not None else pad_str
    if ceil_mode and pads is not None:
        # extend hi padding so ceil-div windows fit
        spatial = v.shape[1:-1] if channel_last else v.shape[2:]
        extra = []
        for i in range(nd):
            size = spatial[i] + pads[i][0] + pads[i][1]
            out_ceil = -(-(size - kernel[i]) // stride[i]) + 1
            needed = (out_ceil - 1) * stride[i] + kernel[i] - size
            extra.append(max(0, needed))
        off = 1 if channel_last else 2
        full_pads = list(full_pads)
        for i in range(nd):
            lo, hi = full_pads[off + i]
            full_pads[off + i] = (lo, hi + extra[i])
    if kind == "max":
        init = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min
        return jax.lax.reduce_window(v, init, jax.lax.max, window, strides,
                                     full_pads if pads is not None else pad_str)
    # avg
    ones = jnp.ones_like(v)
    s = jax.lax.reduce_window(v, 0.0 if jnp.issubdtype(v.dtype, jnp.floating) else 0,
                              jax.lax.add, window, strides,
                              full_pads if pads is not None else pad_str)
    if exclusive:
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                    full_pads if pads is not None else pad_str)
        return s / cnt
    return s / float(np.prod(kernel))


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    out = apply(lambda v: _pool_nd(v, 1, kernel_size, stride, padding,
                                   ceil_mode, "max", True, False), x)
    if return_mask:
        idx = _pool_indices(x, 1, kernel_size, stride, padding, ceil_mode, False)
        return out, idx
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    cl = not data_format.startswith("NC")
    out = apply(lambda v: _pool_nd(v, 2, kernel_size, stride, padding,
                                   ceil_mode, "max", True, cl), x)
    if return_mask:
        idx = _pool_indices(x, 2, kernel_size, stride, padding, ceil_mode, cl)
        return out, idx
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    cl = not data_format.startswith("NC")
    out = apply(lambda v: _pool_nd(v, 3, kernel_size, stride, padding,
                                   ceil_mode, "max", True, cl), x)
    if return_mask:
        idx = _pool_indices(x, 3, kernel_size, stride, padding, ceil_mode, cl)
        return out, idx
    return out


def _pool_indices(x, nd, kernel, stride, padding, ceil_mode, channel_last):
    """Argmax indices within flattened spatial dims (paddle return_mask)."""
    from paddle_tpu.nn.functional.common import unfold as _unfold

    def fn(v):
        kernel_t = _tuple(kernel, nd)
        stride_t = _tuple(stride if stride is not None else kernel, nd)
        if nd != 2:
            # generic path via explicit window extraction is only needed for
            # the less common 1d/3d + return_mask combination
            raise NotImplementedError("return_mask only for 2d pools currently")
        n, c, h, w = v.shape if not channel_last else (
            v.shape[0], v.shape[3], v.shape[1], v.shape[2])
        vv = v if not channel_last else jnp.transpose(v, (0, 3, 1, 2))
        p = padding if isinstance(padding, (list, tuple)) else (padding, padding)
        vv_p = jnp.pad(vv, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])],
                       constant_values=-jnp.inf)
        oh = (vv_p.shape[2] - kernel_t[0]) // stride_t[0] + 1
        ow = (vv_p.shape[3] - kernel_t[1]) // stride_t[1] + 1
        patches = []
        coords = []
        for i in range(kernel_t[0]):
            for j in range(kernel_t[1]):
                patches.append(vv_p[:, :, i: i + oh * stride_t[0]: stride_t[0],
                                    j: j + ow * stride_t[1]: stride_t[1]])
                coords.append((i, j))
        stackv = jnp.stack(patches, axis=0)
        arg = jnp.argmax(stackv, axis=0)
        ci = jnp.asarray([c0 for c0, _ in coords])
        cj = jnp.asarray([c1 for _, c1 in coords])
        rows = ci[arg] + jnp.arange(oh)[None, None, :, None] * stride_t[0] - p[0]
        cols = cj[arg] + jnp.arange(ow)[None, None, None, :] * stride_t[1] - p[1]
        return (rows * w + cols).astype(jnp.int32)
    return apply(fn, x)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return apply(lambda v: _pool_nd(v, 1, kernel_size, stride, padding,
                                    ceil_mode, "avg", exclusive, False), x)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    cl = not data_format.startswith("NC")
    def fn(v):
        out = _pool_nd(v, 2, kernel_size, stride, padding, ceil_mode, "avg",
                       exclusive and divisor_override is None, cl)
        if divisor_override is not None:
            k = _tuple(kernel_size, 2)
            out = out * (float(np.prod(k)) / divisor_override)
        return out
    return apply(fn, x)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    cl = not data_format.startswith("NC")
    def fn(v):
        out = _pool_nd(v, 3, kernel_size, stride, padding, ceil_mode, "avg",
                       exclusive and divisor_override is None, cl)
        if divisor_override is not None:
            k = _tuple(kernel_size, 3)
            out = out * (float(np.prod(k)) / divisor_override)
        return out
    return apply(fn, x)


def _adaptive_windows(in_size, out_size):
    starts = [int(np.floor(i * in_size / out_size)) for i in range(out_size)]
    ends = [int(np.ceil((i + 1) * in_size / out_size)) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(v, out_sizes, kind, channel_last, nd):
    spatial_off = 1 if channel_last else 2
    out = v
    for d in range(nd):
        ax = spatial_off + d
        in_size = out.shape[ax]
        osz = out_sizes[d] if out_sizes[d] is not None else in_size
        starts, ends = _adaptive_windows(in_size, osz)
        slabs = []
        for s, e in zip(starts, ends):
            sl = jax.lax.slice_in_dim(out, s, e, axis=ax)
            red = jnp.max(sl, axis=ax, keepdims=True) if kind == "max" else \
                jnp.mean(sl, axis=ax, keepdims=True)
            slabs.append(red)
        out = jnp.concatenate(slabs, axis=ax)
    return out


def adaptive_avg_pool1d(x, output_size, name=None):
    osz = output_size if isinstance(output_size, int) else output_size[0]
    return apply(lambda v: _adaptive_pool(v, [osz], "avg", False, 1), x)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    osz = _tuple(output_size, 2) if not isinstance(output_size, (list, tuple)) \
        else tuple(output_size)
    cl = not data_format.startswith("NC")
    return apply(lambda v: _adaptive_pool(v, list(osz), "avg", cl, 2), x)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    osz = _tuple(output_size, 3) if not isinstance(output_size, (list, tuple)) \
        else tuple(output_size)
    cl = not data_format.startswith("NC")
    return apply(lambda v: _adaptive_pool(v, list(osz), "avg", cl, 3), x)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    osz = output_size if isinstance(output_size, int) else output_size[0]
    out = apply(lambda v: _adaptive_pool(v, [osz], "max", False, 1), x)
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    osz = _tuple(output_size, 2) if not isinstance(output_size, (list, tuple)) \
        else tuple(output_size)
    out = apply(lambda v: _adaptive_pool(v, list(osz), "max", False, 2), x)
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    osz = _tuple(output_size, 3) if not isinstance(output_size, (list, tuple)) \
        else tuple(output_size)
    out = apply(lambda v: _adaptive_pool(v, list(osz), "max", False, 3), x)
    return (out, None) if return_mask else out


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    def fn(v, idx):
        n, c, oh, ow = v.shape
        k = _tuple(kernel_size, 2)
        st = _tuple(stride if stride is not None else kernel_size, 2)
        if output_size is not None:
            H, W = tuple(output_size)[-2:]
        else:
            p = _tuple(padding, 2)
            H = (oh - 1) * st[0] - 2 * p[0] + k[0]
            W = (ow - 1) * st[1] - 2 * p[1] + k[1]
        flat = jnp.zeros((n, c, H * W), v.dtype)
        flat = flat.at[
            jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
            idx.reshape(n, c, -1)
        ].set(v.reshape(n, c, -1))
        return flat.reshape(n, c, H, W)
    return apply(fn, x, indices)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    def fn(v, idx):
        n, c, ol = v.shape
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        st = stride if stride is not None else k
        st = st if isinstance(st, int) else st[0]
        if output_size is not None:
            L = tuple(output_size)[-1]
        else:
            p = padding if isinstance(padding, int) else padding[0]
            L = (ol - 1) * st - 2 * p + k
        flat = jnp.zeros((n, c, L), v.dtype)
        flat = flat.at[
            jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None], idx
        ].set(v)
        return flat
    return apply(fn, x, indices)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    def fn(v, idx):
        n, c, od, oh, ow = v.shape
        k = _tuple(kernel_size, 3)
        st = _tuple(stride if stride is not None else kernel_size, 3)
        if output_size is not None:
            D, H, W = tuple(output_size)[-3:]
        else:
            p = _tuple(padding, 3)
            D = (od - 1) * st[0] - 2 * p[0] + k[0]
            H = (oh - 1) * st[1] - 2 * p[1] + k[1]
            W = (ow - 1) * st[2] - 2 * p[2] + k[2]
        flat = jnp.zeros((n, c, D * H * W), v.dtype)
        flat = flat.at[
            jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
            idx.reshape(n, c, -1)
        ].set(v.reshape(n, c, -1))
        return flat.reshape(n, c, D, H, W)
    return apply(fn, x, indices)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    def fn(v):
        p = float(norm_type)
        vp = jnp.abs(v) ** p
        s = _pool_nd(vp, 2, kernel_size, stride, padding, ceil_mode, "avg",
                     False, not data_format.startswith("NC"))
        k = _tuple(kernel_size, 2)
        return (s * float(np.prod(k))) ** (1.0 / p)
    return apply(fn, x)
